"""Guided decoding through the OpenAI-compatible API.

Constrain generation to a literal choice set or a regex — the engine
compiles the pattern to a token DFA and masks logits on-device inside
its fused decode window (docs/engine.md), so a guided response is
always a complete match.

Run an engine first (CPU works):
    JAX_PLATFORMS=cpu python -m production_stack_tpu.engine.server \
        --model debug-tiny --port 8100

Then: python examples/guided_decoding.py [base_url]
"""

import json
import sys
import urllib.request

BASE = sys.argv[1] if len(sys.argv) > 1 else "http://localhost:8100"


def post(path, payload):
    req = urllib.request.Request(
        BASE + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.load(r)


# 1. choice: the answer is exactly one of the options
out = post("/v1/chat/completions", {
    "model": "debug-tiny",
    "messages": [{"role": "user", "content": "Is the sky blue?"}],
    "max_tokens": 8,
    "guided_choice": ["yes", "no", "unsure"],
})
print("choice:", out["choices"][0]["message"]["content"])

# 2. regex: force a shaped value (full-match semantics; leading ^ /
# trailing $ are accepted and stripped)
out = post("/v1/completions", {
    "model": "debug-tiny",
    "prompt": "order id: ",
    "max_tokens": 24,
    "guided_regex": r"ORD-[0-9]{6}",
})
print("regex:", out["choices"][0]["text"])

# 3. schema-constrained JSON (vLLM guided_json): pass a JSON-schema
# subset and the engine compiles it to canonical JSON output — every
# declared property in order, no stray whitespace, always parseable
out = post("/v1/completions", {
    "model": "debug-tiny",
    "prompt": "reply with a json object: ",
    "max_tokens": 64,
    "guided_json": {"type": "object", "properties": {
        "name": {"type": "string", "pattern": "[a-z]{1,8}"},
        "count": {"type": "integer"},
        "tags": {"type": "array", "items": {"enum": ["a", "b"]},
                 "maxItems": 2},
    }},
})
print("json:", out["choices"][0]["text"])
