#!/usr/bin/env python
"""Files + Batches API walkthrough against the router.

Uploads a JSONL batch input through /v1/files, submits a /v1/batches job
that executes every line through routing, polls to completion, and
downloads the output file. (Reference ships the same walkthrough as
examples/example_file_upload.py + a batch client; unlike the reference's
placeholder batch processor, this stack's batches actually execute.)

Start a stack first, e.g.:

    python -m production_stack_tpu.engine.server --model debug-tiny \
        --port 8100 &
    python -m production_stack_tpu.router.app --port 8000 \
        --service-discovery static \
        --static-backends http://localhost:8100 \
        --static-models debug-tiny \
        --enable-files-api --enable-batch-api &

    python examples/files_and_batches.py --base-url http://localhost:8000
"""

import argparse
import json
import sys
import time
import urllib.request
import uuid


def api(base, path, data=None, headers=None, method=None):
    req = urllib.request.Request(
        base + path, data=data, headers=headers or {},
        method=method or ("POST" if data is not None else "GET"))
    with urllib.request.urlopen(req) as resp:
        return resp.read()


def upload_jsonl(base, lines):
    boundary = uuid.uuid4().hex
    body = b""
    fields = {"purpose": "batch"}
    for name, value in fields.items():
        body += (f"--{boundary}\r\nContent-Disposition: form-data; "
                 f'name="{name}"\r\n\r\n{value}\r\n').encode()
    payload = "\n".join(json.dumps(line) for line in lines)
    body += (f"--{boundary}\r\nContent-Disposition: form-data; "
             f'name="file"; filename="input.jsonl"\r\n'
             f"Content-Type: application/jsonl\r\n\r\n").encode()
    body += payload.encode() + f"\r\n--{boundary}--\r\n".encode()
    raw = api(base, "/v1/files", data=body, headers={
        "Content-Type": f"multipart/form-data; boundary={boundary}"})
    return json.loads(raw)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-url", default="http://localhost:8000")
    ap.add_argument("--model", default="debug-tiny")
    args = ap.parse_args()
    base = args.base_url.rstrip("/")

    print("1) upload batch input via /v1/files")
    lines = [
        {"custom_id": f"req-{i}",
         "method": "POST", "url": "/v1/chat/completions",
         "body": {"model": args.model, "max_tokens": 8,
                  "messages": [{"role": "user",
                                "content": f"Question {i}: say something"}]}}
        for i in range(3)
    ]
    file_obj = upload_jsonl(base, lines)
    print("   uploaded:", file_obj["id"], f"({file_obj['bytes']} bytes)")

    print("2) submit the batch")
    batch = json.loads(api(base, "/v1/batches", data=json.dumps({
        "input_file_id": file_obj["id"],
        "endpoint": "/v1/chat/completions",
        "completion_window": "24h"}).encode(),
        headers={"Content-Type": "application/json"}))
    print("   batch:", batch["id"], batch["status"])

    print("3) poll until it finishes")
    for _ in range(120):
        batch = json.loads(api(base, f"/v1/batches/{batch['id']}"))
        if batch["status"] in ("completed", "failed", "cancelled"):
            break
        time.sleep(1)
    print("   final status:", batch["status"])
    if batch["status"] != "completed":
        sys.exit(1)

    print("4) download results")
    out = api(base, f"/v1/files/{batch['output_file_id']}/content")
    for line in out.decode().strip().splitlines():
        rec = json.loads(line)
        body = rec["response"]["body"]
        text = body["choices"][0]["message"]["content"]
        print(f"   {rec['custom_id']}: {text[:60]!r}")


if __name__ == "__main__":
    main()
