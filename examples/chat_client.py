#!/usr/bin/env python
"""Minimal OpenAI-compatible chat client against the router: one
non-streaming call, one streaming call (SSE), with session affinity via
the x-user-id header (the routing key the benchmark and the reference's
session router use).

    python examples/chat_client.py --base-url http://localhost:8000 \
        --model debug-tiny
"""

import argparse
import json
import urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-url", default="http://localhost:8000")
    ap.add_argument("--model", default="debug-tiny")
    ap.add_argument("--user", default="example-user")
    args = ap.parse_args()
    base = args.base_url.rstrip("/")
    headers = {"Content-Type": "application/json",
               "x-user-id": args.user}

    body = {"model": args.model, "max_tokens": 24, "temperature": 0.7,
            "messages": [{"role": "user",
                          "content": "Tell me something interesting."}]}

    print("-- non-streaming --")
    req = urllib.request.Request(base + "/v1/chat/completions",
                                 data=json.dumps(body).encode(),
                                 headers=headers)
    with urllib.request.urlopen(req) as resp:
        data = json.loads(resp.read())
    print(data["choices"][0]["message"]["content"])
    print("usage:", data["usage"])

    print("-- streaming --")
    req = urllib.request.Request(
        base + "/v1/chat/completions",
        data=json.dumps({**body, "stream": True}).encode(),
        headers=headers)
    with urllib.request.urlopen(req) as resp:
        for raw in resp:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            chunk = json.loads(payload)
            for choice in chunk.get("choices", []):
                delta = (choice.get("delta") or {}).get("content")
                if delta:
                    print(delta, end="", flush=True)
    print()


if __name__ == "__main__":
    main()
