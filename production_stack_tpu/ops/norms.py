"""Normalization ops.

TPU notes: RMSNorm reduces in float32 regardless of activation dtype
(bf16 accumulation loses ~3 decimal digits and visibly degrades long
sequences), then casts back so the surrounding matmuls stay bf16 on the MXU.
XLA fuses the whole thing into the neighboring matmul's epilogue/prologue;
no Pallas kernel is needed for this op.
"""

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """y = x / rms(x) * weight, computed in fp32, returned in x.dtype."""
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(orig_dtype)
