"""Normalization ops.

TPU notes: RMSNorm reduces in float32 regardless of activation dtype
(bf16 accumulation loses ~3 decimal digits and visibly degrades long
sequences), then casts back so the surrounding matmuls stay bf16 on the MXU.
XLA fuses the whole thing into the neighboring matmul's epilogue/prologue;
no Pallas kernel is needed for this op.
"""

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5,
             offset: float = 0.0) -> jnp.ndarray:
    """y = x / rms(x) * (weight + offset), fp32 compute, x.dtype out.

    offset=1.0 gives Gemma's convention (checkpoints store w with an
    implicit unit gain); 0.0 is the Llama/Mistral/Qwen baseline.
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32) + offset
    return (normed * w).astype(orig_dtype)
