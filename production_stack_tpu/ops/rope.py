"""Rotary position embeddings (non-interleaved, Llama/NeoX layout).

The sin/cos table is precomputed once on host as fp32 and closed over by
the jitted step functions — under jit it becomes a baked-in constant in HBM
and the per-step work is a fused elementwise multiply on the VPU. Positions
are dynamic (per-sequence offsets in continuous batching), so the table is
gathered by position ids rather than sliced statically.
"""

from functools import lru_cache

import jax.numpy as jnp


@lru_cache(maxsize=32)
def rope_table(max_positions: int, head_dim: int, theta: float = 10000.0,
               scaling: tuple = None):
    """Precompute (cos, sin), each [max_positions, head_dim // 2], fp32.

    Cached per (max_positions, head_dim, theta, scaling). Positions >=
    max_positions would be clamp-gathered under jit (silently wrong
    logits) — callers with a cache longer than the model's
    max_position_embeddings must pass a table sized to the cache length
    (the engine does; see engine/runner.py).

    scaling is a hashable spec from ModelConfig.rope_scaling_:
    ("linear", factor) divides every frequency by `factor`;
    ("llama3", factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings) applies Llama-3.1's
    wavelength-dependent warp (long wavelengths scaled by 1/factor,
    short kept, smooth ramp between — same formula as HF
    transformers' _compute_llama3_parameters).

    Computed and CACHED in numpy: the lru_cache makes traced values
    poisonous — a first call under a jit trace (any rope=None path)
    would cache tracers that escape into later traces, and even
    jnp.asarray of a constant is a traced op. Host arrays are safe to
    cache and close over from anywhere; jnp converts them at use (XLA
    bakes them into executables as constants either way).
    """
    import numpy as np
    inv_freq = 1.0 / (
        theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim)
    )
    if scaling is not None:
        kind = scaling[0]
        if kind == "linear":
            inv_freq = inv_freq / float(scaling[1])
        elif kind == "llama3":
            factor, low_f, high_f, orig = (float(scaling[1]),
                                           float(scaling[2]),
                                           float(scaling[3]),
                                           float(scaling[4]))
            low_wavelen = orig / low_f
            high_wavelen = orig / high_f
            wavelen = 2.0 * np.pi / inv_freq
            smooth = (orig / wavelen - low_f) / (high_f - low_f)
            warped = ((1.0 - smooth) * inv_freq / factor
                      + smooth * inv_freq)
            inv_freq = np.where(
                wavelen > low_wavelen, inv_freq / factor,
                np.where(wavelen < high_wavelen, inv_freq, warped))
        else:
            raise ValueError(
                f"unsupported rope scaling {kind!r} (supported: "
                f"linear, llama3)")
    pos = np.arange(max_positions, dtype=np.float32)
    angles = np.outer(pos, inv_freq)  # [P, D/2]
    return np.cos(angles), np.sin(angles)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, cos: jnp.ndarray,
               sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate x [..., T, H, D] by per-token positions [..., T].

    Non-interleaved ("rotate half") convention: the head dim is split into
    two contiguous halves, matching HF Llama's ``rotate_half``.
    """
    # tables may arrive as host numpy (rope_table caches numpy — see its
    # docstring); numpy can't be indexed by a traced positions array
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    c = cos[positions].astype(jnp.float32)[..., None, :]  # [..., T, 1, D/2]
    s = sin[positions].astype(jnp.float32)[..., None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
