"""Rotary position embeddings (non-interleaved, Llama/NeoX layout).

The sin/cos table is precomputed once on host as fp32 and closed over by
the jitted step functions — under jit it becomes a baked-in constant in HBM
and the per-step work is a fused elementwise multiply on the VPU. Positions
are dynamic (per-sequence offsets in continuous batching), so the table is
gathered by position ids rather than sliced statically.
"""

from functools import lru_cache

import jax.numpy as jnp


@lru_cache(maxsize=32)
def rope_table(max_positions: int, head_dim: int, theta: float = 10000.0):
    """Precompute (cos, sin), each [max_positions, head_dim // 2], fp32.

    Cached per (max_positions, head_dim, theta). Positions >= max_positions
    would be clamp-gathered under jit (silently wrong logits) — callers with
    a cache longer than the model's max_position_embeddings must pass a
    table sized to the cache length (the engine does; see engine/runner.py).
    """
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    pos = jnp.arange(max_positions, dtype=jnp.float32)
    angles = jnp.outer(pos, inv_freq)  # [P, D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, cos: jnp.ndarray,
               sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate x [..., T, H, D] by per-token positions [..., T].

    Non-interleaved ("rotate half") convention: the head dim is split into
    two contiguous halves, matching HF Llama's ``rotate_half``.
    """
    c = cos[positions].astype(jnp.float32)[..., None, :]  # [..., T, 1, D/2]
    s = sin[positions].astype(jnp.float32)[..., None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
