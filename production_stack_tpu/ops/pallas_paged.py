"""Paged flash attention for TPU: block-table-aware online-softmax GQA.

ONE kernel for both serving phases:

- **prefill** chunks (T up to the chunk bucket) — replaces the
  gather-view + flash path (ops/pallas_attention.py), deleting the
  per-layer gathered K/V copy AND that kernel's head-major relayout
  copy;
- **decode / speculative windows** (T = 1 or draft+1, inside the
  lax.scan of engine/runner.py) — replaces the gather-view + dense jnp
  path, which materialized a [B, kv, Hkv, D] copy of the live cache
  per layer per step: ~3x the minimal KV HBM traffic, the dominant
  cost of long-context decode.

K/V pool blocks ``[N, Hkv, Bs, D]`` (models/kv.py, head-major: the
per-(block, head) panel is a contiguous [Bs, D] tile) are streamed
straight from HBM through *scalar-prefetched* block tables: the grid's
innermost dimension walks a row's blocks, the BlockSpec index map reads
``tables[b, j]`` to point the next DMA at the right block, and each KV
byte a row needs is read exactly once. Per-row causal skipping falls
out of the index map: blocks past a row's last query position clamp to
an already-resident index (Pallas elides the re-fetch) and their grid
steps are `pl.when`-masked away, so decode cost scales with each row's
LIVE prefix, not the kv bucket.

Grid ``(B, Hkv, NQ, nb)``; per step the q block [BQ, G, D] for one kv
head and one pool block's [Bs, D] K and V panels live in VMEM. Online
(max, sum, acc) statistics persist in VMEM scratch across the
``nb``-axis (sequential "arbitrary" dimension), initialized at j == 0
and emitted at j == nb - 1 — the classic flash accumulation, with GQA
rows flattened as t*G + g so K/V are never broadcast to query heads.

Sharded serving: under a tp-only mesh the kernel runs inside
``shard_map`` over the head axis (q heads and pool heads both shard by
tp; tables/starts replicate) — embarrassingly parallel, no collectives.
Meshes that shard the pool's block axis (dp > 1) keep the jnp gather
path, whose collectives XLA inserts.

The reference repo ships no kernels (attention lives in the external
vLLM engine, SURVEY.md §2.9); this is TPU-first work. Numerics are
pinned against the dense jnp path in tests/test_pallas_paged.py via
interpret mode on CPU.
"""

import functools
import os
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from production_stack_tpu.ops.pallas_attention import VMEM_LIMIT_BYTES

_NEG_INF = -1e30

# VMEM ceiling for the per-grid-step working set (q + acc + scores,
# fp32): conservative slice of the ~16 MB/core budget, leaving room
# for Pallas' double-buffered K/V panels and the output block.
_VMEM_WORK_BYTES = 8 * 1024 * 1024


def paged_viable(T: int, groups: int, head_dim: int,
                 block_size: int) -> bool:
    """Can a [T*G, D] q panel + accumulator + one [T*G, Bs] score
    block hold in VMEM? (Decode windows always can; only very long
    prefill chunks on wide-GQA models cannot.)"""
    rows = max(T * groups, 8)
    work = rows * head_dim * 4 * 2 + rows * block_size * 4 * 2 \
        + rows * head_dim * 2
    return work <= _VMEM_WORK_BYTES


def _paged_kernel(tabs_ref, starts_ref, q_ref, k_ref, v_ref, *refs,
                  block_q: int, groups: int,
                  block_size: int, nb: int, scale: float,
                  quant: bool = False, window: int = 0,
                  softcap: float = 0.0):
    """One (batch row, kv head, q block, pool block) grid step.

    tabs_ref   (SMEM) [B, MB]      block tables
    starts_ref (SMEM) [B]          absolute position of q[:, 0]
    q_ref   [1, BQ, 1, G, D]       this kv-head's query block
    k_ref   [1, 1, Bs, D]          pool block tabs[b, min(j, jmax)]
    v_ref   [1, 1, Bs, D]
    refs    (quant only: ks/vs dequant scales [1, 1, Bs] fp32,)
            out [1, BQ, 1, G, D], scratch m/l/acc (online softmax
            state across j)
    """
    if quant:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    out_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    qi = pl.program_id(2)
    j = pl.program_id(3)
    rows = block_q * groups
    D = q_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    start = starts_ref[b]
    # last block this q block can see (same formula as the index map's
    # clamp): beyond it the DMA re-targets a resident block and the
    # step is skipped entirely
    max_pos = start + qi * block_q + (block_q - 1)
    jmax = jax.lax.div(max_pos, block_size)
    # sliding window: blocks wholly before the EARLIEST query row's
    # window are skipped the same way (window == 0 means full causal)
    jmin = (jax.lax.div(
        jnp.maximum(start + qi * block_q - (window - 1), 0), block_size)
        if window else 0)

    @pl.when((j <= jmax) & (j >= jmin))
    def _compute():
        # absolute position of each q row (rows ordered t*G + g)
        row_ids = jax.lax.broadcasted_iota(
            jnp.int32, (rows, 1), 0) // groups
        q_pos = start + qi * block_q + row_ids                # [rows, 1]
        q = q_ref[0].reshape(rows, D).astype(jnp.float32) * scale
        k_blk = k_ref[0, 0].astype(jnp.float32)               # [Bs, D]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        if quant:
            # int8 pool: dequantize the panel in VMEM (per-token scale)
            k_blk = k_blk * ks_ref[0, 0][:, None]
            v_blk = v_blk * vs_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [rows, Bs]
        if softcap:
            # Gemma-2 tanh cap on RAW scores, before -inf masking
            s = softcap * jnp.tanh(s / softcap)
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        live = k_pos <= q_pos
        if window:
            live = live & (k_pos > q_pos - window)
        s = jnp.where(live, s, _NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1,
                                            keepdims=True))
        p = jnp.exp(s - m_new)                                # [rows, Bs]
        correction = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * correction + jnp.sum(
            p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [rows, D]

    @pl.when(j == nb - 1)
    def _emit():
        # fully-masked (padding/parked) rows have l == 0; keep finite
        out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        out_ref[0] = out.reshape(block_q, 1, groups, D).astype(
            out_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("nb", "block_q", "interpret",
                                    "window", "scale", "softcap"))
def paged_attention(q, k_pool, v_pool, tables, starts, *, nb: int,
                    block_q: int = 0, interpret: bool = False,
                    k_scales=None, v_scales=None, window: int = 0,
                    scale: float = None, softcap: float = 0.0):
    """Causal GQA over paged K/V, positions contiguous per row.

    q [B, T, H, D]; k/v pool [N, Hkv, Bs, D]; tables [B, MB] int32;
    starts [B] = absolute position of q[:, 0] (every call site —
    prefill chunks, decode windows, speculative windows — queries
    contiguous positions start..start+T-1). A query at position p
    attends virtual positions <= p through its table row; the pool
    must already contain the chunk's own K/V (write-then-attend, as
    in models/kv.py). Rows parked at start >= MB*Bs return garbage
    the caller discards, exactly like the jnp path.

    k_scales/v_scales [N, Hkv, Bs] fp32 activate the int8-pool mode:
    panels stream from HBM as int8 (half the bytes) and dequantize in
    VMEM next to the dot.
    """
    B, T, H, D = q.shape
    Hkv, Bs = k_pool.shape[1], k_pool.shape[2]
    G = H // Hkv
    MB = tables.shape[1]
    if scale is None:
        scale = D ** -0.5
    quant = k_scales is not None
    if not block_q:
        # whole chunk per q block while VMEM allows: K/V are streamed
        # once per (batch, head) instead of once per q block
        block_q = T
        while block_q > 16 and not paged_viable(block_q, G, D, Bs):
            block_q //= 2
    block_q = min(block_q, T)
    pad_t = (-T) % block_q
    if pad_t:
        q = jnp.pad(q, ((0, 0), (0, pad_t), (0, 0), (0, 0)))
    Tp = T + pad_t
    nq = Tp // block_q

    # q as [B, Tp, Hkv, G, D]: BlockSpec carves per-(b, kv-head) panels
    # out of the native layout, (G, D) minor
    q5 = q.reshape(B, Tp, Hkv, G, D)

    def kv_index(b, h, qi, j, tabs, sts):
        # clamp out-of-range blocks (past-causal above, before the
        # sliding window below) onto the nearest visible one: the index
        # stops changing, so Pallas skips the DMA re-fetch and pl.when
        # skips the compute
        jmax = jax.lax.div(sts[b] + qi * block_q + (block_q - 1),
                           Bs)
        jj = jnp.minimum(jnp.minimum(j, jmax),
                         jnp.int32(MB - 1))
        if window:
            jmin = jax.lax.div(
                jnp.maximum(sts[b] + qi * block_q - (window - 1), 0), Bs)
            jj = jnp.maximum(jj, jnp.minimum(jmin, jnp.int32(MB - 1)))
        jj = jnp.maximum(jj, 0)
        return (tabs[b, jj], h, 0, 0)

    def scale_index(b, h, qi, j, tabs, sts):
        blk, hh, _, _ = kv_index(b, h, qi, j, tabs, sts)
        return (blk, hh, 0)

    grid = (B, Hkv, nq, nb)
    kernel = functools.partial(
        _paged_kernel, block_q=block_q, groups=G, block_size=Bs,
        nb=nb, scale=scale, quant=quant, window=window,
        softcap=softcap)
    rows = block_q * G
    in_specs = [
        pl.BlockSpec((1, block_q, 1, G, D),
                     lambda b, h, qi, j, tabs, sts:
                     (b, qi, h, 0, 0)),
        pl.BlockSpec((1, 1, Bs, D), kv_index),
        pl.BlockSpec((1, 1, Bs, D), kv_index),
    ]
    operands = [q5, k_pool, v_pool]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, Bs), scale_index)] * 2
        operands += [k_scales, v_scales]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, block_q, 1, G, D),
                                   lambda b, h, qi, j, tabs, sts:
                                   (b, qi, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Tp, Hkv, G, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
            # see pallas_attention.VMEM_LIMIT_BYTES for the rationale
            vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )(jnp.asarray(tables, jnp.int32), jnp.asarray(starts, jnp.int32),
      *operands)

    return out.reshape(B, Tp, H, D)[:, :T]


# ---------------------------------------------------------------------
# decode-specialized kernel: all kv heads + several pool blocks per
# grid step.
#
# The general kernel's grid is (B, Hkv, NQ, nb) with ONE 64-token block
# per step — for decode (T = 1) each step is a [G, D] x [D, Bs] dot,
# so small that fixed per-grid-step cost (DMA issue, program dispatch)
# dominates: at batch 32, kv 768, 22 layers that is ~34k grid steps per
# decode step and the measured device time is ~3x the HBM floor. Here
# the grid is (B, ceil(nb / R)): each step fetches one [Hkv, Bs, D]
# K and V panel per sub-block (all kv heads ride one DMA — they are
# contiguous in the pool's [N, Hkv, Bs, D] layout) and statically
# unrolls Hkv x R small dots, cutting grid steps by Hkv*R (16x for
# TinyLlama geometry) while reading exactly the same KV bytes.
# ---------------------------------------------------------------------

# decode/spec windows have T <= spec+1 << this; prefill chunks go to
# the general kernel
DECODE_T_MAX = 8
# KV pool blocks fetched+processed per decode-kernel grid step. More
# blocks per step = fewer grid steps (less per-step overhead) but a
# bigger VMEM working set (R panels of [Hkv, Bs, D] K and V each).
# Env-tunable for hardware sweeps: PSTPU_DECODE_BLOCKS_PER_STEP.


def _env_blocks_per_step(default: int = 4) -> int:
    """Validated at import: a malformed or non-positive value must not
    crash module import or reach the decode-kernel grid math — warn and
    serve on the default instead."""
    raw = os.environ.get("PSTPU_DECODE_BLOCKS_PER_STEP")
    if raw is None:
        return default
    try:
        value = int(raw)
    except (TypeError, ValueError):
        warnings.warn(
            f"PSTPU_DECODE_BLOCKS_PER_STEP={raw!r} is not an integer; "
            f"falling back to {default}", RuntimeWarning)
        return default
    if value < 1:
        warnings.warn(
            f"PSTPU_DECODE_BLOCKS_PER_STEP={value} must be >= 1; "
            f"falling back to {default}", RuntimeWarning)
        return default
    return value


_BLOCKS_PER_STEP = _env_blocks_per_step()


def _paged_decode_kernel(tabs_ref, starts_ref, q_ref, *refs, T: int,
                         heads_kv: int, groups: int, block_size: int,
                         ngrp: int, R: int, scale: float,
                         quant: bool = False, window: int = 0,
                         softcap: float = 0.0):
    """One (batch row, block group) grid step.

    tabs_ref   (SMEM) [B, MB]     block tables
    starts_ref (SMEM) [B]         absolute position of q[:, 0]
    q_ref   [1, Hkv, T*G, D]      all heads' queries (rows = t*G + g)
    refs    R k panels [1, Hkv, Bs, D], R v panels, (quant only:
            R ks + R vs dequant scales [1, Hkv, Bs] fp32,) out
            [1, Hkv, T*G, D], scratch m/l [Hkv*T*G, 1], acc
            [Hkv*T*G, D] — online softmax state across the group axis.
    """
    k_refs = refs[:R]
    v_refs = refs[R:2 * R]
    refs = refs[2 * R:]
    if quant:
        ks_refs = refs[:R]
        vs_refs = refs[R:2 * R]
        refs = refs[2 * R:]
    out_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    jg = pl.program_id(1)
    rows = T * groups
    D = q_ref.shape[-1]

    @pl.when(jg == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    start = starts_ref[b]
    jmax = jax.lax.div(start + (T - 1), block_size)
    # sliding window: whole groups before the earliest query's window
    # are skipped (window == 0 means full causal)
    jmin = (jax.lax.div(jnp.maximum(start - (window - 1), 0), block_size)
            if window else 0)

    @pl.when((jg * R <= jmax) & (jg * R + (R - 1) >= jmin))
    def _compute():
        # row r (within a head) queries position start + r // G
        row_pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (rows, 1), 0) // groups
        for h in range(heads_kv):
            q = q_ref[0, h].astype(jnp.float32) * scale      # [rows, D]
            sl = slice(h * rows, (h + 1) * rows)
            m_prev = m_ref[sl]
            l_prev = l_ref[sl]
            acc_prev = acc_ref[sl]
            for i in range(R):
                j = jg * R + i
                k_blk = k_refs[i][0, h].astype(jnp.float32)  # [Bs, D]
                v_blk = v_refs[i][0, h].astype(jnp.float32)
                if quant:
                    k_blk = k_blk * ks_refs[i][0, h][:, None]
                    v_blk = v_blk * vs_refs[i][0, h][:, None]
                s = jax.lax.dot_general(
                    q, k_blk, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)      # [rows, Bs]
                if softcap:
                    s = softcap * jnp.tanh(s / softcap)
                k_pos = j * block_size + jax.lax.broadcasted_iota(
                    jnp.int32, (1, block_size), 1)
                live = (k_pos <= row_pos) & (j <= jmax)
                if window:
                    live = live & (k_pos > row_pos - window)
                s = jnp.where(live, s, _NEG_INF)
                m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1,
                                                    keepdims=True))
                p = jnp.exp(s - m_new)
                corr = jnp.exp(m_prev - m_new)
                l_prev = l_prev * corr + jnp.sum(p, axis=-1,
                                                 keepdims=True)
                acc_prev = acc_prev * corr + jax.lax.dot_general(
                    p, v_blk, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)      # [rows, D]
                m_prev = m_new
            m_ref[sl] = m_prev
            l_ref[sl] = l_prev
            acc_ref[sl] = acc_prev

    @pl.when(jg == ngrp - 1)
    def _emit():
        out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        out_ref[0] = out.reshape(heads_kv, rows, D).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("nb", "interpret",
                                             "window", "scale",
                                             "softcap"))
def paged_decode_attention(q, k_pool, v_pool, tables, starts, *,
                           nb: int, interpret: bool = False,
                           k_scales=None, v_scales=None,
                           window: int = 0,
                           scale: float = None, softcap: float = 0.0):
    """paged_attention specialized for short query windows (T <=
    DECODE_T_MAX): same contract, same result, far fewer grid steps.

    q [B, T, H, D]; k/v pool [N, Hkv, Bs, D]; tables [B, MB] int32;
    starts [B]. See paged_attention for semantics. k_scales/v_scales
    [N, Hkv, Bs] fp32 activate the int8-pool mode (panels stream as
    int8, dequantized in VMEM — half the KV bytes of the bf16 pool).
    """
    B, T, H, D = q.shape
    Hkv, Bs = k_pool.shape[1], k_pool.shape[2]
    G = H // Hkv
    MB = tables.shape[1]
    if scale is None:
        scale = D ** -0.5
    quant = k_scales is not None
    R = min(_BLOCKS_PER_STEP, nb)
    ngrp = -(-nb // R)
    rows = T * G

    # [B, T, Hkv, G, D] -> [B, Hkv, T*G, D]: rows ordered t*G + g per
    # head, matching the kernel's row_pos formula
    qh = q.reshape(B, T, Hkv, G, D).transpose(0, 2, 1, 3, 4)
    qh = qh.reshape(B, Hkv, rows, D)

    def kv_index(i):
        def index(b, jg, tabs, sts):
            jmax = jax.lax.div(sts[b] + (T - 1), jnp.int32(Bs))
            jj = jnp.minimum(jnp.minimum(jg * R + i, jmax),
                             jnp.int32(MB - 1))
            if window:
                jmin = jax.lax.div(
                    jnp.maximum(sts[b] - (window - 1), 0), jnp.int32(Bs))
                jj = jnp.maximum(jj, jnp.minimum(jmin,
                                                 jnp.int32(MB - 1)))
            return (tabs[b, jnp.maximum(jj, 0)], 0, 0, 0)
        return index

    kernel = functools.partial(
        _paged_decode_kernel, T=T, heads_kv=Hkv, groups=G,
        block_size=Bs, ngrp=ngrp, R=R, scale=scale, quant=quant,
        window=window, softcap=softcap)
    kv_specs = [pl.BlockSpec((1, Hkv, Bs, D), kv_index(i))
                for i in range(R)]
    in_specs = [
        pl.BlockSpec((1, Hkv, rows, D),
                     lambda b, jg, tabs, sts: (b, 0, 0, 0)),
        *kv_specs, *kv_specs,
    ]
    operands = [qh, *([k_pool] * R), *([v_pool] * R)]
    if quant:
        def sc_index(i):
            ki = kv_index(i)

            def index(b, jg, tabs, sts):
                blk, _, _, _ = ki(b, jg, tabs, sts)
                return (blk, 0, 0)
            return index

        sc_specs = [pl.BlockSpec((1, Hkv, Bs), sc_index(i))
                    for i in range(R)]
        in_specs += [*sc_specs, *sc_specs]
        operands += [*([k_scales] * R), *([v_scales] * R)]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, ngrp),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, Hkv, rows, D),
                                   lambda b, jg, tabs, sts:
                                   (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((Hkv * rows, 1), jnp.float32),
                pltpu.VMEM((Hkv * rows, 1), jnp.float32),
                pltpu.VMEM((Hkv * rows, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rows, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )(jnp.asarray(tables, jnp.int32), jnp.asarray(starts, jnp.int32),
      qh, *operands[1:])

    # [B, Hkv, T*G, D] -> [B, T, H, D]
    out = out.reshape(B, Hkv, T, G, D).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, T, H, D)


def paged_attention_sharded(q, k_pool, v_pool, tables, starts, mesh, *,
                            nb: int, interpret: bool = False,
                            k_scales=None, v_scales=None,
                            window: int = 0,
                            scale: float = None, softcap: float = 0.0):
    """paged_attention under a tp-only mesh: shard_map over the head
    axis (q heads and pool kv heads both shard by tp, tables/starts
    replicated) — shard-local, no collectives. Caller guarantees the
    mesh has no other axis of size > 1 (mesh_tp_only). Short windows
    (decode/spec) take the wide decode kernel, like the unsharded
    path. int8 pools pass their [N, Hkv, Bs] scales, sharded over the
    same head axis."""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    base = (paged_decode_attention if q.shape[1] <= DECODE_T_MAX
            else paged_attention)
    in_specs = (P(None, None, "tp", None),
                P(None, "tp", None, None),
                P(None, "tp", None, None), P(), P())
    args = (q, k_pool, v_pool, tables, starts)
    if k_scales is not None:
        def fn(qq, kk, vv, tt, ss, ks, vs):
            return base(qq, kk, vv, tt, ss, nb=nb, interpret=interpret,
                        k_scales=ks, v_scales=vs, window=window,
                        scale=scale, softcap=softcap)
        in_specs = in_specs + (P(None, "tp", None), P(None, "tp", None))
        args = args + (k_scales, v_scales)
    else:
        fn = functools.partial(base, nb=nb, interpret=interpret,
                               window=window, scale=scale,
                               softcap=softcap)
    return shard_map(
        fn, mesh=mesh,
        in_specs=in_specs,
        out_specs=P(None, None, "tp", None),
        check_rep=False)(*args)


def mesh_tp_only(mesh) -> bool:
    """True when every mesh axis except 'tp' has size 1 — the
    configuration where the kernel can run shard-local per head."""
    return mesh is not None and all(
        size == 1 for name, size in mesh.shape.items() if name != "tp")
