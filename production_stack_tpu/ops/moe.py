"""Mixture-of-experts MLP: top-k routing with static-shape dispatch.

TPU-first design (the reference stack has no model code — MoE models are
strings passed to ``vllm serve``, reference:
helm/templates/deployment-vllm-multi.yaml:57-64; expert parallelism is a
``--enable-expert-parallel``-style engine passthrough, SURVEY.md §2.9):

- Routing, dispatch and combine are all static-shape jnp — no
  data-dependent shapes, so the whole block lives inside the engine's
  jitted prefill/decode executables and XLA can schedule it.
- Two dispatch strategies, chosen at trace time by token count N:

  **Exact (small N, the decode path).** Every expert runs over all N
  tokens and results are combined with the routing weights ([N, E],
  zero for unselected experts). At decode sizes (N = batch ≤ ~tens)
  this is bandwidth-equivalent to "perfect" dispatch — with N*k
  assignments over E experts nearly every expert is touched anyway, so
  the step still streams every expert's weights once — and it is exact:
  no token is ever dropped.

  **Capacity dispatch (large N, the prefill path).** The GShard/Switch
  pattern reshaped for scatter/gather instead of [N, E, C] one-hots:
  each (token, choice) assignment gets a rank within its expert (an
  O(N*k*E) cumsum — integers, negligible next to the FFN matmuls) and
  is scattered into a per-expert [capacity, h] buffer; experts run as
  one batched [E, C, h] matmul; results gather back and combine.
  Assignments ranked past capacity are dropped — their combine weight
  contributes nothing and the token rides the residual stream, the
  standard capacity-factor tradeoff. ``capacity_factor`` ≥ E/k makes
  dropping impossible (capacity = N) at dense-compute cost. Padding
  tokens (``valid`` mask: the engine's full-batch prefill pads idle
  rows and short chunks) are excluded from ranking entirely, so they
  can never crowd real tokens out of an expert.

- Expert weights are stacked [E, h, i] / [E, i, h]: under expert
  parallelism parallel/sharding.py shards the leading E axis over the
  mesh's 'ep' axis (and the i axis over 'tp'), so each device's FFN
  matmul touches only its resident experts and XLA inserts the
  dispatch/combine collectives from the sharding annotations.

Routing follows Mixtral semantics: fp32 softmax over all experts, then
top-k, then renormalize the selected probabilities to sum to 1.
"""

import functools
from typing import Callable

import jax
import jax.numpy as jnp


def capacity_for(n_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-expert token capacity: factor × the perfectly-balanced load,
    8-aligned (TPU sublane), clamped to [8, n_tokens]."""
    balanced = n_tokens * top_k / num_experts
    cap = int(-(-capacity_factor * balanced // 8) * 8)
    return max(8, min(cap, n_tokens))


def route(x: jnp.ndarray, router_w: jnp.ndarray, top_k: int,
          renormalize: bool = True):
    """Top-k routing. x [N, h], router_w [h, E] ->
    (weights [N, k] fp32, expert ids [N, k] int32). renormalize=True is
    Mixtral semantics (selected weights re-sum to 1); False keeps the
    raw softmax probabilities (Qwen2-MoE's norm_topk_prob=False)."""
    logits = jnp.einsum("nh,he->ne", x, router_w,
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, top_k)
    if renormalize:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    return top_p, top_i.astype(jnp.int32)


def _quant():
    """models/quant.py, imported lazily: models/ imports ops/, so a
    top-level import here would cycle. By the first moe_mlp trace both
    packages are fully initialized."""
    from production_stack_tpu.models import quant
    return quant


def _wshape(w) -> tuple:
    """Shape of a raw or int8-quantized weight."""
    return (w["w8"] if _quant().is_quantized(w) else w).shape


def _edot(xb: jnp.ndarray, w) -> jnp.ndarray:
    """einsum('ec?,e?o->eco') with weight-only int8 dequant applied in
    the epilogue (per-expert, per-output-channel scale)."""
    if _quant().is_quantized(w):
        y = jnp.einsum("eci,eio->eco", xb, w["w8"].astype(xb.dtype))
        return y * w["scale"].astype(xb.dtype)[:, None, :]
    return jnp.einsum("eci,eio->eco", xb, w)


def _expert_ffn(xb: jnp.ndarray, gate, up, down,
                act: Callable) -> jnp.ndarray:
    """Batched per-expert FFN. xb [E, C, h] -> [E, C, h]."""
    g = _edot(xb, gate)
    u = _edot(xb, up)
    return _edot(act(g) * u, down)


def _moe_exact(x, top_p, top_i, gate, up, down, act):
    """All experts over all tokens, combined by routing weight."""
    N = x.shape[0]
    E = _wshape(gate)[0]
    # combine [N, E]: routing weight where selected, else 0
    combine = jnp.zeros((N, E), jnp.float32)
    combine = combine.at[
        jnp.arange(N)[:, None], top_i].set(top_p)
    xb = jnp.broadcast_to(x, (E,) + x.shape)            # [E, N, h]
    y_e = _expert_ffn(xb, gate, up, down, act)          # [E, N, h]
    return jnp.einsum("enh,ne->nh", y_e,
                      combine.astype(x.dtype))


def _moe_dispatch(x, top_p, top_i, gate, up, down, act, capacity,
                  valid=None):
    """Scatter-based capacity dispatch (see module docstring)."""
    N, h = x.shape
    E = _wshape(gate)[0]
    k = top_i.shape[1]

    flat_e = top_i.reshape(-1)                          # [N*k] token-major
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)
    if valid is not None:
        # padding tokens must not compete for expert capacity: drop
        # their assignments from the rank count and the buffers
        valid_rep = jnp.repeat(valid.astype(jnp.int32), k)
        onehot = onehot * valid_rep[:, None]
    # rank of each assignment within its expert (how many earlier
    # assignments chose the same expert)
    prior = jnp.cumsum(onehot, axis=0) - onehot
    rank = jnp.take_along_axis(prior, flat_e[:, None], axis=1)[:, 0]
    keep = rank < capacity
    if valid is not None:
        keep = keep & (valid_rep > 0)
    trash = E * capacity                                # overflow row
    dest = jnp.where(keep, flat_e * capacity + rank, trash)

    x_rep = jnp.repeat(x, k, axis=0)                    # [N*k, h]
    buf = jnp.zeros((E * capacity + 1, h), x.dtype).at[dest].set(x_rep)
    xb = buf[:-1].reshape(E, capacity, h)
    y_e = _expert_ffn(xb, gate, up, down, act)          # [E, C, h]
    y_flat = jnp.concatenate(
        [y_e.reshape(E * capacity, h), jnp.zeros((1, h), y_e.dtype)])
    y_rep = y_flat[dest]                                # dropped -> zeros
    w = top_p.reshape(-1)[:, None].astype(x.dtype)
    return jnp.sum((y_rep * w).reshape(N, k, h), axis=1)


def moe_mlp(x: jnp.ndarray, router_w: jnp.ndarray, gate: jnp.ndarray,
            up: jnp.ndarray, down: jnp.ndarray, *, top_k: int,
            capacity_factor: float = 2.0, dense_threshold: int = 64,
            act: Callable = jax.nn.silu, valid=None,
            exact=None, renormalize: bool = True) -> jnp.ndarray:
    """MoE feed-forward. x [N, h]; router_w [h, E]; gate/up [E, h, i];
    down [E, i, h]. Returns [N, h] in x.dtype.

    valid [N] bool marks real tokens: padding rows contribute nothing
    and never consume expert capacity. exact=True forces the all-expert
    path regardless of N (the decode path passes it — decode must never
    drop a token); exact=None auto-selects it for N ≤ dense_threshold
    or whenever capacity covers every possible assignment.
    """
    N = x.shape[0]
    E = _wshape(gate)[0]
    top_p, top_i = route(x, router_w, top_k, renormalize=renormalize)
    if valid is not None:
        top_p = top_p * valid.astype(top_p.dtype)[:, None]
    capacity = capacity_for(N, E, top_k, capacity_factor)
    if exact is None:
        exact = N <= dense_threshold or capacity >= N
    if exact:
        return _moe_exact(x, top_p, top_i, gate, up, down, act)
    return _moe_dispatch(x, top_p, top_i, gate, up, down, act, capacity,
                         valid=valid)
