"""Pallas flash attention for TPU: blockwise online-softmax GQA.

Replaces the jnp cache attention (ops/attention.py) on the *prefill* hot
path, where materializing [B, Hkv, G, T, S] fp32 scores costs O(T*S) HBM
traffic per layer: this kernel streams K/V blocks through VMEM, keeps
online (max, sum, acc) statistics, and never materializes the score
matrix. Decode (T == 1) stays on the jnp path — its score matrix is a
[B, Hkv, G, 1, kv] sliver that XLA already fuses well, and the fused
multi-step decode executable (engine/runner.py) cannot host a per-step
pallas_call more cheaply than the einsum it replaces.

Kernel layout (one q block per grid step, K/V streamed in an inner loop):
- grid (B, Hkv, Tq_blocks); per step the q block [BQ, G, D] and this
  kv-head's full K/V [S, D] live in VMEM. Queries are sliced through
  BlockSpec index maps on the native [B, T, H, D] layout; K/V are
  relayouted to head-major [B, Hkv, S, D] outside the kernel so a
  per-head panel's minor dims are (S, D) — the shape Mosaic's
  last-two-dims tiling rule can block. flash_viable() bounds S*D so
  both K and V fit the ~16 MB VMEM budget; larger caches fall back to
  the jnp path.
- inner lax.fori_loop walks K/V in BK-sized blocks with the classic
  flash update; the loop's upper bound is data-dependent on the block's
  max query position, so fully-masked (future) K blocks are skipped —
  causal work scales with the live prefix, not S. BK is shrunk (halved)
  until it divides S: every block read is in bounds, no clamped-slice
  mislabeling on ragged tails.
- GQA: the q block keeps its [BQ, G, D] shape and flattens to rows
  t*G + g inside VMEM, so a row's position is row // G and K/V are
  never replicated to H query heads.

Sharded serving note: the kernel is only used on unsharded (single-chip)
executables — pallas_call has no GSPMD partitioning rule, so tp/dp
meshes keep the jnp einsum path, which XLA partitions with the usual
collectives (engine/runner.py gates this via models/llama.py forward's
``use_flash``).

The reference repo ships no kernels (attention lives in the external
vLLM engine, SURVEY.md §2.9); this is TPU-first work. Numerics are
pinned against the dense jnp path in tests/test_pallas_attention.py,
which runs the same kernel in interpret mode on CPU.
"""

import functools
import os
from contextlib import contextmanager

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# VMEM budget for the per-grid-step K + V panels ([S, D] each, bf16):
# stay well under the ~16 MB/core so q/acc/scratch fit too.
_VMEM_PANEL_BYTES = 4 * 1024 * 1024

# Per-kernel scoped-VMEM budget (shared by pallas_paged.py): XLA may
# place a chunk-sized kernel OUTPUT on the scoped-VMEM stack (a batch-8
# 512-chunk bf16 output is ~17 MB), and the default 16 MiB budget then
# fails the compile even though the kernel's own working set is small.
# v5e/v5p cores carry 128 MiB VMEM — raise the budget so chunk-sized
# outputs may live on-chip; outputs too big for it simply land in HBM.
VMEM_LIMIT_BYTES = 100 * 1024 * 1024

# runtime gate: PSTPU_FLASH=1/0 forces; "auto" (default) enables the
# compiled kernel on TPU and leaves CPU/other backends on the jnp path
# (interpret mode is for tests, far too slow for serving).
_override = None


def set_flash_enabled(value) -> None:
    """Force-enable/disable (True/False) or restore auto (None). Used by
    the runner to fall back if the kernel fails to compile on a backend."""
    global _override
    _override = value


def flash_enabled() -> bool:
    if _force_jnp_depth:
        return False
    if _override is not None:
        return _override
    env = os.environ.get("PSTPU_FLASH", "auto").lower()
    if env in ("1", "true", "on"):
        return True
    if env in ("0", "false", "off"):
        return False
    return jax.default_backend() == "tpu"


# scoped override: the runner retries a SINGLE failed executable on the
# jnp path without disabling the kernel for every other (shape, bucket)
# combination — compilation failures are per-geometry (e.g. a VMEM
# budget miss at one chunk size), not per-backend
_force_jnp_depth = 0


@contextmanager
def force_jnp():
    """Scoped flash_enabled() == False, for per-executable fallback."""
    global _force_jnp_depth
    _force_jnp_depth += 1
    try:
        yield
    finally:
        _force_jnp_depth -= 1


def flash_viable(S: int, D: int, itemsize: int = 2) -> bool:
    """Can this kv-length/head-dim keep a K and a V panel in VMEM?"""
    return S * D * itemsize <= _VMEM_PANEL_BYTES


def needs_interpret() -> bool:
    """Interpret everywhere but real TPU (kernel targets TPU tiling)."""
    return jax.default_backend() != "tpu"


def _flash_kernel(starts_ref, q_ref, k_ref, v_ref, out_ref, *,
                  block_q: int, block_k: int, groups: int, scale: float):
    """One (batch, kv-head, q-block) grid step.

    q_ref   [1, BQ, 1, G, D]  queries for this kv-head's G query heads
    k_ref   [1, 1, S, D]      this kv-head's full key cache (head-major)
    v_ref   [1, 1, S, D]
    starts_ref (SMEM) [B]     per-batch-row position of q row t=0
    out_ref [1, BQ, 1, G, D]
    """
    b = pl.program_id(0)
    qi = pl.program_id(2)
    S = k_ref.shape[2]
    rows = block_q * groups
    D = q_ref.shape[-1]

    start = starts_ref[b]
    # absolute position of each q row (rows ordered t*G + g): row // G
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // groups
    q_pos = start + qi * block_q + row_ids                    # [rows, 1]

    q = q_ref[0].reshape(rows, D).astype(jnp.float32) * scale

    # causal bound: K blocks fully beyond this q block's last position
    # contribute nothing — skip them (dynamic fori_loop upper bound).
    # block_k divides S (wrapper guarantees), so every read is in bounds.
    max_pos = start + qi * block_q + (block_q - 1)
    n_blocks = jnp.minimum(
        jax.lax.div(max_pos, block_k) + 1, S // block_k)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # [rows, BK]
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                                 # [rows, BK]
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [rows, D]
        return m_new, l_new, acc_new

    m0 = jnp.full((rows, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows, 1), jnp.float32)
    acc0 = jnp.zeros((rows, D), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    # fully-masked (padding) rows have l == 0; keep them finite
    out = acc / jnp.maximum(l, 1e-30)
    out_ref[0] = out.reshape(block_q, 1, groups, D).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret"))
def flash_attention_with_cache(q, k_cache, v_cache, starts, *,
                               block_q: int = 128, block_k: int = 512,
                               interpret: bool = False):
    """Drop-in for ops/attention.attention_with_cache on contiguous
    positions. q [B,T,H,D]; k/v [B,S,Hkv,D]; starts [B] = absolute
    position of q[:, 0]. Query token at position p attends cache slots
    s <= p (the cache already contains the chunk's own K/V). Rows whose
    position exceeds S-1 are padding and return garbage, as in the jnp
    path.
    """
    B, T, H, D = q.shape
    S, Hkv = k_cache.shape[1], k_cache.shape[2]
    G = H // Hkv
    scale = D ** -0.5

    block_q = min(block_q, T)
    # BK must divide S so the last block read stays in bounds (a clamped
    # dynamic slice would silently re-read earlier keys under later
    # position labels). kv buckets are 512-multiples or max_model_len;
    # halving terminates quickly for any S.
    block_k = min(block_k, S)
    while S % block_k:
        block_k //= 2
    # pad T to a block multiple; padded rows mask to zero and are sliced
    pad_t = (-T) % block_q
    if pad_t:
        q = jnp.pad(q, ((0, 0), (0, pad_t), (0, 0), (0, 0)))
    Tp = T + pad_t

    # view q as [B, Tp, Hkv, G, D]: its BlockSpec carves per-(b, kv-head)
    # panels straight out of the native layout (full G and D in the
    # minor dims keeps Mosaic's last-two-dims tiling rule satisfied).
    q5 = q.reshape(B, Tp, Hkv, G, D)
    # K/V go in head-major [B, Hkv, S, D]: a per-head panel then has
    # (S, D) as its last two dims (S a multiple of 8, D native), which
    # Mosaic can tile — carving 1 of Hkv out of [B, S, Hkv, D] cannot
    # be. The swap is a real full-cache copy (the scatter output is
    # also carried as cache state, so it cannot fuse away): ~2*B*Hkv*
    # S*D bf16 of extra HBM traffic per layer per chunk, well under 1%
    # of the chunk's FFN matmul time at flash-viable sizes.
    k_hm = jnp.swapaxes(k_cache, 1, 2)
    v_hm = jnp.swapaxes(v_cache, 1, 2)

    grid = (B, Hkv, Tp // block_q)
    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, groups=G, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, 1, G, D),
                         lambda b, h, i: (b, i, h, 0, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, 1, G, D),
                               lambda b, h, i: (b, i, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Tp, Hkv, G, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )(jnp.asarray(starts, jnp.int32), q5, k_hm, v_hm)

    return out.reshape(B, Tp, H, D)[:, :T]
