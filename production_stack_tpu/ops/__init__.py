from production_stack_tpu.ops.norms import rms_norm
from production_stack_tpu.ops.rope import apply_rope, rope_table
from production_stack_tpu.ops.attention import attention_with_cache, causal_attention

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_table",
    "attention_with_cache",
    "causal_attention",
]
