"""Attention ops (grouped-query, causal, cache-aware).

TPU design notes:
- GQA is computed with *grouped einsums* — q is viewed as
  [B, T, Hkv, G, D] so K/V are never materialized at H query heads,
  saving HBM bandwidth (the usual TPU bottleneck).
- Softmax statistics are fp32; matmuls stay bf16 for the MXU.
- All shapes are static under jit: the serving path attends over the full
  preallocated cache [B, S, Hkv, D] with a position mask rather than
  dynamically slicing to the live length (dynamic shapes would defeat XLA
  tiling). A Pallas flash/chunked variant lives in ops/pallas_attention.py
  for long-context; these jnp versions are the reference semantics.

Reference behavior lives inside the external vLLM engine (reference repo
ships no kernels; see SURVEY.md §2.9) — this module is new TPU-first work.
"""

from typing import Optional

import jax.numpy as jnp

_NEG_INF = -1e30


def _grouped_scores(q: jnp.ndarray, k: jnp.ndarray, scale: float) -> jnp.ndarray:
    """q [B,T,Hkv,G,D] x k [B,S,Hkv,D] -> fp32 scores [B,Hkv,G,T,S]."""
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", q, k, preferred_element_type=jnp.float32
    )
    return scores * scale


def _grouped_out(probs: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """probs [B,Hkv,G,T,S] x v [B,S,Hkv,D] -> [B,T,Hkv,G,D] in v.dtype."""
    return jnp.einsum("bkgts,bskd->btkgd", probs.astype(v.dtype), v)


def _softcap(scores: jnp.ndarray, cap: Optional[float]) -> jnp.ndarray:
    """Gemma-2 logit softcapping: s -> cap * tanh(s / cap). Applied to
    RAW scores, before any -inf masking (capping a masked score would
    resurrect it at -cap)."""
    if cap is None:
        return scores
    return cap * jnp.tanh(scores / cap)


def causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    sliding_window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
) -> jnp.ndarray:
    """Full-sequence causal GQA. q [B,T,H,D]; k,v [B,T,Hkv,D] -> [B,T,H,D].

    Used by the training step and by single-shot (non-incremental) forward.
    Optional segment_ids [B,T] confine attention within packed segments.
    sliding_window W (Mistral/Gemma-2 local layers) further confines a
    query at t to keys in (t - W, t]. logit_softcap applies Gemma-2's
    tanh cap to the raw scores.
    """
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    if scale is None:
        scale = D ** -0.5
    q5 = q.reshape(B, T, Hkv, G, D)
    scores = _softcap(_grouped_scores(q5, k, scale),
                      logit_softcap)  # [B,Hkv,G,T,S] fp32
    t = jnp.arange(T)
    mask = t[:, None] >= t[None, :]  # [T,S] causal
    if sliding_window is not None:
        mask = mask & (t[None, :] > t[:, None] - sliding_window)
    if segment_ids is not None:
        same = segment_ids[:, :, None] == segment_ids[:, None, :]  # [B,T,S]
        mask = mask[None] & same
        mask = mask[:, None, None]  # [B,1,1,T,S]
    else:
        mask = mask[None, None, None]  # [1,1,1,T,S]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = _grouped_out(probs, v)
    return out.reshape(B, T, H, D)


def attention_with_cache(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    q_positions: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
) -> jnp.ndarray:
    """Incremental GQA over a preallocated per-slot cache.

    q           [B,T,H,D]   — the new chunk (T=1 for decode, >1 for prefill)
    k_cache     [B,S,Hkv,D] — cache ALREADY containing the new chunk's K
    v_cache     [B,S,Hkv,D]
    q_positions [B,T]       — absolute position of each query token

    Query token at position p attends to cache slots s <= p (and
    s > p - sliding_window when windowed). Padding query rows
    (q_positions < 0) produce garbage rows the caller discards.
    """
    B, T, H, D = q.shape
    S = k_cache.shape[1]
    Hkv = k_cache.shape[2]
    G = H // Hkv
    if scale is None:
        scale = D ** -0.5
    q5 = q.reshape(B, T, Hkv, G, D)
    scores = _softcap(_grouped_scores(q5, k_cache, scale),
                      logit_softcap)  # [B,Hkv,G,T,S] fp32
    s_idx = jnp.arange(S)
    mask = s_idx[None, None, :] <= q_positions[:, :, None]  # [B,T,S]
    if sliding_window is not None:
        mask = mask & (s_idx[None, None, :]
                       > q_positions[:, :, None] - sliding_window)
    scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = _grouped_out(probs, v_cache)
    return out.reshape(B, T, H, D)
