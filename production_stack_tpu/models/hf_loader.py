"""Load HuggingFace Llama-family checkpoints into the stacked-params layout.

Accepts either a state-dict-like mapping (name -> numpy/torch tensor) or a
checkpoint directory (safetensors preferred, torch .bin fallback). Torch is
used only as a host-side file reader — nothing torch touches the device.

HF stores projections as [out, in]; we store [in, out] (x @ W), so every
projection is transposed on load, and per-layer tensors are stacked along
the leading layer axis to match models/llama.py's scan layout.
"""

import glob
import json
import os
from typing import Any, Dict, Mapping

import numpy as np

from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

_LAYER_MAP = {
    # our-name: (hf-suffix, transpose)
    "attn_norm": ("input_layernorm.weight", False),
    "q": ("self_attn.q_proj.weight", True),
    "k": ("self_attn.k_proj.weight", True),
    "v": ("self_attn.v_proj.weight", True),
    "o": ("self_attn.o_proj.weight", True),
    "mlp_norm": ("post_attention_layernorm.weight", False),
    "gate": ("mlp.gate_proj.weight", True),
    "up": ("mlp.up_proj.weight", True),
    "down": ("mlp.down_proj.weight", True),
}


def _to_numpy(t: Any) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    # torch tensor (possibly bf16, which numpy can't represent) — go via fp32
    return t.detach().to(dtype=__import__("torch").float32).cpu().numpy()


def params_from_state_dict(cfg: ModelConfig, sd: Mapping[str, Any]) -> Dict:
    """Build the stacked-params pytree from an HF LlamaForCausalLM state dict."""
    import jax.numpy as jnp

    def get(name: str, bare: bool = False) -> np.ndarray:
        return _to_numpy(_lookup(sd, name, bare=bare))

    def cast(x: np.ndarray, transpose: bool) -> Any:
        if transpose:
            x = x.T
        return jnp.asarray(x, dtype=cfg.dtype)

    layer_map = dict(_LAYER_MAP)
    if cfg.sandwich_norms:
        # Gemma-2 norm naming: post_attention_layernorm is the SANDWICH
        # post-attn norm (not the MLP pre-norm as in Llama), the MLP
        # pre-norm is pre_feedforward_layernorm, and there is a
        # post_feedforward_layernorm too
        layer_map["mlp_norm"] = ("pre_feedforward_layernorm.weight",
                                 False)
        layer_map["post_attn_norm"] = (
            "post_attention_layernorm.weight", False)
        layer_map["post_mlp_norm"] = (
            "post_feedforward_layernorm.weight", False)
    if cfg.attention_bias:
        # Qwen2: q/k/v projection biases ([out] vectors; no transpose)
        layer_map.update({
            "q_bias": ("self_attn.q_proj.bias", False),
            "k_bias": ("self_attn.k_proj.bias", False),
            "v_bias": ("self_attn.v_proj.bias", False),
        })
    if cfg.num_experts:
        # Mixtral block_sparse_moe replaces the dense MLP (stacked along
        # a leading expert axis; w1=gate, w3=up, w2=down)
        for name in ("gate", "up", "down"):
            del layer_map[name]
    layers: Dict[str, Any] = {}
    for ours, (suffix, transpose) in layer_map.items():
        stacked = np.stack(
            [get(f"layers.{i}.{suffix}") for i in range(cfg.num_layers)])
        if transpose:
            stacked = np.swapaxes(stacked, -1, -2)
        layers[ours] = jnp.asarray(stacked, dtype=cfg.dtype)
    if cfg.num_experts:
        # Mixtral: block_sparse_moe.{gate,experts.N.w1/w3/w2};
        # Qwen2-MoE: mlp.{gate,experts.N.gate_proj/up_proj/down_proj}
        # + an always-on shared expert
        qwen_moe = cfg.moe_naming == "qwen2"
        prefix = "mlp" if qwen_moe else "block_sparse_moe"
        moe_map = ({"gate": "gate_proj", "up": "up_proj",
                    "down": "down_proj"} if qwen_moe
                   else {"gate": "w1", "up": "w3", "down": "w2"})
        for ours, hf in moe_map.items():
            stacked = np.stack([
                np.stack([
                    get(f"layers.{i}.{prefix}.experts.{e}.{hf}.weight").T
                    for e in range(cfg.num_experts)])
                for i in range(cfg.num_layers)])     # [L, E, in, out]
            layers[ours] = jnp.asarray(stacked, dtype=cfg.dtype)
        router = np.stack(
            [get(f"layers.{i}.{prefix}.gate.weight").T
             for i in range(cfg.num_layers)])        # [L, h, E]
        layers["router"] = jnp.asarray(router, dtype=cfg.dtype)
        if qwen_moe and cfg.shared_expert_size:
            for ours, hf in (("s_gate", "gate_proj"), ("s_up", "up_proj"),
                             ("s_down", "down_proj")):
                stacked = np.stack([
                    get(f"layers.{i}.mlp.shared_expert.{hf}.weight").T
                    for i in range(cfg.num_layers)])
                layers[ours] = jnp.asarray(stacked, dtype=cfg.dtype)
            sg = np.stack(
                [get(f"layers.{i}.mlp.shared_expert_gate.weight").T
                 for i in range(cfg.num_layers)])    # [L, h, 1]
            layers["s_gate_w"] = jnp.asarray(sg, dtype=cfg.dtype)

    params = {
        "embed": cast(get("embed_tokens.weight"), False),
        "layers": layers,
        "final_norm": cast(get("norm.weight"), False),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = cast(get("lm_head.weight", bare=True), True)
    return params


def _lookup(sd: Mapping[str, Any], name: str, bare: bool = False) -> Any:
    candidates = [name] if bare else []
    candidates += [f"model.{name}", name]
    for c in candidates:
        if c in sd:
            return sd[c]
    raise KeyError(f"missing weight {name!r}")


def read_state_dict(path: str) -> Dict[str, Any]:
    """Raw tensors from an HF checkpoint dir (safetensors or .bin)."""
    st_files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    sd: Dict[str, Any] = {}
    if st_files:
        from safetensors.numpy import load_file
        for f in st_files:
            sd.update(load_file(f))
    else:
        import torch
        for f in sorted(glob.glob(os.path.join(path, "*.bin"))):
            sd.update(torch.load(f, map_location="cpu", weights_only=True))
    if not sd:
        raise FileNotFoundError(f"no weights (*.safetensors|*.bin) in {path}")
    logger.info("loaded %d tensors from %s", len(sd), path)
    return sd


def load_checkpoint(cfg: ModelConfig, path: str) -> Dict:
    """Load params from an HF checkpoint directory on disk."""
    return params_from_state_dict(cfg, read_state_dict(path))
