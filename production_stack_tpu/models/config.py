"""Model configuration for the Llama decoder family.

One config dataclass covers Llama-2/3, TinyLlama, Mistral and friends —
they differ only in dimensions, GQA ratio, rope theta and vocab. The
reference stack treats models as opaque strings passed to `vllm serve`
(reference: helm/templates/deployment-vllm-multi.yaml:57-64); here model
architecture is first-class so the engine can build/shard/jit it.
"""

import dataclasses
import json
import os
from typing import Any, Dict, Optional

import jax.numpy as jnp


def _rope_scaling_spec(rs: Optional[dict]) -> Optional[tuple]:
    """HF config.json rope_scaling dict -> the hashable spec
    ops/rope.rope_table takes. Unsupported kinds raise (serving with
    the wrong frequencies would be silently wrong logits)."""
    if not rs:
        return None
    kind = rs.get("rope_type") or rs.get("type")
    if kind in ("default", None):
        return None
    if kind == "linear":
        return ("linear", float(rs["factor"]))
    if kind == "llama3":
        return ("llama3", float(rs["factor"]),
                float(rs.get("low_freq_factor", 1.0)),
                float(rs.get("high_freq_factor", 4.0)),
                float(rs.get("original_max_position_embeddings", 8192)))
    raise ValueError(
        f"unsupported rope_scaling type {kind!r} (supported: linear, "
        f"llama3)")


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str = "debug-llama"
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_layers: int = 22
    num_heads: int = 32
    num_kv_heads: int = 4
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    # family variations beyond the Llama/Mistral baseline:
    # sliding-window attention (Mistral v0.1/0.2, Gemma-2 local
    # layers): each query attends only the last `sliding_window`
    # positions. None = full causal.
    sliding_window: Optional[int] = None
    # Gemma-2: every second layer (even indices) uses the sliding
    # window, odd layers are global. False = sliding_window (if any)
    # applies to every layer (Mistral).
    alternating_sliding: bool = False
    # Gemma-2 softcaps: s -> cap * tanh(s / cap) on attention scores
    # and final logits (None = off)
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    # Gemma-2 attention scale: 1/sqrt(query_pre_attn_scalar) instead
    # of 1/sqrt(head_dim) (None = head_dim)
    query_pre_attn_scalar: Optional[float] = None
    # Gemma-2 sandwich norms: post-attention and post-feedforward
    # RMSNorms in ADDITION to the usual pre-norms
    sandwich_norms: bool = False
    # RoPE frequency scaling as a hashable spec (ops/rope.py):
    # ("linear", factor) or ("llama3", factor, low_freq_factor,
    # high_freq_factor, original_max_position_embeddings). None = none.
    # Llama-3.1/3.2 checkpoints REQUIRE the llama3 warp.
    rope_scaling: Optional[tuple] = None
    attention_bias: bool = False    # Qwen2: biases on q/k/v projections
    activation: str = "silu"        # "silu" | "gelu_tanh" (Gemma GeGLU)
    rms_norm_offset: bool = False   # Gemma: y *= (1 + w), not w
    embed_scale: bool = False       # Gemma: embeddings *= sqrt(hidden)
    # MoE (Mixtral / Qwen2-MoE): 0 experts = dense MLP. capacity_factor
    # tunes the prefill dispatch's drop tradeoff (ops/moe.py); decode is
    # exact. Mixtral renormalizes the top-k weights (norm_topk_prob) and
    # has no shared expert; Qwen2-MoE keeps raw softmax weights, uses a
    # narrower per-expert FFN (moe_intermediate_size), and adds an
    # always-on shared expert with a sigmoid gate.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 2.0
    norm_topk_prob: bool = True
    moe_intermediate_size: Optional[int] = None   # default: intermediate
    shared_expert_size: int = 0                   # 0 = no shared expert
    moe_naming: str = "mixtral"   # HF weight naming: "mixtral" | "qwen2" 
    dtype: Any = jnp.bfloat16

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def num_params(self) -> int:
        h, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        hd = self.head_dim_
        E = self.num_experts
        if E:
            mi = self.moe_intermediate_size or i
            mlp = 3 * h * mi * E + h * E
            if self.shared_expert_size:
                mlp += 3 * h * self.shared_expert_size + h
        else:
            mlp = 3 * h * i
        per_layer = (
            h * (self.num_heads * hd)            # q
            + 2 * h * (self.num_kv_heads * hd)   # k, v
            + (self.num_heads * hd) * h          # o
            + mlp                                # experts (+ router) or dense
            + 2 * h                              # norms
        )
        emb = v * h * (1 if self.tie_word_embeddings else 2)
        return self.num_layers * per_layer + emb + h

    @staticmethod
    def from_hf_config(cfg: Dict[str, Any], name: str = "",
                       dtype: Any = jnp.bfloat16) -> "ModelConfig":
        """Map a HuggingFace config dict onto ModelConfig.

        Families: Llama-2/3, TinyLlama, Mistral (the baseline), Qwen2
        (adds q/k/v biases), Gemma (GeGLU via gelu, scaled embeddings,
        unit-offset RMSNorm, tied embeddings).
        """
        archs = cfg.get("architectures") or []
        arch = archs[0] if archs else ""
        model_type = cfg.get("model_type", "")
        # EXACT family matching: substring checks would silently accept
        # e.g. Gemma2ForCausalLM (softcapping, extra norms) or
        # Qwen2MoeForCausalLM as their simpler cousins and serve garbage
        is_qwen2 = model_type == "qwen2" or arch == "Qwen2ForCausalLM"
        is_gemma = model_type == "gemma" or arch == "GemmaForCausalLM"
        is_gemma2 = (model_type == "gemma2"
                     or arch == "Gemma2ForCausalLM")
        is_mixtral = (model_type == "mixtral"
                      or arch == "MixtralForCausalLM")
        is_qwen2_moe = (model_type == "qwen2_moe"
                        or arch == "Qwen2MoeForCausalLM")
        is_llama_like = (model_type in ("llama", "mistral") or arch in
                         ("LlamaForCausalLM", "MistralForCausalLM"))
        if not (is_qwen2 or is_gemma or is_gemma2 or is_mixtral
                or is_qwen2_moe or is_llama_like) and (model_type or arch):
            raise ValueError(
                f"unsupported model family (model_type={model_type!r}, "
                f"architecture={arch!r}); supported: llama, mistral, "
                f"qwen2, gemma, gemma2, mixtral, qwen2_moe")
        if is_qwen2_moe:
            if (cfg.get("decoder_sparse_step", 1) != 1
                    or cfg.get("mlp_only_layers")):
                raise ValueError(
                    "qwen2_moe with dense interleaving "
                    "(decoder_sparse_step != 1 or mlp_only_layers) is "
                    "not supported: every layer must be sparse")
        gemmaish = is_gemma or is_gemma2
        hidden_act = cfg.get("hidden_act") or cfg.get(
            "hidden_activation") or ("gelu_tanh" if gemmaish else "silu")
        return ModelConfig(
            name=name or cfg.get("_name_or_path", "hf-model"),
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            num_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim"),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position_embeddings=cfg.get("max_position_embeddings", 4096),
            # Mistral v0.1/0.2 ship sliding_window in config.json; null
            # (v0.3+) and absent both mean full causal. Mixtral configs
            # carry the field but HF/vLLM ignore it for that family.
            sliding_window=(cfg.get("sliding_window")
                            if (is_llama_like or is_gemma2) else None),
            alternating_sliding=is_gemma2,
            attn_logit_softcap=(cfg.get("attn_logit_softcapping")
                                if is_gemma2 else None),
            final_logit_softcap=(cfg.get("final_logit_softcapping")
                                 if is_gemma2 else None),
            query_pre_attn_scalar=(cfg.get("query_pre_attn_scalar")
                                   if is_gemma2 else None),
            sandwich_norms=is_gemma2,
            rope_scaling=_rope_scaling_spec(cfg.get("rope_scaling")),
            tie_word_embeddings=cfg.get("tie_word_embeddings", gemmaish),
            attention_bias=cfg.get("attention_bias",
                                   is_qwen2 or is_qwen2_moe),
            activation="gelu_tanh" if "gelu" in hidden_act else "silu",
            rms_norm_offset=gemmaish,
            embed_scale=gemmaish,
            num_experts=(cfg.get("num_local_experts", 0) if is_mixtral
                         else cfg.get("num_experts", 0) if is_qwen2_moe
                         else 0),
            num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
            # HF Qwen2MoeConfig defaults norm_topk_prob to FALSE — a
            # missing key must not flip routing to Mixtral semantics
            norm_topk_prob=cfg.get("norm_topk_prob", False)
            if is_qwen2_moe else True,
            moe_intermediate_size=cfg.get("moe_intermediate_size")
            if is_qwen2_moe else None,
            shared_expert_size=cfg.get("shared_expert_intermediate_size",
                                       0) if is_qwen2_moe else 0,
            moe_naming="qwen2" if is_qwen2_moe else "mixtral",
            dtype=dtype,
        )

    @staticmethod
    def from_json(path: str, dtype: Any = jnp.bfloat16) -> "ModelConfig":
        with open(os.path.join(path, "config.json") if os.path.isdir(path) else path) as f:
            return ModelConfig.from_hf_config(json.load(f), name=path, dtype=dtype)


# ---------------------------------------------------------------------------
# Presets. Dimensions are the publicly documented architecture shapes.
# ---------------------------------------------------------------------------

PRESETS: Dict[str, ModelConfig] = {
    # Tiny model for CPU tests — intentionally small, MXU-aligned dims.
    "debug-tiny": ModelConfig(
        name="debug-tiny", vocab_size=512, hidden_size=128,
        intermediate_size=384, num_layers=2, num_heads=4, num_kv_heads=2,
        max_position_embeddings=512,
    ),
    "tinyllama-1.1b": ModelConfig(
        name="tinyllama-1.1b", vocab_size=32000, hidden_size=2048,
        intermediate_size=5632, num_layers=22, num_heads=32, num_kv_heads=4,
        max_position_embeddings=2048,
    ),
    "llama-3-8b": ModelConfig(
        name="llama-3-8b", vocab_size=128256, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        rope_theta=500000.0, max_position_embeddings=8192,
    ),
    # Llama-3.1: same shapes as 3.0 but 128k context via the llama3
    # rope warp (ops/rope.py)
    "llama-3.1-8b": ModelConfig(
        name="llama-3.1-8b", vocab_size=128256, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32,
        num_kv_heads=8, rope_theta=500000.0,
        max_position_embeddings=131072,
        rope_scaling=("llama3", 8.0, 1.0, 4.0, 8192),
    ),
    "llama-3-70b": ModelConfig(
        name="llama-3-70b", vocab_size=128256, hidden_size=8192,
        intermediate_size=28672, num_layers=80, num_heads=64, num_kv_heads=8,
        rope_theta=500000.0, max_position_embeddings=8192,
    ),
    # Llama-3.2 small models: 3.1-style rope warp (factor 32), tied
    # embeddings
    "llama-3.2-1b": ModelConfig(
        name="llama-3.2-1b", vocab_size=128256, hidden_size=2048,
        intermediate_size=8192, num_layers=16, num_heads=32,
        num_kv_heads=8, head_dim=64, rope_theta=500000.0,
        max_position_embeddings=131072, tie_word_embeddings=True,
        rope_scaling=("llama3", 32.0, 1.0, 4.0, 8192),
    ),
    "llama-3.2-3b": ModelConfig(
        name="llama-3.2-3b", vocab_size=128256, hidden_size=3072,
        intermediate_size=8192, num_layers=28, num_heads=24,
        num_kv_heads=8, head_dim=128, rope_theta=500000.0,
        max_position_embeddings=131072, tie_word_embeddings=True,
        rope_scaling=("llama3", 32.0, 1.0, 4.0, 8192),
    ),
    "llama-3.1-70b": ModelConfig(
        name="llama-3.1-70b", vocab_size=128256, hidden_size=8192,
        intermediate_size=28672, num_layers=80, num_heads=64,
        num_kv_heads=8, rope_theta=500000.0,
        max_position_embeddings=131072,
        rope_scaling=("llama3", 8.0, 1.0, 4.0, 8192),
    ),
    "mistral-7b": ModelConfig(
        name="mistral-7b", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        max_position_embeddings=32768,
    ),
    # Mistral-7B v0.1: same shapes, 4096-token sliding-window attention
    "mistral-7b-v0.1": ModelConfig(
        name="mistral-7b-v0.1", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32,
        num_kv_heads=8, max_position_embeddings=32768,
        sliding_window=4096,
    ),
    # Tiny sliding-window model for CPU tests (window << context)
    "debug-sliding": ModelConfig(
        name="debug-sliding", vocab_size=512, hidden_size=128,
        intermediate_size=384, num_layers=2, num_heads=4, num_kv_heads=2,
        max_position_embeddings=512, sliding_window=64,
    ),
    "qwen2-7b": ModelConfig(
        name="qwen2-7b", vocab_size=152064, hidden_size=3584,
        intermediate_size=18944, num_layers=28, num_heads=28,
        num_kv_heads=4, rope_theta=1000000.0,
        max_position_embeddings=32768, attention_bias=True,
    ),
    "gemma-2b": ModelConfig(
        name="gemma-2b", vocab_size=256000, hidden_size=2048,
        intermediate_size=16384, num_layers=18, num_heads=8,
        num_kv_heads=1, head_dim=256, max_position_embeddings=8192,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True, activation="gelu_tanh",
        rms_norm_offset=True, embed_scale=True,
    ),
    # Tiny MoE for CPU tests: 4 experts, top-2, Mixtral semantics.
    "debug-moe": ModelConfig(
        name="debug-moe", vocab_size=512, hidden_size=128,
        intermediate_size=256, num_layers=2, num_heads=4, num_kv_heads=2,
        max_position_embeddings=512, num_experts=4, num_experts_per_tok=2,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b", vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32,
        num_kv_heads=8, rope_theta=1000000.0,
        max_position_embeddings=32768, num_experts=8,
        num_experts_per_tok=2,
    ),
    # Qwen1.5-MoE-A2.7B: 60 experts top-4 (raw softmax weights) + an
    # always-on shared expert behind a sigmoid gate
    "qwen1.5-moe-a2.7b": ModelConfig(
        name="qwen1.5-moe-a2.7b", vocab_size=151936, hidden_size=2048,
        intermediate_size=5632, num_layers=24, num_heads=16,
        num_kv_heads=16, rope_theta=1000000.0,
        max_position_embeddings=8192, attention_bias=True,
        num_experts=60, num_experts_per_tok=4, norm_topk_prob=False,
        moe_intermediate_size=1408, shared_expert_size=5632,
        moe_naming="qwen2",
    ),
    # Gemma-2-2B: alternating 4096-window/global layers, softcaps,
    # sandwich norms, query_pre_attn_scalar = head_dim (256)
    "gemma-2-2b": ModelConfig(
        name="gemma-2-2b", vocab_size=256000, hidden_size=2304,
        intermediate_size=9216, num_layers=26, num_heads=8,
        num_kv_heads=4, head_dim=256, max_position_embeddings=8192,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True, activation="gelu_tanh",
        rms_norm_offset=True, embed_scale=True,
        sliding_window=4096, alternating_sliding=True,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        query_pre_attn_scalar=256.0, sandwich_norms=True,
    ),
    "gemma-2-9b": ModelConfig(
        name="gemma-2-9b", vocab_size=256000, hidden_size=3584,
        intermediate_size=14336, num_layers=42, num_heads=16,
        num_kv_heads=8, head_dim=256, max_position_embeddings=8192,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True, activation="gelu_tanh",
        rms_norm_offset=True, embed_scale=True,
        sliding_window=4096, alternating_sliding=True,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        query_pre_attn_scalar=256.0, sandwich_norms=True,
    ),
    # Tiny Gemma-2-style model for CPU tests (all deviations on)
    "debug-gemma2": ModelConfig(
        name="debug-gemma2", vocab_size=512, hidden_size=128,
        intermediate_size=384, num_layers=2, num_heads=4,
        num_kv_heads=2, max_position_embeddings=512,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True, activation="gelu_tanh",
        rms_norm_offset=True, embed_scale=True,
        sliding_window=64, alternating_sliding=True,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        query_pre_attn_scalar=32.0, sandwich_norms=True,
    ),
    "gemma-7b": ModelConfig(
        name="gemma-7b", vocab_size=256000, hidden_size=3072,
        intermediate_size=24576, num_layers=28, num_heads=16,
        num_kv_heads=16, head_dim=256, max_position_embeddings=8192,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True, activation="gelu_tanh",
        rms_norm_offset=True, embed_scale=True,
    ),
}

# Qwen2.5-7B shares Qwen2-7B's architecture shapes exactly
PRESETS["qwen2.5-7b"] = dataclasses.replace(PRESETS["qwen2-7b"],
                                            name="qwen2.5-7b")


# HF hub ids commonly passed as --model (e.g. from helm modelSpec
# entries) resolved to the preset with the same geometry; weights still
# come from --checkpoint (or are random-initialized).
HF_ALIASES: Dict[str, str] = {
    "meta-llama/Meta-Llama-3-8B": "llama-3-8b",
    "meta-llama/Meta-Llama-3-8B-Instruct": "llama-3-8b",
    "meta-llama/Llama-3.1-8B": "llama-3.1-8b",
    "meta-llama/Llama-3.1-8B-Instruct": "llama-3.1-8b",
    "meta-llama/Meta-Llama-3-70B": "llama-3-70b",
    "meta-llama/Meta-Llama-3-70B-Instruct": "llama-3-70b",
    "meta-llama/Llama-3.1-70B-Instruct": "llama-3.1-70b",
    "mistralai/Mistral-7B-v0.1": "mistral-7b-v0.1",
    "mistralai/Mistral-7B-Instruct-v0.2": "mistral-7b",
    "mistralai/Mistral-7B-Instruct-v0.3": "mistral-7b",
    "TinyLlama/TinyLlama-1.1B-Chat-v1.0": "tinyllama-1.1b",
    "Qwen/Qwen2-7B": "qwen2-7b",
    "Qwen/Qwen2-7B-Instruct": "qwen2-7b",
    "Qwen/Qwen2.5-7B": "qwen2.5-7b",
    "Qwen/Qwen2.5-7B-Instruct": "qwen2.5-7b",
    "mistralai/Mixtral-8x7B-v0.1": "mixtral-8x7b",
    "mistralai/Mixtral-8x7B-Instruct-v0.1": "mixtral-8x7b",
    "Qwen/Qwen1.5-MoE-A2.7B": "qwen1.5-moe-a2.7b",
    "Qwen/Qwen1.5-MoE-A2.7B-Chat": "qwen1.5-moe-a2.7b",
    "google/gemma-2b": "gemma-2b",
    "google/gemma-2b-it": "gemma-2b",
    "google/gemma-7b": "gemma-7b",
    "google/gemma-7b-it": "gemma-7b",
    "meta-llama/Llama-3.2-1B": "llama-3.2-1b",
    "meta-llama/Llama-3.2-1B-Instruct": "llama-3.2-1b",
    "meta-llama/Llama-3.2-3B": "llama-3.2-3b",
    "meta-llama/Llama-3.2-3B-Instruct": "llama-3.2-3b",
    "google/gemma-2-2b": "gemma-2-2b",
    "google/gemma-2-2b-it": "gemma-2-2b",
    "google/gemma-2-9b": "gemma-2-9b",
    "google/gemma-2-9b-it": "gemma-2-9b",
}


def get_config(name: str) -> ModelConfig:
    if name in PRESETS:
        return PRESETS[name]
    if name in HF_ALIASES:
        cfg = PRESETS[HF_ALIASES[name]]
        return dataclasses.replace(cfg, name=name)
    if os.path.exists(name):
        return ModelConfig.from_json(name)
    raise KeyError(
        f"unknown model {name!r}; presets: {sorted(PRESETS)}, known HF ids: "
        f"{sorted(HF_ALIASES)}, or a path to an HF checkpoint directory"
    )
