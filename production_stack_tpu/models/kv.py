"""Paged KV cache: a global block pool + per-slot block tables.

Layout: ``k, v [L, N, Hkv, Bs, D]`` — N fixed-size blocks of Bs token
positions each, shared by every sequence, with blocks stored
HEAD-MAJOR: a (block, kv-head) panel is a contiguous ``[Bs, D]`` tile,
the shape both the XLA gather path and the Pallas paged-attention
kernel (ops/pallas_paged.py) want as their minor dims on TPU. A
sequence owns an ordered list of blocks; its *block table* row maps
virtual position ``p`` to pool location ``(table[p // Bs], p % Bs)``.
HBM is sized by ``EngineConfig.kv_pool_tokens``, not
``max_num_seqs × max_model_len``: batch capacity scales with *live*
context, and prefix caching is block *sharing* (refcounts in
engine/block_manager.py) instead of copies.

TPU-first invariants:
- Static shapes everywhere: the pool, the tables [B, MB], and the
  attention view are all fixed-size; block allocation is pure host
  bookkeeping and never recompiles anything.
- **Block 0 is the trash block.** It is never allocated; writes from
  parked rows, padding tokens, and beyond-capacity window tails are
  routed to it via the ``valid`` mask. Invalid writes all land in a
  block no table references.
- Reads go through the Pallas paged kernel (blocks streamed straight
  from the pool through scalar-prefetched tables — each KV byte read
  once) or, on backends/meshes the kernel does not cover, a *gathered
  view* (``gather_view``): the first ``nb`` table entries pull
  [B, nb*Bs, Hkv, D] out of the pool for the position-masked jnp
  attention (ops/attention.py). View index s IS virtual position s,
  so the causal position mask also hides any stale/garbage block
  contents: a query at position p only attends s <= p, and every
  position <= p of a live row has been written by construction.
- Sharding: heads over tp, block axis over dp
  (parallel/sharding.py cache_pspec). Under a tp-only serving mesh
  both the kernel (shard_map over the head axis) and the gather
  (indices replicated, gathered axis unsharded) are shard-local: no
  extra collectives.

The reference stack's KV management is configuration around LMCache env
vars (reference: helm/templates/deployment-vllm-multi.yaml:154-178) and
its engine's paged KV lives inside vLLM (the stack passes
--enable-prefix-caching, deployment-vllm-multi.yaml:73-75); this module
is the TPU-native equivalent of that engine layer.
"""

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, N, Hkv, Bs, D]
    v: jnp.ndarray  # [L, N, Hkv, Bs, D]
    # int8 KV mode only: symmetric per-(token, head) dequant scales
    # (models/quant.py recipe applied to the cache): value = int8 *
    # scale. None = full-precision cache.
    ks: Optional[jnp.ndarray] = None  # [L, N, Hkv, Bs] f32
    vs: Optional[jnp.ndarray] = None

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.ks is not None


def make_cache(num_layers: int, num_blocks: int, block_size: int,
               num_kv_heads: int, head_dim: int,
               dtype=jnp.bfloat16) -> KVCache:
    """Block pool. num_blocks INCLUDES the reserved trash block 0.

    dtype jnp.int8 allocates the quantized pool: int8 payload plus
    per-(token, head) fp32 scales — halving decode's KV HBM traffic
    (the dominant long-context cost) for ~0.4% the scale overhead
    (4 bytes per D=64..128 values)."""
    shape = (num_layers, num_blocks, num_kv_heads, block_size, head_dim)
    if dtype == jnp.int8:
        sshape = shape[:-1]
        return KVCache(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       ks=jnp.zeros(sshape, jnp.float32),
                       vs=jnp.zeros(sshape, jnp.float32))
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def linear_tables(num_slots: int, max_len: int,
                  block_size: int) -> jnp.ndarray:
    """Identity block tables [B, MB]: slot b owns blocks
    1 + b*MB .. 1 + (b+1)*MB - 1 (block 0 stays trash). With a pool of
    num_slots*MB + 1 blocks this reproduces the contiguous per-slot
    cache — the simple configuration for tests and single-sequence
    use (models/__init__.make_slot_cache)."""
    mb = -(-max_len // block_size)
    return (1 + jnp.arange(num_slots * mb, dtype=jnp.int32)
            ).reshape(num_slots, mb)


def make_slot_cache(num_layers: int, num_slots: int, max_len: int,
                    num_kv_heads: int, head_dim: int,
                    dtype=jnp.bfloat16, block_size: int = 64,
                    ) -> Tuple[KVCache, jnp.ndarray]:
    """(pool, tables) equivalent to the old per-slot contiguous cache."""
    block_size = min(block_size, max(8, max_len))
    mb = -(-max_len // block_size)
    cache = make_cache(num_layers, num_slots * mb + 1, block_size,
                       num_kv_heads, head_dim, dtype)
    return cache, linear_tables(num_slots, max_len, block_size)


def _chunk_addresses(tables: jnp.ndarray, positions: jnp.ndarray,
                     block_size: int,
                     valid: Optional[jnp.ndarray],
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(flat block ids, flat intra-block offsets) for a [B, T] chunk of
    virtual positions — the ONE addressing contract every pool writer
    shares: tables map position//Bs to a block; tokens that are invalid,
    negative, or beyond the virtual capacity MB*Bs route to trash
    block 0 (collisions there are irrelevant by construction)."""
    Bs = block_size
    MB = tables.shape[1]
    bi = jnp.clip(positions // Bs, 0, MB - 1)
    blk = jnp.take_along_axis(tables, bi, axis=1)           # [B, T]
    off = positions % Bs
    oob = (positions < 0) | (positions >= MB * Bs)
    if valid is not None:
        oob = oob | ~valid
    blk = jnp.where(oob, 0, blk)                            # block 0
    return blk.reshape(-1), off.reshape(-1)


def write_chunk(cache_layer: jnp.ndarray, new: jnp.ndarray,
                tables: jnp.ndarray, positions: jnp.ndarray,
                valid: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Scatter new [B,T,Hkv,D] into the pool layer [N,Hkv,Bs,D].

    positions [B,T] are virtual positions; tables [B,MB] map them to
    blocks. Tokens with valid == False (padding, parked rows, window
    tails past capacity) are routed to trash block 0. Callers on the
    serving path MUST pass valid; None (tests, single-sequence loops)
    treats every in-range token as real, which is only safe when
    positions never exceed the virtual capacity MB*Bs.
    """
    new = new.astype(cache_layer.dtype)
    B, T = positions.shape
    blk, off = _chunk_addresses(tables, positions, cache_layer.shape[2],
                                valid)
    # advanced indices on the block and offset axes land the [Hkv, D]
    # slab of every token at its (block, head-major row) home
    return cache_layer.at[blk, :, off, :].set(
        new.reshape((B * T,) + new.shape[2:]))


def quantize_chunk(new: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-(token, head) int8 over the head dim.

    new [B,T,Hkv,D] -> (int8 same shape, fp32 scale [B,T,Hkv]) with
    value = int8 * scale. Mirrors models/quant.quantize_tensor's
    recipe, with the channel axis per cached token (K/V vectors are
    consumed whole per position, so one scale per vector loses
    nothing to outlier columns)."""
    f = new.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(f), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127
                 ).astype(jnp.int8)
    return q, scale


def write_chunk_q(cache_layer: jnp.ndarray, scale_layer: jnp.ndarray,
                  new: jnp.ndarray, tables: jnp.ndarray,
                  positions: jnp.ndarray,
                  valid: Optional[jnp.ndarray] = None,
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """write_chunk for the int8 pool: quantize new [B,T,Hkv,D] and
    scatter payload + scales ([N,Hkv,Bs,D] int8, [N,Hkv,Bs] f32)
    through the same (block, offset) addressing (_chunk_addresses)."""
    q, scale = quantize_chunk(new)
    B, T = positions.shape
    blk, off = _chunk_addresses(tables, positions, cache_layer.shape[2],
                                valid)
    layer = cache_layer.at[blk, :, off, :].set(
        q.reshape((B * T,) + q.shape[2:]))
    scales = scale_layer.at[blk, :, off].set(
        scale.reshape(B * T, -1))
    return layer, scales


def gather_view(cache_layer: jnp.ndarray, tables: jnp.ndarray,
                nb: int) -> jnp.ndarray:
    """Materialize the first nb blocks of every slot as a contiguous
    [B, nb*Bs, Hkv, D] view; view index s is virtual position s.
    Unallocated table entries read trash block 0 — garbage that the
    causal position mask always hides (a query at position p only
    attends positions <= p, all of which are allocated and written)."""
    Hkv, Bs = cache_layer.shape[1], cache_layer.shape[2]
    t = tables[:, :nb]                                       # [B, nb]
    g = cache_layer[t]                                       # [B,nb,Hkv,Bs,D]
    g = g.transpose(0, 1, 3, 2, 4)                           # [B,nb,Bs,Hkv,D]
    return g.reshape(t.shape[0], nb * Bs, Hkv,
                     cache_layer.shape[-1])


def gather_view_q(cache_layer: jnp.ndarray, scale_layer: jnp.ndarray,
                  tables: jnp.ndarray, nb: int,
                  dtype=jnp.bfloat16) -> jnp.ndarray:
    """gather_view for the int8 pool: dequantized [B, nb*Bs, Hkv, D]
    in `dtype`. The HBM read is int8 + one scale per vector — half the
    bf16 pool's traffic; the dequantized product is a fused temporary
    feeding attention, never resident."""
    Hkv, Bs = cache_layer.shape[1], cache_layer.shape[2]
    t = tables[:, :nb]
    # dequantize in f32 and cast the PRODUCT — the pallas kernels
    # dequantize at f32 too, so the fallback and kernel paths stay
    # numerically identical (greedy streams must not depend on which
    # backend served a window)
    g = cache_layer[t].astype(jnp.float32)            # [B,nb,Hkv,Bs,D]
    s = scale_layer[t].astype(jnp.float32)            # [B,nb,Hkv,Bs]
    g = (g * s[..., None]).astype(dtype)
    g = g.transpose(0, 1, 3, 2, 4)
    return g.reshape(t.shape[0], nb * Bs, Hkv, cache_layer.shape[-1])
