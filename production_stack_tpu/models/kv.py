"""Paged KV cache: a global block pool + per-slot block tables.

Layout: ``k, v [L, N, Hkv, Bs, D]`` — N fixed-size blocks of Bs token
positions each, shared by every sequence, with blocks stored
HEAD-MAJOR: a (block, kv-head) panel is a contiguous ``[Bs, D]`` tile,
the shape both the XLA gather path and the Pallas paged-attention
kernel (ops/pallas_paged.py) want as their minor dims on TPU. A
sequence owns an ordered list of blocks; its *block table* row maps
virtual position ``p`` to pool location ``(table[p // Bs], p % Bs)``.
HBM is sized by ``EngineConfig.kv_pool_tokens``, not
``max_num_seqs × max_model_len``: batch capacity scales with *live*
context, and prefix caching is block *sharing* (refcounts in
engine/block_manager.py) instead of copies.

TPU-first invariants:
- Static shapes everywhere: the pool, the tables [B, MB], and the
  attention view are all fixed-size; block allocation is pure host
  bookkeeping and never recompiles anything.
- **Block 0 is the trash block.** It is never allocated; writes from
  parked rows, padding tokens, and beyond-capacity window tails are
  routed to it via the ``valid`` mask. Invalid writes all land in a
  block no table references.
- Reads go through the Pallas paged kernel (blocks streamed straight
  from the pool through scalar-prefetched tables — each KV byte read
  once) or, on backends/meshes the kernel does not cover, a *gathered
  view* (``gather_view``): the first ``nb`` table entries pull
  [B, nb*Bs, Hkv, D] out of the pool for the position-masked jnp
  attention (ops/attention.py). View index s IS virtual position s,
  so the causal position mask also hides any stale/garbage block
  contents: a query at position p only attends s <= p, and every
  position <= p of a live row has been written by construction.
- Sharding: heads over tp, block axis over dp
  (parallel/sharding.py cache_pspec). Under a tp-only serving mesh
  both the kernel (shard_map over the head axis) and the gather
  (indices replicated, gathered axis unsharded) are shard-local: no
  extra collectives.

The reference stack's KV management is configuration around LMCache env
vars (reference: helm/templates/deployment-vllm-multi.yaml:154-178) and
its engine's paged KV lives inside vLLM (the stack passes
--enable-prefix-caching, deployment-vllm-multi.yaml:73-75); this module
is the TPU-native equivalent of that engine layer.
"""

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, N, Hkv, Bs, D]
    v: jnp.ndarray  # [L, N, Hkv, Bs, D]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]


def make_cache(num_layers: int, num_blocks: int, block_size: int,
               num_kv_heads: int, head_dim: int,
               dtype=jnp.bfloat16) -> KVCache:
    """Block pool. num_blocks INCLUDES the reserved trash block 0."""
    shape = (num_layers, num_blocks, num_kv_heads, block_size, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def linear_tables(num_slots: int, max_len: int,
                  block_size: int) -> jnp.ndarray:
    """Identity block tables [B, MB]: slot b owns blocks
    1 + b*MB .. 1 + (b+1)*MB - 1 (block 0 stays trash). With a pool of
    num_slots*MB + 1 blocks this reproduces the contiguous per-slot
    cache — the simple configuration for tests and single-sequence
    use (models/__init__.make_slot_cache)."""
    mb = -(-max_len // block_size)
    return (1 + jnp.arange(num_slots * mb, dtype=jnp.int32)
            ).reshape(num_slots, mb)


def make_slot_cache(num_layers: int, num_slots: int, max_len: int,
                    num_kv_heads: int, head_dim: int,
                    dtype=jnp.bfloat16, block_size: int = 64,
                    ) -> Tuple[KVCache, jnp.ndarray]:
    """(pool, tables) equivalent to the old per-slot contiguous cache."""
    block_size = min(block_size, max(8, max_len))
    mb = -(-max_len // block_size)
    cache = make_cache(num_layers, num_slots * mb + 1, block_size,
                       num_kv_heads, head_dim, dtype)
    return cache, linear_tables(num_slots, max_len, block_size)


def write_chunk(cache_layer: jnp.ndarray, new: jnp.ndarray,
                tables: jnp.ndarray, positions: jnp.ndarray,
                valid: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Scatter new [B,T,Hkv,D] into the pool layer [N,Hkv,Bs,D].

    positions [B,T] are virtual positions; tables [B,MB] map them to
    blocks. Tokens with valid == False (padding, parked rows, window
    tails past capacity) are routed to trash block 0 — collisions
    there are irrelevant by construction. Callers on the serving path
    MUST pass valid; None (tests, single-sequence loops) treats every
    in-range token as real, which is only safe when positions never
    exceed the virtual capacity MB*Bs.
    """
    new = new.astype(cache_layer.dtype)
    Bs = cache_layer.shape[2]
    B, T = positions.shape
    MB = tables.shape[1]
    bi = jnp.clip(positions // Bs, 0, MB - 1)
    blk = jnp.take_along_axis(tables, bi, axis=1)           # [B, T]
    off = positions % Bs
    # beyond-capacity positions can only reach here masked or in test
    # paths; clamp them onto trash rather than wrapping into a block
    oob = (positions < 0) | (positions >= MB * Bs)
    if valid is not None:
        oob = oob | ~valid
    blk = jnp.where(oob, 0, blk)                            # block 0
    # advanced indices on the block and offset axes land the [Hkv, D]
    # slab of every token at its (block, head-major row) home
    return cache_layer.at[blk.reshape(-1), :, off.reshape(-1), :].set(
        new.reshape((B * T,) + new.shape[2:]))


def gather_view(cache_layer: jnp.ndarray, tables: jnp.ndarray,
                nb: int) -> jnp.ndarray:
    """Materialize the first nb blocks of every slot as a contiguous
    [B, nb*Bs, Hkv, D] view; view index s is virtual position s.
    Unallocated table entries read trash block 0 — garbage that the
    causal position mask always hides (a query at position p only
    attends positions <= p, all of which are allocated and written)."""
    Hkv, Bs = cache_layer.shape[1], cache_layer.shape[2]
    t = tables[:, :nb]                                       # [B, nb]
    g = cache_layer[t]                                       # [B,nb,Hkv,Bs,D]
    g = g.transpose(0, 1, 3, 2, 4)                           # [B,nb,Bs,Hkv,D]
    return g.reshape(t.shape[0], nb * Bs, Hkv,
                     cache_layer.shape[-1])
