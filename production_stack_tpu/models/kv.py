"""KV cache container shared by models/ and engine/.

Slot-based, statically-shaped cache: each running sequence owns one batch
slot of a preallocated [L, B, S, Hkv, D] buffer. Static shapes keep every
decode step a single cached XLA executable; per-sequence lengths are data
(positions/masks), not shapes.

The reference stack's KV management is configuration around LMCache env
vars (reference: helm/templates/deployment-vllm-multi.yaml:154-178); the
actual in-engine cache is external to it. Here the cache is a first-class
functional object so tiering (kvcache/connector.py) can snapshot/restore
slots via the runner's extract_chunk/inject_chunk primitives.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, S, Hkv, D]
    v: jnp.ndarray  # [L, B, S, Hkv, D]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def make_cache(num_layers: int, num_slots: int, max_len: int,
               num_kv_heads: int, head_dim: int, dtype=jnp.bfloat16) -> KVCache:
    shape = (num_layers, num_slots, max_len, num_kv_heads, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def write_chunk(cache_layer: jnp.ndarray, new: jnp.ndarray,
                starts: jnp.ndarray) -> jnp.ndarray:
    """Write new [B,T,Hkv,D] into cache_layer [B,S,Hkv,D] at per-row starts [B].

    T == 1 (decode): contiguous dynamic-update-slice per batch row — lowers
    to an in-place DUS on TPU when the buffer is donated. DUS start
    clamping is LOAD-BEARING here: the engine parks free/prefilling rows
    at position S (engine.py _park_slot), so their per-window writes
    arrive with s >= S and must clamp onto S-1 — a position outside every
    live kv bucket that is rewritten with real K/V (earlier in the same
    forward) before any query could attend it. Do not replace the DUS
    with an unclamped scatter.

    T > 1 (prefill): per-row scatter with clipped indices. A prefill chunk
    is right-padded to its length bucket, so start+T can exceed S near the
    end of the cache; DUS would *clamp the start* and silently overwrite
    valid earlier entries with padding K/V. Scatter clips only the padding
    rows onto index S-1 (real prompt rows never reach S-1 because prompts
    are capped below max_model_len), and that slot is rewritten with real
    K/V by the decode step that reaches position S-1 before any query can
    attend to it.
    """
    # the cache may be narrower than the compute dtype (fp32 model with a
    # bf16 KV cache); DUS/scatter require matching dtypes
    new = new.astype(cache_layer.dtype)
    if new.shape[1] == 1:
        def _one(c, x, s):
            return jax.lax.dynamic_update_slice(c, x, (s, 0, 0))
        return jax.vmap(_one)(cache_layer, new, starts)

    S = cache_layer.shape[1]
    T = new.shape[1]

    def _scatter(c, x, s):
        idx = jnp.clip(s + jnp.arange(T), 0, S - 1)
        return c.at[idx].set(x)

    return jax.vmap(_scatter)(cache_layer, new, starts)
