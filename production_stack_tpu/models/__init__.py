from production_stack_tpu.models.config import ModelConfig, PRESETS, get_config
from production_stack_tpu.models.kv import (KVCache, make_cache,
                                             make_slot_cache)
from production_stack_tpu.models import llama

__all__ = ["ModelConfig", "PRESETS", "get_config", "KVCache", "make_cache",
           "make_slot_cache", "llama"]
