"""Llama-family decoder as pure JAX functions over a stacked-params pytree.

Design (TPU-first, not a torch translation):
- All L layers' weights are stacked along a leading layer axis and the
  forward pass runs ``lax.scan`` over layers: one traced layer body, O(1)
  compile time in depth, and a natural seam for pipeline parallelism.
- Weights live in bf16 (MXU-native); norms/softmax/logits in fp32.
- Two entry points: ``forward`` (incremental, serving; reads/writes the
  slot KV cache) and ``forward_train`` (full-sequence, no cache; used by
  the training step and numerics tests).
- Sharding is NOT baked in here — parallel/sharding.py assigns
  PartitionSpecs to this pytree by path (megatron-style column/row rules),
  so the same model code runs single-chip or on any mesh.

The reference repo contains no model code (models are strings passed to
``vllm serve``, reference: helm/templates/deployment-vllm-multi.yaml:57-64);
this module is the TPU-native engine's compute core.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.models import lora, quant
from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.models.kv import (KVCache, gather_view,
                                            gather_view_q, write_chunk,
                                            write_chunk_q)
from production_stack_tpu.ops import moe, pallas_attention, pallas_paged
from production_stack_tpu.ops.attention import attention_with_cache, causal_attention
from production_stack_tpu.ops.norms import rms_norm
from production_stack_tpu.ops.rope import apply_rope, rope_table

Params = Dict[str, Any]


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random init (normal 0.02) in cfg.dtype, stacked-layer layout."""
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    nh, nkv, hd, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_, cfg.num_layers
    keys = iter(jax.random.split(key, 16))

    def w(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(cfg.dtype)

    norm_init = jnp.zeros if cfg.rms_norm_offset else jnp.ones
    E = cfg.num_experts
    params: Params = {
        "embed": w(next(keys), (v, h)),
        "layers": {
            "attn_norm": norm_init((L, h), cfg.dtype),
            "q": w(next(keys), (L, h, nh * hd)),
            "k": w(next(keys), (L, h, nkv * hd)),
            "v": w(next(keys), (L, h, nkv * hd)),
            "o": w(next(keys), (L, nh * hd, h)),
            "mlp_norm": norm_init((L, h), cfg.dtype),
        },
        "final_norm": norm_init((h,), cfg.dtype),
    }
    if cfg.sandwich_norms:
        # Gemma-2: post-attention and post-feedforward norms too
        params["layers"]["post_attn_norm"] = norm_init((L, h), cfg.dtype)
        params["layers"]["post_mlp_norm"] = norm_init((L, h), cfg.dtype)
    # key order matters: dense models must draw gate/up/down from the
    # same key positions as before MoE existed (seeded tests pin outputs)
    if E:
        mi = cfg.moe_intermediate_size or i
        params["layers"].update({
            "gate": w(next(keys), (L, E, h, mi)),
            "up": w(next(keys), (L, E, h, mi)),
            "down": w(next(keys), (L, E, mi, h)),
            "router": w(next(keys), (L, h, E)),
        })
        if cfg.shared_expert_size:
            si = cfg.shared_expert_size
            params["layers"].update({
                "s_gate": w(next(keys), (L, h, si)),
                "s_up": w(next(keys), (L, h, si)),
                "s_down": w(next(keys), (L, si, h)),
                "s_gate_w": w(next(keys), (L, h, 1)),
            })
    else:
        params["layers"].update({
            "gate": w(next(keys), (L, h, i)),
            "up": w(next(keys), (L, h, i)),
            "down": w(next(keys), (L, i, h)),
        })
    if cfg.attention_bias:
        # Qwen2: biases on the q/k/v projections only
        params["layers"]["q_bias"] = jnp.zeros((L, nh * hd), cfg.dtype)
        params["layers"]["k_bias"] = jnp.zeros((L, nkv * hd), cfg.dtype)
        params["layers"]["v_bias"] = jnp.zeros((L, nkv * hd), cfg.dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(next(keys), (h, v))
    return params


def _layer_body(cfg: ModelConfig, rope: Tuple[jnp.ndarray, jnp.ndarray],
                positions: jnp.ndarray, starts: Optional[jnp.ndarray],
                x: jnp.ndarray, lp: Params,
                kv: Optional[Tuple[jnp.ndarray, jnp.ndarray]],
                attention_fn=None, kv_len: Optional[int] = None,
                use_flash: bool = False, lora_layer=None,
                adapter_ids: Optional[jnp.ndarray] = None,
                lora_scaling: float = 1.0,
                token_valid: Optional[jnp.ndarray] = None,
                block_tables: Optional[jnp.ndarray] = None,
                mesh=None, layer_local=None):
    """One transformer block. x [B,T,H]; kv = this layer's paged pool
    (k, v) [N,Bs,Hkv,D] addressed through block_tables [B,MB]
    (models/kv.py).

    attention_fn(q, k, v) overrides the no-cache attention — used to swap
    in ring attention when the sequence dim is sharded (parallel/train.py).
    kv_len (static) bounds attention to the first ceil(kv_len/Bs) blocks
    of every slot: K/V writes target the pool via the tables, and
    score/value matmuls scale with the live context instead of
    max_model_len. Caller guarantees every real query position is
    < kv_len.
    token_valid [B,T] marks real tokens: invalid tokens' K/V writes are
    routed to the trash block and (on MoE models) they are kept out of
    expert-capacity competition.
    lora_layer: this layer's stacked adapters {proj: {a, b}} + per-row
    adapter_ids [B] (models/lora.py) — batched multi-LoRA.
    """
    B, T, _ = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    cos, sin = rope

    def proj(h, name):
        out = quant.dequant_matmul(h, lp[name])
        bias = lp.get(f"{name}_bias")
        if bias is not None:
            out = out + bias
        if lora_layer is not None and name in lora_layer:
            out = lora.apply(h, out, lora_layer[name], adapter_ids,
                             lora_scaling)
        return out

    offset = 1.0 if cfg.rms_norm_offset else 0.0
    hidden = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps, offset=offset)
    q = proj(hidden, "q").reshape(B, T, nh, hd)
    k = proj(hidden, "k").reshape(B, T, nkv, hd)
    v = proj(hidden, "v").reshape(B, T, nkv, hd)
    q = apply_rope(q, positions, cos, sin)
    k = apply_rope(k, positions, cos, sin)

    # Gemma-2 deviations from the Llama baseline: attention scale from
    # query_pre_attn_scalar, tanh score softcap, and (alternating)
    # sliding windows. layer_local (traced bool, from the scan's
    # per-layer flags) picks between two STATICALLY-windowed branches
    # via lax.cond — kernels stay static-shaped.
    scale_val = ((float(cfg.query_pre_attn_scalar) ** -0.5)
                 if cfg.query_pre_attn_scalar else hd ** -0.5)
    cap = cfg.attn_logit_softcap
    sw = cfg.sliding_window

    def _windowed(attn_fn_w):
        if cfg.alternating_sliding:
            return jax.lax.cond(layer_local,
                                lambda: attn_fn_w(sw),
                                lambda: attn_fn_w(None))
        return attn_fn_w(sw)

    if kv is None:
        if attention_fn is not None:
            attn = attention_fn(q, k, v)
        else:
            attn = _windowed(lambda w: causal_attention(
                q, k, v, scale=scale_val, sliding_window=w,
                logit_softcap=cap))
        new_kv = None
    else:
        quant_kv = len(kv) == 4   # (k, v, ks, vs): int8 pool + scales
        if quant_kv:
            k_cache, k_scales = write_chunk_q(
                kv[0], kv[2], k, block_tables, positions,
                valid=token_valid)
            v_cache, v_scales = write_chunk_q(
                kv[1], kv[3], v, block_tables, positions,
                valid=token_valid)
        else:
            k_cache = write_chunk(kv[0], k, block_tables, positions,
                                  valid=token_valid)
            v_cache = write_chunk(kv[1], v, block_tables, positions,
                                  valid=token_valid)
        Bs = k_cache.shape[2]
        MB = block_tables.shape[1]
        nb = MB if kv_len is None else min(-(-kv_len // Bs), MB)

        def cached_attn(w):
            if (use_flash
                    and pallas_paged.paged_viable(T, nh // nkv, hd, Bs)
                    and (mesh is None
                         or pallas_paged.mesh_tp_only(mesh))):
                # paged flash kernel: K/V blocks streamed straight from
                # the pool through the tables — no gathered copy, no
                # [T, S] score materialization, per-row causal block
                # skipping. Covers prefill chunks AND decode/spec
                # windows; under a tp-only mesh it runs shard-local per
                # head via shard_map.
                interp = pallas_attention.needs_interpret()
                sc = (dict(k_scales=k_scales, v_scales=v_scales)
                      if quant_kv else {})
                if w:
                    sc["window"] = w
                sc["scale"] = scale_val
                sc["softcap"] = cap or 0.0
                if mesh is None:
                    # short windows (decode / speculative verify) take
                    # the wide kernel: all kv heads + several pool
                    # blocks per grid step, ~16x fewer grid steps than
                    # the general one
                    paged_fn = (pallas_paged.paged_decode_attention
                                if T <= pallas_paged.DECODE_T_MAX
                                else pallas_paged.paged_attention)
                    return paged_fn(
                        q, k_cache, v_cache, block_tables, starts,
                        nb=nb, interpret=interp, **sc)
                return pallas_paged.paged_attention_sharded(
                    q, k_cache, v_cache, block_tables, starts, mesh,
                    nb=nb, interpret=interp, **sc)
            if quant_kv:
                k_att = gather_view_q(k_cache, k_scales, block_tables,
                                      nb, dtype=q.dtype)
                v_att = gather_view_q(v_cache, v_scales, block_tables,
                                      nb, dtype=q.dtype)
            else:
                k_att = gather_view(k_cache, block_tables, nb)
                v_att = gather_view(v_cache, block_tables, nb)
            return attention_with_cache(q, k_att, v_att, positions,
                                        scale=scale_val,
                                        sliding_window=w,
                                        logit_softcap=cap)

        attn = _windowed(cached_attn)
        new_kv = ((k_cache, v_cache, k_scales, v_scales) if quant_kv
                  else (k_cache, v_cache))
    o_out = proj(attn.reshape(B, T, nh * hd), "o")
    if cfg.sandwich_norms:
        # Gemma-2: normalize the attention OUTPUT before the residual
        o_out = rms_norm(o_out, lp["post_attn_norm"], cfg.rms_norm_eps,
                         offset=offset)
    x = x + o_out

    hidden = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps, offset=offset)
    act = jax.nn.silu if cfg.activation == "silu" else _gelu_tanh
    if cfg.num_experts:
        H = hidden.shape[-1]
        y = moe.moe_mlp(
            hidden.reshape(B * T, H), lp["router"], lp["gate"],
            lp["up"], lp["down"], top_k=cfg.num_experts_per_tok,
            capacity_factor=cfg.moe_capacity_factor, act=act,
            valid=None if token_valid is None
            else token_valid.reshape(B * T),
            renormalize=cfg.norm_topk_prob,
            # decode (T == 1) must be exact: a dropped token would
            # corrupt a live sequence's residual stream mid-generation
            exact=True if T == 1 else None)
        if cfg.shared_expert_size:
            # Qwen2-MoE: an always-on shared expert, scaled by a
            # per-token sigmoid gate
            shared = quant.dequant_matmul(
                act(quant.dequant_matmul(hidden, lp["s_gate"]))
                * quant.dequant_matmul(hidden, lp["s_up"]),
                lp["s_down"])
            y = y.reshape(B, T, H) + jax.nn.sigmoid(
                hidden @ lp["s_gate_w"]) * shared
            x = x + y
        else:
            x = x + y.reshape(B, T, H)
    else:
        gated = act(proj(hidden, "gate")) * proj(hidden, "up")
        mlp_out = proj(gated, "down")
        if cfg.sandwich_norms:
            mlp_out = rms_norm(mlp_out, lp["post_mlp_norm"],
                               cfg.rms_norm_eps, offset=offset)
        x = x + mlp_out
    return x, new_kv


def _gelu_tanh(x: jnp.ndarray) -> jnp.ndarray:
    """Gemma's gelu_pytorch_tanh (jax.nn.gelu's approximate form)."""
    return jax.nn.gelu(x, approximate=True)


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, cache: KVCache,
            block_tables: Optional[jnp.ndarray] = None,
            rope: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
            kv_len: Optional[int] = None,
            use_flash: Optional[bool] = None,
            lora_params=None, adapter_ids: Optional[jnp.ndarray] = None,
            lora_scaling: float = 1.0,
            token_valid: Optional[jnp.ndarray] = None,
            mesh=None) -> Tuple[jnp.ndarray, KVCache]:
    """Incremental forward. tokens/positions [B,T] -> (logits fp32 [B,T,V], cache').

    cache is the paged block pool (models/kv.py); block_tables [B, MB]
    map each row's virtual positions to pool blocks (None = identity
    tables for a pool built by make_slot_cache, i.e. the contiguous
    per-slot layout). positions[b] must be contiguous starting at the
    sequence's current length; the new K/V chunk is written at that
    offset through the tables.
    kv_len (static) bounds attention to the first ceil(kv_len/Bs)
    blocks — see _layer_body.
    use_flash: None = auto (pallas flash prefill when the runtime gate is
    on); pass False on sharded executables — pallas_call has no GSPMD
    partitioning rule (see ops/pallas_attention.py).
    lora_params: layer-leading stacked adapters (models/lora.layer_slice)
    + adapter_ids [B] selecting each row's adapter (0 = base).
    token_valid [B,T] bool marks real (non-padding) tokens — their K/V
    writes are routed to the trash block, and MoE models keep them out
    of expert-capacity competition (ops/moe.py).
    """
    if rope is None:
        rope = rope_table(cfg.max_position_embeddings, cfg.head_dim_,
                          cfg.rope_theta, scaling=cfg.rope_scaling)
    if use_flash is None:
        use_flash = pallas_attention.flash_enabled()
    if block_tables is None:
        from production_stack_tpu.models.kv import linear_tables
        B = tokens.shape[0]
        Bs = cache.k.shape[2]
        n_per = (cache.k.shape[1] - 1) // B
        block_tables = linear_tables(B, n_per * Bs, Bs)
    starts = positions[:, 0]
    x = _embed(params, cfg, tokens)

    quant_kv = cache.quantized
    has_lora = lora_params is not None
    alternating = cfg.alternating_sliding
    nkv_leaves = 4 if quant_kv else 2

    def scan_body(carry, xs):
        i = 1
        lp = xs[0]
        kv_tuple = xs[i:i + nkv_leaves]
        i += nkv_leaves
        ll = None
        if has_lora:
            ll = xs[i]
            i += 1
        local = xs[i] if alternating else None
        out, new_kv = _layer_body(cfg, rope, positions, starts, carry,
                                  lp, kv_tuple, kv_len=kv_len,
                                  use_flash=use_flash, lora_layer=ll,
                                  adapter_ids=adapter_ids,
                                  lora_scaling=lora_scaling,
                                  token_valid=token_valid,
                                  block_tables=block_tables,
                                  mesh=mesh, layer_local=local)
        return out, new_kv

    xs = (params["layers"], cache.k, cache.v)
    if quant_kv:
        xs = xs + (cache.ks, cache.vs)
    if has_lora:
        xs = xs + (lora_params,)
    if alternating:
        # Gemma-2 layer pattern: even layers sliding, odd global
        xs = xs + (jnp.arange(cfg.num_layers) % 2 == 0,)
    x, new = jax.lax.scan(scan_body, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps,
                 offset=1.0 if cfg.rms_norm_offset else 0.0)
    logits = _lm_head(params, cfg, x)
    new_cache = (KVCache(k=new[0], v=new[1], ks=new[2], vs=new[3])
                 if quant_kv else KVCache(k=new[0], v=new[1]))
    return logits, new_cache


def encode(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
           rope: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
           attention_fn=None,
           token_valid: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Full-sequence causal forward WITHOUT the LM head: final-normed
    hidden states [B,T,H]. The embeddings/rerank/score endpoints pool
    these (engine/server.py); forward_train puts the head on top.
    token_valid [B,T] marks real tokens in right-padded batches — on
    MoE models padding must not compete for expert capacity.
    """
    if rope is None:
        rope = rope_table(cfg.max_position_embeddings, cfg.head_dim_,
                          cfg.rope_theta, scaling=cfg.rope_scaling)
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    x = _embed(params, cfg, tokens)

    def scan_body(carry, xs):
        lp, local = xs
        out, _ = _layer_body(cfg, rope, positions, None, carry, lp, None,
                             attention_fn=attention_fn,
                             token_valid=token_valid,
                             layer_local=local)
        return out, None

    local_flags = (jnp.arange(cfg.num_layers) % 2 == 0
                   if cfg.alternating_sliding
                   else jnp.zeros((cfg.num_layers,), bool))
    x, _ = jax.lax.scan(scan_body, x, (params["layers"], local_flags))
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps,
                    offset=1.0 if cfg.rms_norm_offset else 0.0)


def forward_train(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                  rope: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
                  attention_fn=None) -> jnp.ndarray:
    """Full-sequence causal forward without cache. tokens [B,T] -> logits fp32.

    attention_fn(q, k, v) -> out replaces dense causal attention when given
    (e.g. ring attention over an 'sp'-sharded sequence).
    """
    return _lm_head(params, cfg,
                    encode(params, cfg, tokens, rope=rope,
                           attention_fn=attention_fn))


def _embed(params: Params, cfg: ModelConfig,
           tokens: jnp.ndarray) -> jnp.ndarray:
    x = quant.dequant_rows(params["embed"], tokens, cfg.dtype)
    if cfg.embed_scale:
        # Gemma scales embeddings by sqrt(hidden)
        x = x.astype(jnp.float32) * jnp.sqrt(float(cfg.hidden_size))
    return x.astype(cfg.dtype)


def _lm_head(params: Params, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    from production_stack_tpu.ops.attention import _softcap

    def cap(logits):
        return _softcap(logits, cfg.final_logit_softcap)
    if cfg.tie_word_embeddings:
        emb = params["embed"]
        if quant.is_quantized(emb):
            # per-row scale (quantize_embed) lands on the vocab axis of
            # embed.T — apply it per logit after the int8 matmul
            logits = jnp.einsum("bth,vh->btv", x,
                                emb["w8"].astype(x.dtype),
                                preferred_element_type=jnp.float32)
            return cap(logits * emb["scale"][None, None, :])
        return cap(jnp.einsum("bth,hv->btv", x, emb.T,
                              preferred_element_type=jnp.float32))
    head = params["lm_head"]
    if quant.is_quantized(head):
        logits = jnp.einsum("bth,hv->btv", x, head["w8"].astype(x.dtype),
                            preferred_element_type=jnp.float32)
        return cap(logits * head["scale"][None, None, :])
    return cap(jnp.einsum("bth,hv->btv", x, head,
                          preferred_element_type=jnp.float32))
