"""Batched multi-LoRA for the Llama decoder: stacked adapters, per-row
selection inside one executable.

TPU-first design: all adapters live as ONE stacked pytree
``{proj: {"a": [N+1, L, in, r], "b": [N+1, L, r, out]}}`` with slot 0
zeroed (= base model). A decode/prefill batch carries per-row adapter
ids [B]; each layer gathers its rows' A/B factors and adds
``(x @ A_i) @ B_i * (alpha / r)`` to the base projection. Mixing
adapters in a batch therefore costs two small einsums per targeted
projection — no recompilation, no per-adapter executables, no batch
regrouping (the scheduler stays adapter-oblivious).

The reference exposes LoRA as engine flags + a CRD proposal
(reference: helm/templates/deployment-vllm-multi.yaml:65-67,
tutorials/09-lora-enabled-installation.md, proposals/lora-k8s-support.md
— load/unload adapters, route by served model name); here the engine
implements it natively and serves each adapter as a model id.

Checkpoint format: an .npz per adapter with keys
``{proj}.a`` [L, in, r] and ``{proj}.b`` [L, r, out] (float32/bf16),
plus optional scalars ``rank``/``alpha``. models/hf_loader.py-style PEFT
conversion is a thin reshape away (PEFT stores per-layer
lora_A [r, in] / lora_B [out, r]); see docs/lora.md.
"""

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from production_stack_tpu.models.config import ModelConfig

# projection name -> (in_dim, out_dim) factory
def _proj_dims(cfg: ModelConfig) -> Dict[str, Tuple[int, int]]:
    h, i = cfg.hidden_size, cfg.intermediate_size
    hd = cfg.head_dim_
    dims = {
        "q": (h, cfg.num_heads * hd),
        "k": (h, cfg.num_kv_heads * hd),
        "v": (h, cfg.num_kv_heads * hd),
        "o": (cfg.num_heads * hd, h),
    }
    if not cfg.num_experts:
        # MoE models have no dense MLP projections: the expert FFN runs
        # outside the LoRA-hooked proj() path (models/llama.py), so
        # offering gate/up/down there would silently no-op
        dims.update({"gate": (h, i), "up": (h, i), "down": (i, h)})
    return dims


DEFAULT_TARGETS = ("q", "v")


def _check_targets(cfg: ModelConfig, targets: Tuple[str, ...],
                   dims: Dict[str, Tuple[int, int]]) -> None:
    unknown = [t for t in targets if t not in dims]
    if unknown:
        hint = (" (MoE expert FFNs cannot take LoRA — adapt the "
                "attention projections instead)" if cfg.num_experts
                else "")
        raise ValueError(
            f"LoRA target(s) {unknown} not available for model "
            f"{cfg.name!r}; valid: {sorted(dims)}{hint}")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def init_adapter(cfg: ModelConfig, lcfg: LoRAConfig, key: jax.Array,
                 zero: bool = False) -> Dict[str, Dict[str, jnp.ndarray]]:
    """One adapter's params {proj: {a: [L, in, r], b: [L, r, out]}}.

    Standard LoRA init: A ~ N(0, 0.02), B = 0 (so a fresh adapter is a
    no-op until trained); ``zero`` also zeroes A (the base-model slot).
    """
    dims = _proj_dims(cfg)
    _check_targets(cfg, lcfg.targets, dims)
    L, r = cfg.num_layers, lcfg.rank
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for name in lcfg.targets:
        d_in, d_out = dims[name]
        key, sub = jax.random.split(key)
        a = jnp.zeros((L, d_in, r), cfg.dtype) if zero else (
            jax.random.normal(sub, (L, d_in, r), jnp.float32) * 0.02
        ).astype(cfg.dtype)
        out[name] = {"a": a, "b": jnp.zeros((L, r, d_out), cfg.dtype)}
    return out


def random_adapter(cfg: ModelConfig, lcfg: LoRAConfig, key: jax.Array,
                   ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """A synthetic adapter with BOTH factors non-zero — visibly changes
    model output. For tests/demos ("random:SEED" in EngineConfig)."""
    dims = _proj_dims(cfg)
    _check_targets(cfg, lcfg.targets, dims)
    L, r = cfg.num_layers, lcfg.rank
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for name in lcfg.targets:
        d_in, d_out = dims[name]
        key, ka, kb = jax.random.split(key, 3)
        out[name] = {
            "a": (jax.random.normal(ka, (L, d_in, r), jnp.float32)
                  * 0.05).astype(cfg.dtype),
            "b": (jax.random.normal(kb, (L, r, d_out), jnp.float32)
                  * 0.05).astype(cfg.dtype),
        }
    return out


def stack_adapters(cfg: ModelConfig, lcfg: LoRAConfig,
                   adapters: Sequence[Dict[str, Dict[str, jnp.ndarray]]],
                   ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """Stack [base-zero] + adapters into {proj: {a: [N+1, L, in, r], ...}}."""
    base = init_adapter(cfg, lcfg, jax.random.PRNGKey(0), zero=True)
    stacked: Dict[str, Dict[str, jnp.ndarray]] = {}
    for name in lcfg.targets:
        stacked[name] = {
            "a": jnp.stack([base[name]["a"]]
                           + [ad[name]["a"] for ad in adapters]),
            "b": jnp.stack([base[name]["b"]]
                           + [ad[name]["b"] for ad in adapters]),
        }
    return stacked


def load_adapter_npz(cfg: ModelConfig, lcfg: LoRAConfig, path: str,
                     ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """Load one adapter from an .npz checkpoint (format in module doc)."""
    data = np.load(path)
    dims = _proj_dims(cfg)
    _check_targets(cfg, lcfg.targets, dims)
    L, r = cfg.num_layers, lcfg.rank
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for name in lcfg.targets:
        a_key, b_key = f"{name}.a", f"{name}.b"
        if a_key not in data or b_key not in data:
            raise ValueError(f"adapter {path} missing {a_key}/{b_key}")
        a, b = np.asarray(data[a_key]), np.asarray(data[b_key])
        d_in, d_out = dims[name]
        if a.shape != (L, d_in, r) or b.shape != (L, r, d_out):
            raise ValueError(
                f"adapter {path} {name}: got a{a.shape} b{b.shape}, want "
                f"a{(L, d_in, r)} b{(L, r, d_out)}")
        out[name] = {"a": jnp.asarray(a, cfg.dtype),
                     "b": jnp.asarray(b, cfg.dtype)}
    return out


def save_adapter_npz(adapter: Dict[str, Dict[str, jnp.ndarray]],
                     path: str) -> None:
    # stored as float32: npz has no bfloat16; the loader casts back to
    # the model dtype, and fp32 round-trips bf16 values exactly
    arrays = {}
    for name, ab in adapter.items():
        arrays[f"{name}.a"] = np.asarray(ab["a"], np.float32)
        arrays[f"{name}.b"] = np.asarray(ab["b"], np.float32)
    np.savez(path, **arrays)


def layer_slice(stacked: Optional[Dict[str, Dict[str, jnp.ndarray]]],
                ) -> Optional[Dict[str, Dict[str, jnp.ndarray]]]:
    """Move the layer axis to the front for lax.scan consumption:
    {proj: {a: [L, N+1, in, r], b: [L, N+1, r, out]}}."""
    if stacked is None:
        return None
    return jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), stacked)


def apply(x: jnp.ndarray, base_out: jnp.ndarray,
          ab: Dict[str, jnp.ndarray], adapter_ids: jnp.ndarray,
          scaling: float) -> jnp.ndarray:
    """base_out [B,T,out] += scaling * (x @ A_i) @ B_i per batch row.

    ab: {"a": [N+1, in, r], "b": [N+1, r, out]} (one layer's stack);
    adapter_ids [B] int32 (0 = base, zeroed). The [B, in, r] / [B, r,
    out] gathers are tiny (rank << in/out) and stay fused by XLA.
    """
    a = ab["a"][adapter_ids]                     # [B, in, r]
    b = ab["b"][adapter_ids]                     # [B, r, out]
    xa = jnp.einsum("bti,bir->btr", x, a)
    delta = jnp.einsum("btr,bro->bto", xa, b)
    return base_out + delta.astype(base_out.dtype) * scaling
