"""BERT-family bidirectional text encoder: REAL embeddings for
/v1/embeddings (and the rerank/score endpoints built on it).

The causal chat model's mean-pooled hidden states are a *shape*
approximation of an embedding API, not an embedding model (causal
attention only mixes leftward; quality is unvalidated). This module is
the honest path: a sentence-transformers-style encoder (BERT post-LN,
bidirectional attention, mean pooling over valid tokens) served next to
the causal model when ``--embedding-model`` is set
(engine/config.py). The reference stack proxies /v1/embeddings to
engines that serve embedding models the same way
(reference: src/vllm_router/routers/main_router.py:87-117).

TPU-first structure mirrors models/llama.py: all L layers stacked on a
leading axis, one traced layer body under ``lax.scan``, matmuls in the
model dtype with fp32 LayerNorm/softmax. Bidirectional attention is one
dense [T, T] masked softmax — encoder inputs are short (<= 512) and
prefill-shaped, squarely MXU territory; no KV cache, nothing donated,
safe to dispatch from the server thread next to the engine loop.

HF parity is pinned against transformers BertModel in
tests/test_encoder.py (same harness as the causal families in
tests/test_model_numerics.py).
"""

import dataclasses
from typing import Any, Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclasses.dataclass
class EncoderConfig:
    name: str = "debug-encoder"
    vocab_size: int = 30522
    hidden_size: int = 384
    intermediate_size: int = 1536
    num_layers: int = 6
    num_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


ENCODER_PRESETS: Dict[str, EncoderConfig] = {
    # debug geometry (tests, --embedding-model debug-encoder)
    "debug-encoder": EncoderConfig(
        name="debug-encoder", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4,
        max_position_embeddings=128),
    # sentence-transformers/all-MiniLM-L6-v2 geometry
    "minilm-l6": EncoderConfig(
        name="minilm-l6", vocab_size=30522, hidden_size=384,
        intermediate_size=1536, num_layers=6, num_heads=12),
    # BAAI/bge-base-en-v1.5 / bert-base geometry
    "bert-base": EncoderConfig(
        name="bert-base", vocab_size=30522, hidden_size=768,
        intermediate_size=3072, num_layers=12, num_heads=12),
}


def get_encoder_config(name: str) -> EncoderConfig:
    if name not in ENCODER_PRESETS:
        raise ValueError(
            f"unknown encoder preset {name!r}; known: "
            f"{sorted(ENCODER_PRESETS)} (or pass a HF checkpoint dir)")
    return ENCODER_PRESETS[name]


def init_params(cfg: EncoderConfig, key: jax.Array) -> Params:
    """Random init, stacked-layer layout (layer axis leading)."""
    h, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    keys = iter(jax.random.split(key, 12))

    def w(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(
            cfg.dtype)

    def zeros(shape):
        return jnp.zeros(shape, cfg.dtype)

    def ones(shape):
        return jnp.ones(shape, cfg.dtype)

    return {
        "word_emb": w(next(keys), (cfg.vocab_size, h)),
        "pos_emb": w(next(keys), (cfg.max_position_embeddings, h)),
        "type_emb": w(next(keys), (cfg.type_vocab_size, h)),
        "emb_ln_w": ones((h,)), "emb_ln_b": zeros((h,)),
        "layers": {
            "q": w(next(keys), (L, h, h)), "q_b": zeros((L, h)),
            "k": w(next(keys), (L, h, h)), "k_b": zeros((L, h)),
            "v": w(next(keys), (L, h, h)), "v_b": zeros((L, h)),
            "o": w(next(keys), (L, h, h)), "o_b": zeros((L, h)),
            "attn_ln_w": ones((L, h)), "attn_ln_b": zeros((L, h)),
            "up": w(next(keys), (L, h, i)), "up_b": zeros((L, i)),
            "down": w(next(keys), (L, i, h)), "down_b": zeros((L, h)),
            "out_ln_w": ones((L, h)), "out_ln_b": zeros((L, h)),
        },
    }


def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


def encode_hidden(params: Params, cfg: EncoderConfig, tokens: jnp.ndarray,
                  lengths: jnp.ndarray) -> jnp.ndarray:
    """tokens [N, T] int32 (right-padded), lengths [N] -> final-layer
    hidden states [N, T, H] in cfg.dtype (padding rows are garbage the
    caller must mask). Shared body of encode() (mean-pooled embeddings)
    and token-level heads (e.g. the NER PII analyzer, router/pii.py)."""
    N, T = tokens.shape
    mask = jnp.arange(T)[None, :] < lengths[:, None]          # [N, T]
    x = (params["word_emb"][tokens]
         + params["pos_emb"][None, :T]
         + params["type_emb"][0][None, None])
    x = _layer_norm(x, params["emb_ln_w"], params["emb_ln_b"],
                    cfg.layer_norm_eps)
    nh, hd = cfg.num_heads, cfg.head_dim
    # padding keys are masked out of every softmax; padding queries
    # produce garbage rows the pooling mask drops
    bias = jnp.where(mask, 0.0, -1e30)[:, None, None, :]      # [N,1,1,T]

    def layer(x, lp):
        def lin(h, name):
            return h @ lp[name] + lp[name + "_b"]

        q = lin(x, "q").reshape(N, T, nh, hd)
        k = lin(x, "k").reshape(N, T, nh, hd)
        v = lin(x, "v").reshape(N, T, nh, hd)
        s = jnp.einsum("bthd,bshd->bhts", q, k,
                       preferred_element_type=jnp.float32)
        s = s * (hd ** -0.5) + bias
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhts,bshd->bthd", p, v).reshape(N, T, -1)
        x = _layer_norm(x + lin(attn, "o"), lp["attn_ln_w"],
                        lp["attn_ln_b"], cfg.layer_norm_eps)
        ff = lin(jax.nn.gelu(lin(x, "up"), approximate=False), "down")
        x = _layer_norm(x + ff, lp["out_ln_w"], lp["out_ln_b"],
                        cfg.layer_norm_eps)
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return x


def encode(params: Params, cfg: EncoderConfig, tokens: jnp.ndarray,
           lengths: jnp.ndarray) -> jnp.ndarray:
    """tokens [N, T] int32 (right-padded), lengths [N] ->
    mean-pooled embeddings fp32 [N, H] (sentence-transformers mean
    pooling: sum of valid hidden states / count)."""
    T = tokens.shape[1]
    mask = jnp.arange(T)[None, :] < lengths[:, None]          # [N, T]
    x = encode_hidden(params, cfg, tokens, lengths)
    pooled = jnp.sum(x.astype(jnp.float32) * mask[:, :, None], axis=1)
    return pooled / jnp.maximum(lengths, 1)[:, None]


def params_from_state_dict(cfg: EncoderConfig,
                           sd: Mapping[str, Any]) -> Params:
    """Map a HF BertModel state dict (optionally prefixed 'bert.') to
    the stacked layout. torch Linear weights are [out, in] ->
    transposed."""
    def np_(t):
        return t.detach().cpu().numpy() if hasattr(t, "detach") else \
            np.asarray(t)

    def get(name):
        for pfx in ("", "bert.", "model."):
            if pfx + name in sd:
                return np_(sd[pfx + name])
        raise KeyError(name)

    def stack(fmt, transpose=False):
        mats = [get(fmt.format(i)) for i in range(cfg.num_layers)]
        a = np.stack([m.T if transpose else m for m in mats])
        return jnp.asarray(a, cfg.dtype)

    e = "embeddings."
    l = "encoder.layer.{}."
    return {
        "word_emb": jnp.asarray(get(e + "word_embeddings.weight"),
                                cfg.dtype),
        "pos_emb": jnp.asarray(get(e + "position_embeddings.weight"),
                               cfg.dtype),
        "type_emb": jnp.asarray(get(e + "token_type_embeddings.weight"),
                                cfg.dtype),
        "emb_ln_w": jnp.asarray(get(e + "LayerNorm.weight"), cfg.dtype),
        "emb_ln_b": jnp.asarray(get(e + "LayerNorm.bias"), cfg.dtype),
        "layers": {
            "q": stack(l + "attention.self.query.weight", True),
            "q_b": stack(l + "attention.self.query.bias"),
            "k": stack(l + "attention.self.key.weight", True),
            "k_b": stack(l + "attention.self.key.bias"),
            "v": stack(l + "attention.self.value.weight", True),
            "v_b": stack(l + "attention.self.value.bias"),
            "o": stack(l + "attention.output.dense.weight", True),
            "o_b": stack(l + "attention.output.dense.bias"),
            "attn_ln_w": stack(l + "attention.output.LayerNorm.weight"),
            "attn_ln_b": stack(l + "attention.output.LayerNorm.bias"),
            "up": stack(l + "intermediate.dense.weight", True),
            "up_b": stack(l + "intermediate.dense.bias"),
            "down": stack(l + "output.dense.weight", True),
            "down_b": stack(l + "output.dense.bias"),
            "out_ln_w": stack(l + "output.LayerNorm.weight"),
            "out_ln_b": stack(l + "output.LayerNorm.bias"),
        },
    }


def load_checkpoint(cfg: EncoderConfig, path: str) -> Params:
    """Load a HF BertModel checkpoint dir (safetensors or torch .bin),
    reusing the causal loader's file handling."""
    from production_stack_tpu.models import hf_loader
    sd = hf_loader.read_state_dict(path)
    return params_from_state_dict(cfg, sd)


def config_from_hf_json(d: Mapping[str, Any],
                        name: str = "") -> EncoderConfig:
    """EncoderConfig from a HF BERT config.json dict."""
    return EncoderConfig(
        name=name or d.get("_name_or_path", "hf-encoder"),
        vocab_size=d["vocab_size"],
        hidden_size=d["hidden_size"],
        intermediate_size=d["intermediate_size"],
        num_layers=d["num_hidden_layers"],
        num_heads=d["num_attention_heads"],
        max_position_embeddings=d.get("max_position_embeddings", 512),
        type_vocab_size=d.get("type_vocab_size", 2),
        layer_norm_eps=d.get("layer_norm_eps", 1e-12),
    )
