"""Weight-only int8 quantization for the decoder's projection matmuls.

The reference passes --quantization down to vllm serve (reference:
helm/values.yaml modelSpec args / SURVEY.md §2.9 config surface); here
the engine implements the TPU-appropriate variant natively:

- **Symmetric per-output-channel int8** on every large matmul weight
  (q/k/v/o, dense gate/up/down, MoE expert stacks, embed, lm_head).
  Norm weights, biases, and the MoE router (tiny, accuracy-critical)
  stay in the model dtype.
- **Weight-only**: activations stay bf16. The matmul reads int8
  weights from HBM and converts in-register; XLA fuses the
  convert+scale into the dot epilogue. Decode is weight-bandwidth
  bound, so halving weight bytes approaches a 2x step-time headroom
  without the accuracy risk of activation quantization.
- A quantized leaf is ``{"w8": int8 [..., in, out], "scale": fp32
  [..., out]}`` in place of the raw array — same pytree *names*, so
  checkpoint loaders and sharding-by-name rules keep working
  (parallel/sharding.py maps the nested leaves' specs from the base
  rule: w8 keeps the weight's spec, scale keeps (leading..., out)).

Dequantized matmul identity: ``x @ (w8 * scale) == (x @ w8) * scale``
(scale broadcasts over the out axis), so projections compute
``(x @ w8.astype(dtype)) * scale`` — one fused multiply per output.
"""

from typing import Any, Dict

import jax.numpy as jnp

# layer-dict entries that stay un-quantized (small or accuracy-critical)
_SKIP_LAYER = ("attn_norm", "mlp_norm", "post_attn_norm", "post_mlp_norm",
               "q_bias", "k_bias", "v_bias", "router", "s_gate_w")


def quantize_tensor(w: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8 over the last axis.

    w [..., in, out] -> {"w8": int8 same shape, "scale": fp32 [..., out]}
    with per-channel scale = max|w| / 127 reduced over the `in` axis
    (leading axes — layer/expert stacks — keep independent channels).
    """
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2)               # [..., out]
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    w8 = jnp.clip(jnp.round(wf / scale[..., None, :]), -127, 127
                  ).astype(jnp.int8)
    return {"w8": w8, "scale": scale}


def quantize_embed(w: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Per-ROW int8 for the [V, H] embedding table: scale [V]. A row
    scale serves both roles — the token gather dequantizes the gathered
    rows, and the tied lm_head applies it per logit AFTER x @ w8.T."""
    wf = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-1), 1e-8) / 127.0
    w8 = jnp.clip(jnp.round(wf / scale[:, None]), -127, 127
                  ).astype(jnp.int8)
    return {"w8": w8, "scale": scale}


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "w8" in leaf


def dequant_matmul(x: jnp.ndarray, w: Any, dtype=None) -> jnp.ndarray:
    """x @ w for raw or quantized w, in x.dtype (or `dtype`)."""
    if not is_quantized(w):
        return x @ w
    dtype = dtype or x.dtype
    y = x @ w["w8"].astype(dtype)
    return y * w["scale"].astype(dtype)


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize a stacked-params pytree (models/llama.py layout) in the
    standard int8 recipe. Returns a new pytree; embed quantizes per
    row so the gather and tied-lm_head roles share one scale axis."""
    out: Dict[str, Any] = {"final_norm": params["final_norm"]}
    out["embed"] = quantize_embed(params["embed"])
    if "lm_head" in params:
        out["lm_head"] = quantize_tensor(params["lm_head"])
    layers: Dict[str, Any] = {}
    for name, w in params["layers"].items():
        layers[name] = (w if name in _SKIP_LAYER
                        else quantize_tensor(w))
    out["layers"] = layers
    return out


def dequant_rows(w: Any, rows: jnp.ndarray, dtype) -> jnp.ndarray:
    """Gather rows of a (possibly quantized) [V, H] table: the embedding
    lookup path (per-row scale from quantize_embed)."""
    if not is_quantized(w):
        return w[rows].astype(dtype)
    return (w["w8"][rows].astype(dtype)
            * w["scale"][rows].astype(dtype)[..., None])
