"""OpenAI-compatible API protocol models (pydantic v2).

Shared by the engine server and the router. Extra fields are tolerated
everywhere (parity with the reference's extra-field-tolerant
OpenAIBaseModel, reference: src/vllm_router/protocols.py) so newer client
SDKs never break the stack.
"""

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field


class OpenAIBase(BaseModel):
    model_config = ConfigDict(extra="allow")


def _gen_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


def _now() -> int:
    return int(time.time())


# ---------------------------------------------------------------- requests

class CompletionRequest(OpenAIBase):
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]] = ""
    max_tokens: Optional[int] = 16
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0                      # vLLM extension
    n: int = 1
    stream: bool = False
    stream_options: Optional["StreamOptions"] = None
    stop: Optional[Union[str, List[str]]] = None
    stop_token_ids: Optional[List[int]] = None  # vLLM extension
    ignore_eos: bool = False            # vLLM extension
    echo: bool = False
    logprobs: Optional[int] = None      # legacy: N requests logprobs
    seed: Optional[int] = None
    # vLLM guided-decoding extensions (engine/guided.py)
    guided_regex: Optional[str] = None
    guided_choice: Optional[List[str]] = None
    guided_json: Optional[Union[str, dict]] = None
    # OpenAI structured outputs: {"type": "json_schema", "json_schema":
    # {...}} maps onto guided_json; "json_object" is rejected (DFA)
    response_format: Optional[Dict[str, Any]] = None
    # OpenAI logit shaping + vLLM extensions (engine/sampler.py)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0    # vLLM extension (HF semantics)
    min_p: float = 0.0                 # vLLM extension
    min_tokens: int = 0                # vLLM extension
    priority: int = 0                  # vLLM extension (lower = sooner)
    logit_bias: Optional[Dict[str, float]] = None
    user: Optional[str] = None


class ChatMessage(OpenAIBase):
    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = ""


class StreamOptions(OpenAIBase):
    include_usage: bool = False


class ChatCompletionRequest(OpenAIBase):
    model: str
    messages: List[ChatMessage]
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    n: int = 1
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    stop: Optional[Union[str, List[str]]] = None
    stop_token_ids: Optional[List[int]] = None
    ignore_eos: bool = False
    logprobs: Optional[bool] = False
    top_logprobs: Optional[int] = None
    seed: Optional[int] = None
    # vLLM guided-decoding extensions (engine/guided.py)
    guided_regex: Optional[str] = None
    guided_choice: Optional[List[str]] = None
    guided_json: Optional[Union[str, dict]] = None
    # OpenAI structured outputs: {"type": "json_schema", "json_schema":
    # {...}} maps onto guided_json; "json_object" is rejected (DFA)
    response_format: Optional[Dict[str, Any]] = None
    # OpenAI logit shaping + vLLM extensions (engine/sampler.py)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0    # vLLM extension (HF semantics)
    min_p: float = 0.0                 # vLLM extension
    min_tokens: int = 0                # vLLM extension
    priority: int = 0                  # vLLM extension (lower = sooner)
    logit_bias: Optional[Dict[str, float]] = None
    user: Optional[str] = None


# ---------------------------------------------------------------- responses

class UsageInfo(OpenAIBase):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class CompletionLogprobs(OpenAIBase):
    """Legacy completions logprobs block. logprobs=N returns the N
    highest-probability alternatives per position, computed on-device
    next to the chosen token's logprob. Both report the PRE-temperature,
    POST-shaping distribution: for requests without penalties/
    logit_bias/guided constraints that is the raw model distribution;
    shaped requests report the distribution they were actually decoded
    from (engine/runner.py). Paths without alternatives fall back to
    the chosen token's entry."""
    tokens: List[str] = Field(default_factory=list)
    token_logprobs: List[Optional[float]] = Field(default_factory=list)
    top_logprobs: Optional[List[Optional[Dict[str, float]]]] = None
    text_offset: Optional[List[int]] = None


class CompletionChoice(OpenAIBase):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[CompletionLogprobs] = None


class CompletionResponse(OpenAIBase):
    id: str = Field(default_factory=lambda: _gen_id("cmpl"))
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=_now)
    model: str = ""
    choices: List[CompletionChoice] = Field(default_factory=list)
    usage: UsageInfo = Field(default_factory=UsageInfo)


class ChatChoiceMessage(OpenAIBase):
    role: str = "assistant"
    content: Optional[str] = None


class ChatLogprobTop(OpenAIBase):
    token: str = ""
    logprob: float = 0.0
    bytes: Optional[List[int]] = None


class ChatLogprobToken(OpenAIBase):
    token: str = ""
    logprob: float = 0.0
    bytes: Optional[List[int]] = None
    top_logprobs: List[ChatLogprobTop] = Field(default_factory=list)


class ChatLogprobs(OpenAIBase):
    content: Optional[List[ChatLogprobToken]] = None


class ChatCompletionChoice(OpenAIBase):
    index: int = 0
    message: ChatChoiceMessage = Field(default_factory=ChatChoiceMessage)
    finish_reason: Optional[str] = None
    logprobs: Optional[ChatLogprobs] = None


class ChatCompletionResponse(OpenAIBase):
    id: str = Field(default_factory=lambda: _gen_id("chatcmpl"))
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=_now)
    model: str = ""
    choices: List[ChatCompletionChoice] = Field(default_factory=list)
    usage: UsageInfo = Field(default_factory=UsageInfo)


class DeltaMessage(OpenAIBase):
    role: Optional[str] = None
    content: Optional[str] = None


class ChatCompletionChunkChoice(OpenAIBase):
    index: int = 0
    delta: DeltaMessage = Field(default_factory=DeltaMessage)
    finish_reason: Optional[str] = None
    logprobs: Optional[ChatLogprobs] = None


class ChatCompletionChunk(OpenAIBase):
    id: str = ""
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=_now)
    model: str = ""
    choices: List[ChatCompletionChunkChoice] = Field(default_factory=list)
    # present only on the final chunk when stream_options.include_usage
    usage: Optional[UsageInfo] = None


class CompletionChunkChoice(OpenAIBase):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[CompletionLogprobs] = None


class CompletionChunk(OpenAIBase):
    id: str = ""
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=_now)
    model: str = ""
    choices: List[CompletionChunkChoice] = Field(default_factory=list)
    # present only on the final chunk when stream_options.include_usage
    usage: Optional[UsageInfo] = None


# ---------------------------------------------------------------- models API

class ModelCard(OpenAIBase):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=_now)
    owned_by: str = "production-stack-tpu"
    root: Optional[str] = None
    parent: Optional[str] = None


class ModelList(OpenAIBase):
    object: Literal["list"] = "list"
    data: List[ModelCard] = Field(default_factory=list)


class ErrorInfo(OpenAIBase):
    message: str
    type: str = "invalid_request_error"
    code: Optional[int] = None


class ErrorResponse(OpenAIBase):
    error: ErrorInfo
