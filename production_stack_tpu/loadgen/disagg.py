"""disagg mode: disaggregated prefill/decode, measured end to end.

The closed loop for ROADMAP item 2 / BASELINE config 5 (ISSUE 7). The
orchestrator launches the SPLIT topology — a shared TPKV cache server, P
kv_producer prefill engines, D kv_consumer decode engines, and the real
router wired with ``--prefill-backends`` — then the AGGREGATED baseline
at **equal engine count** (P+D plain engines, no pools), and drives the
identical mixed workload at both:

- **chat class**: short prompts, long streamed decodes — the traffic
  whose inter-token latency (ITL) the split is supposed to protect;
- **rag class**: long unique prompts, short decodes — the head-of-line
  blocker. In the aggregated fleet its prefill paces on the same
  engines that are mid-decode (the fake's
  ``--prefill-decode-interference`` models the fused-step contention a
  real engine shows); in the split fleet prefill runs on the producer
  pool and the decode pool sees only the uncached chunk remainder.

Mid-run the rig SIGKILLs a prefill pod and restarts it (the chaos
extension to the split topology): the degradation contract says decode
recomputes — **zero client-visible errors** — while the router's
fallback counters tick.

``disagg_violations`` is the pass/fail contract the CLI enforces
(exit 1): any raw 5xx or transport error in either phase, chat ITL p99
not improving by ``min_itl_improvement`` split-vs-aggregated, a split
decode pool that never consumed tier KV, producers that never published
mid-prefill, or a scheduled prefill-kill that didn't happen. Run with
``--no-split`` both phases are aggregated and the ITL gate must fail —
the committed anti-vacuity check.

Engines: the fake (``--kv-role producer/consumer`` simulation over the
real TPKV tier protocol — measures the router orchestration + transfer
data path with deterministic pacing) or real engines
(``--kv-transfer-config`` roles; ITL then includes real model compute).
"""

import asyncio
import dataclasses
import json
import random
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_cache_server,
                                                       launch_engine,
                                                       launch_router,
                                                       wait_cache_ready,
                                                       wait_healthy)
from production_stack_tpu.loadgen.report import percentile
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

# real engines run under orchestrator.ENGINE_ARGS (--max-model-len
# 1024, char-level debug-tiny tokenizer): storm prompts above this are
# clamped so the advertised real-engine recipe can't 400 out of the box
REAL_ENGINE_PROMPT_CHARS = 700


def clamp_storm_for_real_engine(storm_kwargs: Dict) -> Dict:
    """launch_engine pins real engines to --max-model-len 1024 and the
    server 400-rejects prompts at or over it; debug-tiny tokenizes per
    char, so the fake-mode rag default (2400 chars) would error every
    rag request out of the gate. 700 leaves decode + chat-history
    headroom inside the window (the slow-test shape). Mutates and
    returns ``storm_kwargs``."""
    for key in ("chat_prompt_chars", "rag_prompt_chars"):
        if storm_kwargs[key] > REAL_ENGINE_PROMPT_CHARS:
            logger.warning(
                "disagg: clamping %s %d -> %d to fit the real-engine "
                "--max-model-len window", key, storm_kwargs[key],
                REAL_ENGINE_PROMPT_CHARS)
            storm_kwargs[key] = REAL_ENGINE_PROMPT_CHARS
    return storm_kwargs

CHAT_PATH = "/v1/chat/completions"

# real-engine geometry (debug-tiny character-level tokenizer: chars ~
# tokens; the orchestrator's 1024-token max-model-len bounds prompts)
REAL_KV_CHUNK_TOKENS = 32


@dataclasses.dataclass
class _ClassStats:
    """Aggregated outcomes for one traffic class in one phase."""

    launched: int = 0
    finished: int = 0
    errors: int = 0
    raw_5xx: int = 0
    transport_errors: int = 0
    error_samples: List[str] = dataclasses.field(default_factory=list)
    ttft_s: List[float] = dataclasses.field(default_factory=list)
    itl_s: List[float] = dataclasses.field(default_factory=list)

    def note_error(self, sample: str) -> None:
        self.errors += 1
        if len(self.error_samples) < 8:
            self.error_samples.append(sample)

    def summary(self) -> Dict:
        def pct(vals, p):
            return round(percentile(vals, p) * 1e3, 2) if vals else None
        return {
            "launched": self.launched,
            "finished": self.finished,
            "errors": self.errors,
            "raw_5xx": self.raw_5xx,
            "transport_errors": self.transport_errors,
            "error_samples": self.error_samples or None,
            "ttft_ms": {"p50": pct(self.ttft_s, 50),
                        "p99": pct(self.ttft_s, 99)},
            "itl_ms": {"p50": pct(self.itl_s, 50),
                       "p90": pct(self.itl_s, 90),
                       "p99": pct(self.itl_s, 99)},
        }


def _words(rng: random.Random, n_chars: int) -> str:
    out, size = [], 0
    while size < n_chars:
        w = "w%04x" % rng.randrange(1 << 16)
        out.append(w)
        size += len(w) + 1
    return " ".join(out)[:n_chars]


async def _storm(router_url: str, model: str, *, duration_s: float,
                 chat_users: int, rag_users: int, chat_prompt_chars: int,
                 chat_tokens: int, rag_prompt_chars: int, rag_tokens: int,
                 seed: int, request_timeout_s: float = 120.0
                 ) -> Dict[str, _ClassStats]:
    """Closed-loop mixed storm: ``chat_users`` + ``rag_users``
    concurrent users looping for ``duration_s``. Prompts are unique per
    request (prefixed from the FIRST chars) so neither phase gets
    cross-request prefix reuse — the A/B isolates the split itself, not
    caching luck."""
    stats = {"chat": _ClassStats(), "rag": _ClassStats()}
    timeout = aiohttp.ClientTimeout(total=request_timeout_s)
    end_at = time.monotonic() + duration_s

    async def one_request(http, cls: str, rng: random.Random,
                          uid: str) -> None:
        st = stats[cls]
        if cls == "chat":
            prompt = f"chat {uid} " + _words(rng, chat_prompt_chars)
            max_tokens = chat_tokens
        else:
            prompt = f"rag {uid} " + _words(rng, rag_prompt_chars)
            max_tokens = rag_tokens
        body = json.dumps({
            "model": model, "stream": True, "max_tokens": max_tokens,
            "messages": [{"role": "user", "content": prompt}]}).encode()
        st.launched += 1
        t0 = time.monotonic()
        first_at = last_at = None
        chunks = 0
        try:
            async with http.post(
                    f"{router_url}{CHAT_PATH}", data=body,
                    headers={"Content-Type": "application/json"},
                    timeout=timeout) as resp:
                if resp.status != 200:
                    if resp.status >= 500:
                        st.raw_5xx += 1
                    st.note_error(f"HTTP {resp.status}: "
                                  f"{(await resp.text())[:120]}")
                    return
                async for raw_line in resp.content:
                    line = raw_line.strip()
                    if not line.startswith(b"data:"):
                        continue
                    if line[5:].strip() == b"[DONE]":
                        continue
                    now = time.monotonic()
                    if first_at is None:
                        first_at = now
                    last_at = now
                    chunks += 1
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            st.transport_errors += 1
            st.note_error(f"{type(e).__name__}: {e}")
            return
        if first_at is None:
            st.note_error("stream produced no data frames")
            return
        st.finished += 1
        st.ttft_s.append(first_at - t0)
        if chunks > 1:
            st.itl_s.append((last_at - first_at) / (chunks - 1))

    async def user(cls: str, i: int) -> None:
        rng = random.Random(seed * 104729 + (0 if cls == "chat"
                                             else 1 << 20) + i)
        k = 0
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as http:
            while time.monotonic() < end_at:
                await one_request(http, cls, rng, f"{i}-{k}")
                k += 1

    await asyncio.gather(
        *[user("chat", i) for i in range(chat_users)],
        *[user("rag", i) for i in range(rag_users)])
    return stats


async def _scrape_json(url: str) -> Dict:
    try:
        async with aiohttp.ClientSession() as http:
            async with http.get(
                    url, timeout=aiohttp.ClientTimeout(total=5)) as r:
                return await r.json()
    except (aiohttp.ClientError, ConnectionError, OSError,
            asyncio.TimeoutError, ValueError):
        return {}


async def _kill_prefill_pod(procs: List[Proc], engine: str,
                            engine_args: List[str], *, at_s: float,
                            downtime_s: float, platform: str,
                            log_dir: str, record: Dict,
                            startup_timeout_s: float) -> None:
    """SIGKILL the first prefill pod mid-run, restart it on the same
    port after ``downtime_s`` (the chaos extension: a dead prefill pod
    must cost recompute, never a client-visible error)."""
    await asyncio.sleep(at_s)
    victim = procs[0]
    port = int(victim.url.rsplit(":", 1)[1])
    # reap and respawn off the event loop: the storm's inter-chunk
    # timestamps are being sampled on this loop, and a blocking wait()
    # or subprocess spawn would land its stall in the measured split
    # phase's ITL (the aggregated baseline never pays it)
    victim.popen.kill()
    await asyncio.to_thread(victim.popen.wait)
    record["kills"] += 1
    logger.info("disagg chaos: SIGKILLed prefill pod %s", victim.url)
    await asyncio.sleep(downtime_s)

    # the registration runs in the worker thread: a cancel that lands
    # while the spawn is in flight must not drop the Proc handle, or
    # the phase's cleanup never sees (and never stops) the new engine
    def _respawn() -> None:
        procs[0] = launch_engine(engine, port, log_dir=log_dir,
                                 platform=platform,
                                 extra_args=engine_args)

    spawn = asyncio.ensure_future(asyncio.to_thread(_respawn))
    try:
        await asyncio.shield(spawn)
    except asyncio.CancelledError:
        await spawn                  # join the thread; procs[0] is set
        raise
    try:
        await wait_healthy(procs[0].url, startup_timeout_s)
        record["restarts"] += 1
        logger.info("disagg chaos: prefill pod %s restarted",
                    procs[0].url)
    except TimeoutError:
        logger.warning("disagg chaos: prefill pod did not come back")


async def _run_phase(*, split: bool, prefill_engines: int,
                     decode_engines: int, engine: str, model: str,
                     tokens_per_s: float, prefill_ms_per_char: float,
                     interference: float, kv_chunk_chars: int,
                     headstart_s: float, min_prompt_chars: int,
                     routing: str, storm_kwargs: Dict,
                     prefill_kill: bool, kill_downtime_s: float,
                     duration_s: float, platform: str, log_dir: str,
                     startup_timeout_s: float) -> Dict:
    procs: List[Proc] = []
    prefill_procs: List[Proc] = []
    kill_task: Optional[asyncio.Task] = None
    total = prefill_engines + decode_engines
    fake = engine == "fake"
    prefill_args: List[str] = []
    try:
        cache_url = None
        if split:
            cache = launch_cache_server(free_port(), log_dir=log_dir)
            procs.append(cache)
            await wait_cache_ready(cache.url)
            cache_url = cache.url

        def fake_args(role: Optional[str]) -> List[str]:
            args = ["--num-tokens", str(max(
                        storm_kwargs["chat_tokens"],
                        storm_kwargs["rag_tokens"])),
                    "--tokens-per-s", str(tokens_per_s),
                    "--prefill-ms-per-char", str(prefill_ms_per_char),
                    "--prefill-decode-interference", str(interference)]
            if role is not None:
                args += ["--kv-role", role,
                         "--kv-remote-url", cache_url,
                         "--kv-chunk-chars", str(kv_chunk_chars)]
            return args

        def real_args(role: Optional[str]) -> List[str]:
            if role is None:
                return []
            return ["--kv-transfer-config",
                    json.dumps({"kv_role": role,
                                "chunk_size": REAL_KV_CHUNK_TOKENS,
                                "remote_url": cache_url})]

        mk_args = fake_args if fake else real_args
        if split:
            prefill_args = mk_args("kv_producer")
            prefill_procs = [launch_engine(engine, free_port(),
                                           log_dir=log_dir,
                                           platform=platform,
                                           extra_args=prefill_args)
                             for _ in range(prefill_engines)]
            decode_procs = [launch_engine(engine, free_port(),
                                          log_dir=log_dir,
                                          platform=platform,
                                          extra_args=mk_args(
                                              "kv_consumer"))
                            for _ in range(decode_engines)]
        else:
            prefill_procs = []
            decode_procs = [launch_engine(engine, free_port(),
                                          log_dir=log_dir,
                                          platform=platform,
                                          extra_args=mk_args(None))
                            for _ in range(total)]
        procs.extend(prefill_procs)
        procs.extend(decode_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in prefill_procs + decode_procs])

        router_extra = ["--engine-stats-interval", "2"]
        if split:
            router_extra += [
                "--prefill-backends",
                ",".join(e.url for e in prefill_procs),
                "--prefill-models",
                ",".join([model] * prefill_engines),
                "--prefill-headstart", str(headstart_s),
                "--disagg-min-prompt-chars", str(min_prompt_chars),
                "--prefill-breaker-cooldown", "2",
            ]
        router = launch_router([e.url for e in decode_procs], model,
                               free_port(), routing=routing,
                               log_dir=log_dir, extra_args=router_extra)
        procs.append(router)
        await wait_healthy(router.url, 60.0,
                           require_endpoints=len(decode_procs))

        chaos_record = {"kills": 0, "restarts": 0}
        if split and prefill_kill:
            kill_task = asyncio.ensure_future(_kill_prefill_pod(
                prefill_procs, engine, prefill_args,
                at_s=duration_s * 0.4, downtime_s=kill_downtime_s,
                platform=platform, log_dir=log_dir,
                record=chaos_record,
                startup_timeout_s=startup_timeout_s))

        t0 = time.monotonic()
        stats = await _storm(router.url, model,
                             duration_s=duration_s, **storm_kwargs)
        elapsed = time.monotonic() - t0
        # settle chaos before scraping: a respawn still in flight would
        # be scraped half-started (and the finally re-joins on the
        # failure path, where this line never ran)
        if kill_task is not None:
            kill_task.cancel()
            await asyncio.gather(kill_task, return_exceptions=True)

        engine_kv = {}
        for p in prefill_procs + decode_procs:
            data = await _scrape_json(f"{p.url}/load")
            kv = data.get("kv_cache") or {}
            engine_kv[p.url] = {
                "pool": "prefill" if p in prefill_procs else "decode",
                "role": kv.get("role"),
                "hit_tokens": kv.get("hit_tokens", 0),
                "query_tokens": kv.get("query_tokens", 0),
                "published_chunks": kv.get("published_chunks", 0),
                "progress_published_chunks": kv.get(
                    "progress_published_chunks", 0),
            }
        router_health = await _scrape_json(f"{router.url}/health")
    finally:
        # a failing storm must not leak the kill task: left pending it
        # would wake after its downtime sleep and respawn an engine
        # nobody stops
        if kill_task is not None:
            kill_task.cancel()
            await asyncio.gather(kill_task, return_exceptions=True)
        # a chaos restart replaced an entry in prefill_procs; the stale
        # handle in procs is already dead, the fresh one must die too
        seen = {id(p) for p in procs}
        _stop(procs + [p for p in prefill_procs
                       if id(p) not in seen])

    return {
        "split": split,
        "duration_s": round(elapsed, 1),
        "chat": stats["chat"].summary(),
        "rag": stats["rag"].summary(),
        "engine_kv": engine_kv,
        "prefill_pool": router_health.get("prefill_pool"),
        "chaos": chaos_record if split else None,
    }


async def run_disagg(*, prefill_engines: int = 2,
                     decode_engines: int = 2,
                     engine: str = "fake",
                     chat_users: int = 8, rag_users: int = 4,
                     duration_s: float = 30.0,
                     chat_prompt_chars: int = 96,
                     chat_tokens: int = 24,
                     rag_prompt_chars: int = 2400,
                     rag_tokens: int = 4,
                     tokens_per_s: float = 40.0,
                     prefill_ms_per_char: float = 0.4,
                     interference: float = 1.5,
                     kv_chunk_chars: int = 64,
                     headstart_s: float = 3.0,
                     min_prompt_chars: int = 512,
                     routing: str = "least_loaded",
                     seed: int = 0,
                     no_split: bool = False,
                     prefill_kill: bool = True,
                     kill_downtime_s: float = 3.0,
                     platform: str = "cpu",
                     log_dir: str = "loadgen-logs",
                     startup_timeout_s: float = 420.0) -> Dict:
    """Run the split phase (or a second aggregated phase with
    ``no_split`` — the anti-vacuity mode) plus the aggregated
    equal-hardware baseline; return the DISAGG record."""
    model = "fake-model" if engine == "fake" else engine
    storm_kwargs = dict(chat_users=chat_users, rag_users=rag_users,
                        chat_prompt_chars=chat_prompt_chars,
                        chat_tokens=chat_tokens,
                        rag_prompt_chars=rag_prompt_chars,
                        rag_tokens=rag_tokens, seed=seed)
    if engine != "fake":
        clamp_storm_for_real_engine(storm_kwargs)
    phase_kwargs = dict(prefill_engines=prefill_engines,
                        decode_engines=decode_engines, engine=engine,
                        model=model, tokens_per_s=tokens_per_s,
                        prefill_ms_per_char=prefill_ms_per_char,
                        interference=interference,
                        kv_chunk_chars=kv_chunk_chars,
                        headstart_s=headstart_s,
                        min_prompt_chars=min_prompt_chars,
                        routing=routing, storm_kwargs=storm_kwargs,
                        prefill_kill=prefill_kill,
                        kill_downtime_s=kill_downtime_s,
                        duration_s=duration_s, platform=platform,
                        log_dir=log_dir,
                        startup_timeout_s=startup_timeout_s)
    logger.info("disagg: %s phase — %d prefill + %d decode %s engines, "
                "%d chat + %d rag users, %.0fs",
                "aggregated (--no-split)" if no_split else "split",
                prefill_engines, decode_engines, engine, chat_users,
                rag_users, duration_s)
    main = await _run_phase(split=not no_split, **phase_kwargs)
    logger.info("disagg: measuring the aggregated equal-hardware "
                "baseline (%d engines, no pools)...",
                prefill_engines + decode_engines)
    baseline = await _run_phase(split=False, **{
        **phase_kwargs, "prefill_kill": False})

    main_itl = main["chat"]["itl_ms"]["p99"]
    base_itl = baseline["chat"]["itl_ms"]["p99"]
    improvement = None
    if main_itl and base_itl:
        improvement = round(100.0 * (1.0 - main_itl / base_itl), 1)
    return {
        "metric": "disaggregated prefill/decode: chat ITL p99 under a "
                  "mixed long-prefill/short-decode storm, split "
                  "topology vs aggregated serving at equal engine "
                  "count (prefill-pod SIGKILL mid-run)",
        "value": improvement,
        "unit": "% chat ITL p99 improvement",
        "platform": platform,
        "detail": {
            "engine": engine,
            "prefill_engines": prefill_engines,
            "decode_engines": decode_engines,
            "chat_users": chat_users, "rag_users": rag_users,
            "duration_s": duration_s,
            "chat_prompt_chars": chat_prompt_chars,
            "chat_tokens": chat_tokens,
            "rag_prompt_chars": rag_prompt_chars,
            "rag_tokens": rag_tokens,
            "tokens_per_s": tokens_per_s if engine == "fake" else None,
            "prefill_ms_per_char": prefill_ms_per_char
            if engine == "fake" else None,
            "interference": interference if engine == "fake" else None,
            "kv_chunk": kv_chunk_chars if engine == "fake"
            else REAL_KV_CHUNK_TOKENS,
            "headstart_s": headstart_s,
            "min_prompt_chars": min_prompt_chars,
            "routing": routing, "seed": seed, "no_split": no_split,
            "prefill_kill": prefill_kill and not no_split,
            "split_phase": main,
            "aggregated_baseline": baseline,
            "chat_itl_p99_ms": {"split": main_itl,
                                "aggregated": base_itl,
                                "improvement_pct": improvement},
        },
    }


def disagg_violations(record: Dict,
                      min_itl_improvement: Optional[float] = 0.1
                      ) -> List[str]:
    """The disagg pass/fail contract (CLI exits 1 on any violation).

    ``min_itl_improvement=None`` skips the ITL gate (errors, KV-flow
    evidence, and the kill contract still apply) — for configurations
    whose ITL is noise-dominated, e.g. real debug-tiny engines on CPU,
    where the committed fake-engine A/B holds the latency claim and
    the real-engine run proves the data path."""
    d = record["detail"]
    main, base = d["split_phase"], d["aggregated_baseline"]
    out: List[str] = []
    for phase_name, phase in (("split", main), ("aggregated", base)):
        for cls in ("chat", "rag"):
            c = phase[cls]
            if c["raw_5xx"]:
                out.append(f"{phase_name}/{cls}: {c['raw_5xx']} raw 5xx "
                           f"(first: {(c['error_samples'] or ['?'])[0]})")
            if c["errors"] - c["raw_5xx"]:
                out.append(
                    f"{phase_name}/{cls}: "
                    f"{c['errors'] - c['raw_5xx']} non-5xx errors "
                    f"(first: {(c['error_samples'] or ['?'])[0]})")
            if not c["finished"]:
                out.append(f"{phase_name}/{cls}: nothing finished")
    itl = d["chat_itl_p99_ms"]
    if min_itl_improvement is None:
        pass
    elif itl["split"] is None or itl["aggregated"] is None:
        out.append("chat ITL comparison missing (no multi-chunk "
                   "streams measured on one side)")
    elif itl["split"] > itl["aggregated"] * (1.0 - min_itl_improvement):
        out.append(
            f"chat ITL p99 did not improve by "
            f"{min_itl_improvement:.0%}: split {itl['split']:.1f}ms vs "
            f"aggregated {itl['aggregated']:.1f}ms "
            f"({(itl['improvement_pct'] or 0):.1f}%)")
    if not d["no_split"]:
        decode_hits = sum(kv.get("hit_tokens", 0)
                          for kv in main["engine_kv"].values()
                          if kv["pool"] == "decode")
        if not decode_hits:
            out.append("split decode pool consumed zero tier KV — the "
                       "prefill handoff never happened")
        progress = sum(kv.get("progress_published_chunks", 0)
                       for kv in main["engine_kv"].values()
                       if kv["pool"] == "prefill")
        if not progress:
            out.append("prefill pool published zero chunks mid-prefill "
                       "— progressive publish is not overlapping")
        if d.get("prefill_kill") and \
                (main.get("chaos") or {}).get("kills", 0) < 1:
            # a scheduled kill that never fired would leave the
            # degradation contract unmeasured
            out.append("prefill-pod kill never fired — the degradation "
                       "contract went unmeasured")
    return out
