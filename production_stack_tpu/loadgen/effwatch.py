"""Effwatch: storm an engine and audit its efficiency accounting.

The closed loop for the engine-efficiency telemetry layer
(engine/efficiency.py): the roofline push (ROADMAP item 2) is about to
make optimization decisions off the real/pad/dead token-step split, the
MBU gauge, and the compile counters — so those numbers must first be
proven to reconcile with ground truth an independent observer can
measure. The rig launches ONE engine (a real ``debug-tiny`` process or
a fake), drives a warmup storm (so every executable the steady shape
needs is compiled), scrapes the ``/load`` ``perf`` block immediately
around a steady measured storm, and gates on:

- **sum-to-1**: the real+pad+dead token-step deltas must equal the
  separately accumulated ``token_steps_total`` delta within
  ``--sum-tolerance`` (default 2%). For the real engine this is a
  *plumbing* check spanning every adder, the ``/load``
  serialization, and the scrape-delta math (the engine derives dead
  by subtraction, so it cannot catch a misclassification by itself —
  that is the reconciliation gate's job); the fake's ``--fake-skew``
  knob proves the gate can fail;
- **reconciliation**: accounted decode tokens/s (the ``real`` delta
  over the scrape window) must match CLIENT-measured completion
  tokens/s within ``--rate-tolerance`` (default 10%). The client
  counts what it actually received (the stream's ``include_usage``
  tail, content chunks as fallback), minus one token per request —
  the first output token comes from the prefill dispatch, which the
  decode accounting correctly excludes;
- **steady-window compile silence**: zero XLA compile events may land
  between the two scrapes — post-warmup steady serving that still
  compiles means the warmup story is broken;
- zero client-visible errors.

``--anti-vacuity`` deliberately mis-sizes the accounting window (the
"before" scrape is taken before the warmup storm instead of after it),
so the accounted-token delta covers warmup + steady while the client
only measured steady — the reconciliation gate MUST fail, proving the
gates can fail at all.

Committed records are ``EFF_*.json``; reproduction one-liners live in
docs/benchmarks.md "Engine efficiency: effwatch".
"""

import asyncio
import json
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (_stop, free_port,
                                                       launch_engine,
                                                       wait_healthy)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"


class _StormCounters:
    def __init__(self):
        self.requests = 0
        self.tokens = 0           # completion tokens the client received
        self.errors = 0
        self.samples: List[str] = []

    def sample(self, text: str) -> None:
        if len(self.samples) < 6:
            self.samples.append(text[:160])


async def _one_stream(session: aiohttp.ClientSession, url: str,
                      model: str, prompt: str, num_tokens: int,
                      c: _StormCounters) -> None:
    """One streaming chat request; counts completion tokens the client
    actually received (usage tail when the server sends one, content
    chunks otherwise — the fake has no usage tail)."""
    payload = {
        "model": model, "stream": True,
        "stream_options": {"include_usage": True},
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": num_tokens, "temperature": 0.0,
        "ignore_eos": True,
    }
    chunks = 0
    usage_tokens = None
    try:
        async with session.post(
                f"{url}{CHAT_PATH}", json=payload,
                timeout=aiohttp.ClientTimeout(total=120)) as resp:
            if resp.status != 200:
                c.errors += 1
                c.sample(f"HTTP {resp.status}: "
                         f"{(await resp.read())[:120]!r}")
                return
            async for raw in resp.content:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data == "[DONE]":
                    break
                try:
                    obj = json.loads(data)
                except ValueError:
                    continue
                usage = obj.get("usage")
                if usage and usage.get("completion_tokens") is not None:
                    usage_tokens = int(usage["completion_tokens"])
                for choice in obj.get("choices") or []:
                    if (choice.get("delta") or {}).get("content"):
                        chunks += 1
    except (aiohttp.ClientError, ConnectionError, OSError,
            asyncio.TimeoutError) as e:
        c.errors += 1
        c.sample(f"{type(e).__name__}: {e}")
        return
    c.requests += 1
    c.tokens += usage_tokens if usage_tokens is not None else chunks


async def _storm(url: str, model: str, *, users: int, duration_s: float,
                 num_tokens: int, tag: str,
                 stagger_s: float = 0.0,
                 mixed_tokens: Optional[List[int]] = None,
                 prompt_chars: int = 0) -> _StormCounters:
    """Closed-loop storm: ``users`` workers re-issuing streams until
    the window elapses; in-flight requests run to completion so every
    received token lies inside the surrounding scrape window.

    The churny shape for the window-adaptation A/B: ``stagger_s``
    offsets each worker's first request (staggered arrivals — batch
    composition keeps changing instead of settling once), and
    ``mixed_tokens`` cycles per-request ``max_tokens`` through the
    given list offset by worker id (mixed short/long outputs — rows
    finish at different steps, the finished-tail regime)."""
    c = _StormCounters()
    t_end = time.monotonic() + duration_s

    async def worker(wid: int):
        i = 0
        if stagger_s > 0:
            await asyncio.sleep(stagger_s * wid)
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as session:
            while time.monotonic() < t_end:
                toks = (mixed_tokens[(wid + i) % len(mixed_tokens)]
                        if mixed_tokens else num_tokens)
                i += 1
                prompt = f"{tag} worker {wid} round {i}"
                if prompt_chars and len(prompt) < prompt_chars:
                    # pad the prompt to a target length (longer live
                    # context -> the per-row KV read dominates the
                    # dispatch's fixed overhead; debug-tiny tokenizes
                    # per character)
                    prompt += " " + "ctx " * ((prompt_chars
                                               - len(prompt)) // 4 + 1)
                    prompt = prompt[:prompt_chars]
                await _one_stream(session, url, model, prompt,
                                  toks, c)

    await asyncio.gather(*(worker(w) for w in range(users)))
    return c


async def _scrape_perf(url: str) -> Dict:
    async with aiohttp.ClientSession() as session:
        async with session.get(
                f"{url}/load",
                timeout=aiohttp.ClientTimeout(total=10)) as r:
            r.raise_for_status()
            data = await r.json()
    return data.get("perf") or {}


async def _scrape_debug_perf(url: str) -> Optional[Dict]:
    """Best-effort /debug/perf grab for the committed record (the fake
    engine serves no /debug/perf — absence is not a failure)."""
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                    f"{url}/debug/perf?limit=12",
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                if r.status != 200:
                    return None
                return await r.json()
    except (aiohttp.ClientError, ConnectionError, OSError,
            asyncio.TimeoutError):
        return None


def _steps(perf: Dict) -> Dict:
    return perf.get("token_steps") or {}


def effwatch_violations(record: Dict,
                        sum_tolerance: float = 0.02,
                        rate_tolerance: float = 0.10) -> List[str]:
    """The accounting audit's pass/fail contract (CLI exits 1 on
    any)."""
    d = record["detail"]
    out = []
    if d["errors"]:
        out.append(f"{d['errors']} client-visible errors during the "
                   f"storm: {d.get('error_samples')}")
    delta = d["deltas"]
    total = delta["token_steps_total"]
    kinds = delta["real"] + delta["pad"] + delta["dead"]
    if total <= 0:
        out.append("no decode token-steps accounted in the measured "
                   "window (accounting dead or storm too short)")
    else:
        frac = kinds / total
        if abs(frac - 1.0) > sum_tolerance:
            out.append(
                f"token-step kinds do not sum to the independent "
                f"total: (real+pad+dead)/total = {frac:.4f} "
                f"(|1-x| > {sum_tolerance})")
    acct = d["accounted_decode_tokens"]
    client = d["client_decode_tokens"]
    if client <= 0:
        out.append("client measured zero decode tokens")
    else:
        ratio = acct / client
        if abs(ratio - 1.0) > rate_tolerance:
            out.append(
                f"accounted decode tokens diverge from client-measured"
                f": accounted {acct} vs client {client} "
                f"(ratio {ratio:.3f}, tolerance {rate_tolerance})")
    if delta["compiles_total"] != 0:
        out.append(
            f"{delta['compiles_total']} XLA compile events landed in "
            f"the post-warmup steady window (must be zero)")
    return out


async def run_effwatch(*, engine: str = "debug-tiny",
                       users: int = 6,
                       duration_s: float = 20.0,
                       warmup_s: float = 8.0,
                       num_tokens: int = 32,
                       sum_tolerance: float = 0.02,
                       rate_tolerance: float = 0.10,
                       anti_vacuity: bool = False,
                       window_adapt: bool = True,
                       stagger_s: float = 0.0,
                       mixed_tokens: Optional[List[int]] = None,
                       prompt_chars: int = 0,
                       engine_args: Optional[List[str]] = None,
                       fake_pad_fraction: float = 0.3,
                       fake_dead_fraction: float = 0.1,
                       fake_skew: float = 0.0,
                       fake_tokens_per_s: float = 200.0,
                       platform: str = "cpu",
                       log_dir: str = "loadgen-logs",
                       startup_timeout_s: float = 420.0) -> Dict:
    """Launch one engine, audit its efficiency accounting around a
    steady storm; return the EFF record (BENCH schema; headline =
    accounted steady decode tokens/s).

    ``window_adapt=False`` launches the real engine with
    ``--no-window-adapt`` (the r17 A/B control: full-geometry windows
    whatever the batch holds); ``stagger_s``/``mixed_tokens`` shape
    the churny storm; ``engine_args`` appends raw engine CLI flags
    (geometry overrides for the compile-budget tests)."""
    procs = []
    try:
        if engine == "fake":
            extra = ["--num-tokens", str(num_tokens),
                     "--tokens-per-s", str(fake_tokens_per_s)]
        else:
            extra = list(engine_args or [])
            if not window_adapt:
                extra.append("--no-window-adapt")
        proc = launch_engine(engine, free_port(), log_dir=log_dir,
                             platform=platform, extra_args=extra)
        procs.append(proc)
        await wait_healthy(proc.url, startup_timeout_s)
        model = "fake-model" if engine == "fake" else engine
        if engine == "fake":
            # synthetic pad/dead fractions (and optionally a sum skew)
            # so the engine-free smoke exercises non-trivial splits
            async with aiohttp.ClientSession() as session:
                await session.post(f"{proc.url}/fault", json={
                    "perf": {"pad_fraction": fake_pad_fraction,
                             "dead_fraction": fake_dead_fraction,
                             "skew": fake_skew}})

        before_warmup = await _scrape_perf(proc.url)
        t_before_warmup = time.monotonic()
        logger.info("effwatch warmup storm: %d users for %.0fs", users,
                    warmup_s)
        await _storm(proc.url, model, users=users, duration_s=warmup_s,
                     num_tokens=num_tokens, tag="warmup",
                     stagger_s=stagger_s, mixed_tokens=mixed_tokens,
                     prompt_chars=prompt_chars)

        if anti_vacuity:
            # deliberately mis-sized accounting window: the "before"
            # scrape predates the warmup storm, so the accounted delta
            # covers warmup + steady while the client only measures
            # steady — reconciliation MUST fail
            before, t_before = before_warmup, t_before_warmup
        else:
            before = await _scrape_perf(proc.url)
            t_before = time.monotonic()
        logger.info("effwatch steady storm: %d users for %.0fs", users,
                    duration_s)
        c = await _storm(proc.url, model, users=users,
                         duration_s=duration_s, num_tokens=num_tokens,
                         tag="steady", stagger_s=stagger_s,
                         mixed_tokens=mixed_tokens,
                         prompt_chars=prompt_chars)
        after = await _scrape_perf(proc.url)
        t_after = time.monotonic()
        debug_perf = await _scrape_debug_perf(proc.url)
    finally:
        _stop(procs)

    window_s = max(1e-9, t_after - t_before)
    b, a = _steps(before), _steps(after)
    deltas = {
        "real": a.get("real", 0) - b.get("real", 0),
        "pad": a.get("pad", 0) - b.get("pad", 0),
        "dead": a.get("dead", 0) - b.get("dead", 0),
        "token_steps_total": (a.get("token_steps_total", 0)
                              - b.get("token_steps_total", 0)),
        "windows": a.get("windows", 0) - b.get("windows", 0),
        "compiles_total": (after.get("compiles_total", 0)
                           - before.get("compiles_total", 0)),
    }
    # the client's decode-token ground truth: tokens received minus
    # one per request (the first token is prefill-sampled, so the
    # decode accounting rightly never saw it)
    client_decode = c.tokens - c.requests
    acct_rate = deltas["real"] / window_s
    record = {
        "metric": "engine efficiency accounting audit: accounted vs "
                  "client-measured decode tokens/s, token-step "
                  "fraction consistency, steady-window compile "
                  "silence" + (" (ANTI-VACUITY: mis-sized accounting "
                               "window, must fail)" if anti_vacuity
                               else ""),
        "value": round(acct_rate, 2),
        "unit": "accounted_decode_tokens_per_s",
        "platform": platform,
        "detail": {
            "engine": engine,
            "users": users,
            "duration_s": duration_s,
            "warmup_s": warmup_s,
            "num_tokens": num_tokens,
            "window_adapt": window_adapt,
            "stagger_s": stagger_s,
            "mixed_tokens": mixed_tokens,
            "prompt_chars": prompt_chars,
            "anti_vacuity": anti_vacuity,
            "window_s": round(window_s, 3),
            "requests": c.requests,
            "client_tokens": c.tokens,
            "client_decode_tokens": client_decode,
            "client_decode_tokens_per_s": round(
                client_decode / window_s, 2),
            "accounted_decode_tokens": deltas["real"],
            "accounted_decode_tokens_per_s": round(acct_rate, 2),
            "deltas": deltas,
            "fraction_sum": round(
                (deltas["real"] + deltas["pad"] + deltas["dead"])
                / deltas["token_steps_total"], 4)
            if deltas["token_steps_total"] else None,
            # live fraction over the WHOLE measured window (delta-
            # derived — the A/B gates on this, not on the ring's
            # recent-horizon figure)
            "live_fraction_window": round(
                deltas["real"] / max(1, deltas["real"] + deltas["pad"]
                                     + deltas["dead"]), 4),
            "live_fraction_steady": after.get("live_fraction"),
            "mbu_perc_steady": after.get("mbu_perc"),
            "effective_bytes_per_s_steady":
                after.get("effective_bytes_per_s"),
            "compiles_total_lifetime": after.get("compiles_total"),
            "compile_in_flight_at_end":
                after.get("compile_in_flight"),
            "errors": c.errors,
            "error_samples": c.samples,
            "sum_tolerance": sum_tolerance,
            "rate_tolerance": rate_tolerance,
            "perf_before": before,
            "perf_after": after,
            "debug_perf": debug_perf,
        },
    }
    return record


def effwatch_ab_violations(record: Dict,
                           live_floor: float = 0.80,
                           improve_floor: float = 0.20,
                           sum_tolerance: float = 0.02,
                           rate_tolerance: float = 0.10) -> List[str]:
    """The A/B acceptance contract (CLI exits 1 on any):

    - BOTH sides must individually pass every effwatch gate (sum-to-1,
      client reconciliation, steady-window compile silence, zero
      errors) — the anti-vacuity substrate holds under variable batch
      and window geometry, or the win is unaccountable;
    - the adapt side's whole-window live fraction must reach
      ``live_floor`` AND beat the control's (directional: adaptation
      off must actually cost live fraction, or the storm shape proves
      nothing);
    - accounted decode tokens/s must improve by ``improve_floor``
      relative to the control.
    """
    d = record["detail"]
    out = []
    for side in ("adapt", "control"):
        for v in effwatch_violations({"detail": d[side]},
                                     sum_tolerance=sum_tolerance,
                                     rate_tolerance=rate_tolerance):
            out.append(f"[{side}] {v}")
    live_a = d["adapt"].get("live_fraction_window") or 0.0
    live_c = d["control"].get("live_fraction_window") or 0.0
    if live_a < live_floor:
        out.append(f"adapt-side live fraction {live_a:.3f} below the "
                   f"{live_floor} floor")
    if live_a <= live_c:
        out.append(f"adapt-side live fraction {live_a:.3f} does not "
                   f"beat the control's {live_c:.3f} — the storm "
                   f"shape is not exercising the levers")
    rate_a = d["adapt"]["accounted_decode_tokens_per_s"]
    rate_c = d["control"]["accounted_decode_tokens_per_s"]
    if rate_c <= 0:
        out.append("control side accounted zero decode tokens/s")
    elif rate_a < rate_c * (1.0 + improve_floor):
        out.append(
            f"accounted decode tokens/s improved only "
            f"{100.0 * (rate_a / rate_c - 1.0):.1f}% "
            f"({rate_a} vs {rate_c}; floor {100 * improve_floor:.0f}%)")
    return out


def _aggregate_side(details: List[Dict]) -> Dict:
    """Fold one side's per-round details into an aggregate the A/B
    gates read: counters and token counts SUM across rounds, rates
    come from the summed tokens over the summed measured windows, so
    no single round's host noise owns the comparison."""
    deltas = {k: sum(d["deltas"][k] for d in details)
              for k in ("real", "pad", "dead", "token_steps_total",
                        "windows", "compiles_total")}
    window_s = sum(d["window_s"] for d in details)
    acct = sum(d["accounted_decode_tokens"] for d in details)
    client = sum(d["client_decode_tokens"] for d in details)
    kinds = deltas["real"] + deltas["pad"] + deltas["dead"]
    return {
        "rounds": len(details),
        "window_adapt": details[0]["window_adapt"],
        "errors": sum(d["errors"] for d in details),
        "error_samples": [s for d in details
                          for s in d["error_samples"]][:6],
        "requests": sum(d["requests"] for d in details),
        "deltas": deltas,
        "window_s": round(window_s, 3),
        "accounted_decode_tokens": acct,
        "client_decode_tokens": client,
        "accounted_decode_tokens_per_s": round(acct / window_s, 2),
        "client_decode_tokens_per_s": round(client / window_s, 2),
        "fraction_sum": round(kinds / deltas["token_steps_total"], 4)
        if deltas["token_steps_total"] else None,
        "live_fraction_window": round(deltas["real"] / max(1, kinds),
                                      4),
    }


async def run_effwatch_ab(*, live_floor: float = 0.80,
                          improve_floor: float = 0.20,
                          rounds: int = 1,
                          fake_control_pad_fraction: float = 0.40,
                          fake_control_dead_fraction: float = 0.10,
                          fake_control_tokens_per_s: float = 200.0,
                          **kw) -> Dict:
    """Same-storm A/B: window adaptation ON vs ``--no-window-adapt``
    (identical storm shape, fresh engine process per side per round).
    ``rounds`` > 1 repeats the pair in ABBA order (adapt-control /
    control-adapt alternating) and gates on per-side AGGREGATES —
    single-host run-to-run noise is comparable to the effect size, so
    the committed record sums tokens and measured seconds across
    rounds instead of trusting one pair. Returns an EFF record whose
    detail carries both aggregates, every per-round detail, and the
    comparison; headline value = accounted decode tokens/s
    improvement (%).

    Fake-engine mode is a PLUMBING smoke (delta math, per-side gates,
    comparison arithmetic): the control side runs with deliberately
    worse synthetic pad/dead fractions and pacing — the committed
    acceptance record comes from the real-engine A/B
    (benchmarks/run_effwatch.sh --ab)."""
    ctrl_kw = dict(kw)
    if ctrl_kw.get("engine") == "fake":
        ctrl_kw.update(
            fake_pad_fraction=fake_control_pad_fraction,
            fake_dead_fraction=fake_control_dead_fraction,
            fake_tokens_per_s=fake_control_tokens_per_s)
    per: Dict[bool, List[Dict]] = {True: [], False: []}
    for i in range(max(1, rounds)):
        order = (True, False) if i % 2 == 0 else (False, True)
        for adapt_side in order:
            logger.info("effwatch A/B round %d/%d: %s side", i + 1,
                        max(1, rounds),
                        "adapt" if adapt_side else "control")
            rec = await run_effwatch(
                window_adapt=adapt_side,
                **(kw if adapt_side else ctrl_kw))
            per[adapt_side].append(rec["detail"])
    adapt = _aggregate_side(per[True])
    control = _aggregate_side(per[False])
    rate_a = adapt["accounted_decode_tokens_per_s"]
    rate_c = control["accounted_decode_tokens_per_s"]
    improvement = (100.0 * (rate_a / rate_c - 1.0)
                   if rate_c > 0 else None)
    return {
        "metric": "continuous batching across fused decode windows: "
                  "same-storm A/B, window adaptation (live-row "
                  "compaction + adaptive window sizing + mid-window "
                  "admission) vs --no-window-adapt",
        "value": round(improvement, 2) if improvement is not None
        else None,
        "unit": "accounted_decode_tokens_per_s_improvement_perc",
        "platform": kw.get("platform", "cpu"),
        "detail": {
            "adapt": adapt,
            "control": control,
            "rounds": {"adapt": per[True], "control": per[False]},
            "accounted_decode_tokens_per_s_adapt": rate_a,
            "accounted_decode_tokens_per_s_control": rate_c,
            "improvement_perc": round(improvement, 2)
            if improvement is not None else None,
            "live_fraction_adapt": adapt["live_fraction_window"],
            "live_fraction_control": control["live_fraction_window"],
            "live_floor": live_floor,
            "improve_floor": improve_floor,
        },
    }
