"""Schedule and rate sharding: how N workers split one workload.

Pure functions (no IO) so the partition laws are unit-testable:

- ``shard_sessions(total, workers)`` — contiguous [start, end) ranges
  covering [0, total) exactly once. Contiguity matters: a session's
  turns must all be fired by ONE worker (multi-turn history and
  session-affinity routing both key off the session), and contiguous
  ``first_id`` ranges are what ``plan_sessions`` resumes from.
- ``worker_arrival_seed(seed, i)`` — per-worker arrival RNG seeds,
  distinct by construction, decoupled from the (shared) planning seed.

The rate law needs no function: worker i runs the spec's open-loop
stages with every qps divided by N. Superposing N independent Poisson
processes at qps/N yields one Poisson process at qps — the merged
arrival statistics are the single-worker statistics.
"""

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


def shard_sessions(total: int, workers: int) -> List[Tuple[int, int]]:
    """Contiguous [start, end) session-id ranges, one per worker,
    covering [0, total) with sizes differing by at most 1. Empty ranges
    (more workers than sessions) are legal and returned as (k, k)."""
    if workers < 1:
        raise ValueError(f"workers must be >= 1, got {workers}")
    if total < 0:
        raise ValueError(f"total must be >= 0, got {total}")
    base, extra = divmod(total, workers)
    out: List[Tuple[int, int]] = []
    start = 0
    for i in range(workers):
        size = base + (1 if i < extra else 0)
        out.append((start, start + size))
        start += size
    return out


def worker_arrival_seed(seed: int, worker_index: int) -> int:
    """Worker i's open-loop arrival seed: derived from the workload
    seed but distinct per worker (identical streams would synchronize
    into N-request bursts) and distinct from the single-process
    arrival seed (``(seed << 8) ^ 0xa441``) so a 1-worker distributed
    run is still an independent draw, not a bit-identical rerun."""
    return ((seed << 16) ^ 0xD157_0000) + worker_index * 0x9E37


@dataclass
class WorkerAssignment:
    """Everything one worker process needs, JSON round-tripped through
    the assignment file the coordinator writes and the worker loads.

    mode "synthetic": run ``spec`` (arrival qps already divided by
    ``num_workers`` by the coordinator) over sessions
    [first_session_id, first_session_id + session_count).

    mode "replay": re-issue ``trace_path``'s recorded requests whose
    session_id % num_workers == worker_index, at recorded offsets.
    """
    worker_index: int
    num_workers: int
    base_url: str
    mode: str = "synthetic"              # "synthetic" | "replay"
    spec: Optional[Dict] = None          # WorkloadSpec asdict (synthetic)
    first_session_id: int = 0
    session_count: Optional[int] = None
    duration_s: Optional[float] = None
    arrival_seed: Optional[int] = None
    trace_path: Optional[str] = None     # replay
    speedup: float = 1.0
    api_key: Optional[str] = None
    warmup_requests: int = 0
    extra_headers: Dict[str, str] = field(default_factory=dict)

    def validate(self) -> "WorkerAssignment":
        if self.mode not in ("synthetic", "replay"):
            raise ValueError(f"mode {self.mode!r} must be 'synthetic' "
                             f"or 'replay'")
        if self.mode == "synthetic" and self.spec is None:
            raise ValueError("synthetic assignment needs a spec")
        if self.mode == "replay" and not self.trace_path:
            raise ValueError("replay assignment needs a trace_path")
        if not (0 <= self.worker_index < self.num_workers):
            raise ValueError(
                f"worker_index {self.worker_index} outside "
                f"[0, {self.num_workers})")
        return self

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "WorkerAssignment":
        return cls(**json.loads(text)).validate()

    @classmethod
    def from_file(cls, path: str) -> "WorkerAssignment":
        with open(path) as f:
            return cls.from_json(f.read())
