"""Distributed load generation: coordinator/worker sharded loadgen.

One loadgen process caps out well below the saturation point of an
N-router fleet — this package shards generation across worker
processes (one coordinator, N workers, optionally on N hosts via
``--base-url`` per worker) without changing what is measured:

- the deterministic session schedule is partitioned by contiguous
  ``first_id`` ranges (``workload.plan_sessions`` is resumable, so the
  shards concatenate to exactly the single-process schedule);
- each worker runs an independent open-loop Poisson stream at
  rate/N — the superposition of N independent Poisson processes at
  qps/N is one Poisson process at qps, so the fleet sees the same
  arrival statistics one big worker would produce;
- workers ship RAW per-request records (JSONL), and the coordinator
  merges samples before taking quantiles (``report.LatencyRecordSet``
  — merge-then-quantile, never quantile-then-merge).

Trace replay rides the same sharding: ``tracefile`` records any run's
per-request schedule to a ``.trace.jsonl`` and replays it with original
timing, sessions sharded across workers by id.
"""

from production_stack_tpu.loadgen.distributed.shard import (  # noqa: F401
    WorkerAssignment, shard_sessions, worker_arrival_seed)
from production_stack_tpu.loadgen.distributed.tracefile import (  # noqa: F401
    TRACE_SCHEMA, TraceRequest, read_trace, synthesize_trace,
    trace_from_records, write_trace)
