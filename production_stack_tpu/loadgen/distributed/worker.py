"""Distributed loadgen worker: one shard, raw records out.

Subprocess entry point (the coordinator spawns N of these; a
multi-host run spawns them by hand or via ssh with the same files)::

    python -m production_stack_tpu.loadgen.distributed.worker \\
        --assignment /tmp/dist/worker-0.json \\
        --records /tmp/dist/worker-0.records.jsonl \\
        --summary /tmp/dist/worker-0.summary.json

The assignment file (``shard.WorkerAssignment``) says what to run;
this process stays dumb on purpose. Output discipline is the whole
contract: the records file carries one RAW ``RequestRecord`` per line
— individual samples, never pre-aggregated percentiles — so the
coordinator can merge-then-quantile. The summary carries worker-local
bookkeeping (counts, violations, the issued-request digest replay
determinism is gated on) and a convenience aggregate that is NEVER
merged with other workers' (skew diagnostics only).

Exit 0 iff the shard ran and both files were written; invariant
violations are reported in the summary, not the exit code — the
coordinator owns the verdict.
"""

import argparse
import asyncio
import dataclasses
import json
import sys
from typing import Dict, List

from production_stack_tpu.loadgen.client import RequestRecord
from production_stack_tpu.loadgen.distributed.shard import WorkerAssignment
from production_stack_tpu.loadgen.distributed.tracefile import (
    read_trace, replay_workload)
from production_stack_tpu.loadgen.runner import run_workload
from production_stack_tpu.loadgen.spec import WorkloadSpec


def write_records(path: str, records: List[RequestRecord]) -> None:
    with open(path, "w") as f:
        for r in records:
            d = dataclasses.asdict(r)
            d.pop("body", None)          # measurement, not payload
            f.write(json.dumps(d) + "\n")


def read_records(path: str) -> List[RequestRecord]:
    out: List[RequestRecord] = []
    with open(path) as f:
        for ln in f:
            if ln.strip():
                out.append(RequestRecord(**json.loads(ln)))
    return out


async def run_assignment(asn: WorkerAssignment) -> Dict:
    """Run the shard; returns {"records", "summary_extra"}."""
    if asn.mode == "replay":
        _, requests = read_trace(asn.trace_path)
        res = await replay_workload(
            requests, asn.base_url, worker_index=asn.worker_index,
            num_workers=asn.num_workers, speedup=asn.speedup,
            api_key=asn.api_key,
            extra_headers=asn.extra_headers or None)
        return {"records": res["records"],
                "summary_extra": {"violations": res["violations"],
                                  "issued": res["issued"],
                                  "issued_digest": res["issued_digest"]}}
    spec = WorkloadSpec.from_dict(asn.spec)
    result = await run_workload(
        spec, asn.base_url, api_key=asn.api_key,
        duration_s=asn.duration_s, max_sessions=asn.session_count,
        first_session_id=asn.first_session_id,
        arrival_seed=asn.arrival_seed,
        warmup_requests=asn.warmup_requests,
        checkpoint_interval_s=3600.0)    # coordinator owns progress
    return {"records": result.records,
            "summary_extra": {"violations": result.violations}}


def main(argv=None) -> int:
    p = argparse.ArgumentParser("loadgen-dist-worker")
    p.add_argument("--assignment", required=True,
                   help="WorkerAssignment JSON file the coordinator "
                        "wrote (shard bounds, arrival seed, mode)")
    p.add_argument("--records", required=True,
                   help="output: one raw RequestRecord JSON per line "
                        "(samples, never percentiles)")
    p.add_argument("--summary", required=True,
                   help="output: worker-local counts/violations JSON")
    args = p.parse_args(argv)
    asn = WorkerAssignment.from_file(args.assignment)
    res = asyncio.run(run_assignment(asn))
    records = res["records"]
    write_records(args.records, records)
    ok = [r for r in records if r.ok]
    summary = {
        "worker_index": asn.worker_index,
        "mode": asn.mode,
        "launched": len(records),
        "finished": len(ok),
        "errors": len([r for r in records if r.error is not None]),
        "http_5xx": len([r for r in records if r.status >= 500]),
        **res["summary_extra"],
    }
    with open(args.summary, "w") as f:
        json.dump(summary, f, indent=2)
        f.write("\n")
    print(f"worker {asn.worker_index}: {summary['launched']} launched, "
          f"{summary['errors']} errors, "
          f"{len(summary.get('violations', []))} violations",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
