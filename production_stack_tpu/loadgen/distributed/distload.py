"""distload mode: prove sharded loadgen measures the same thing.

A distributed load generator is only worth trusting if sharding the
generation changes NOTHING about what is measured. This rig closes
that loop, then composes the capstone demonstration ROADMAP item 5
asked for:

1. **Scaling gate** — one router + M fake engines (with per-request
   ``--service-jitter`` so latency has real spread to get wrong).
   A single-worker control drives the open-loop workload at global
   rate Q; then N >= 3 workers drive the SAME schedule at Q/N each.
   Merged offered load must land on Q, and the merged TTFT/e2e
   percentiles (merge-then-quantile across workers) must match the
   control within tolerance. Zero errors on both sides.
2. **Replay determinism gate** — the committed production-shaped
   trace is replayed twice across N workers; both replays must issue
   the SAME request multiset (digest over every (session, turn, kind,
   model, shape, tenant)), with zero errors.
3. **Capstone** (``--capstone``) — 2 peered routers fronting the r21
   two-pool heterogeneous fleet (pool-a: model-a + runtime LoRA,
   pool-b: model-b) + the r18 obsplane scraping all of it, under
   multi-worker replay of the mixed chat/rag/LoRA trace, workers
   pinned round-robin across routers. Gates: zero raw 5xx anywhere,
   and the obsplane's online stitcher shows >= ``min_chain_fraction``
   (0.95) complete router->engine chains — the fleet-wide measurement
   story holds under distributed production-shaped load.

Anti-vacuity: ``--anti-vacuity mismatched-rate`` skips the per-worker
rate division (every worker fires at the FULL global rate) and
``--anti-vacuity single-worker`` runs the "distributed" side with one
worker; either way the scaling gate must verifiably fail. The full
rig also embeds a short mismatched-rate run and requires its failure
in the committed record — a tolerance loose enough to pass a 3x
offered-load error would be certified useless by its own record.

Committed record: ``DISTLOAD_r22.json`` via
``benchmarks/run_distload.sh``; exit 1 on any gate violation.
"""

import asyncio
import json
import os
import sys
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.distributed.coordinator import (
    DistResult, replay_assignments, run_coordinated,
    synthetic_assignments)
from production_stack_tpu.loadgen.distributed.shard import WorkerAssignment
from production_stack_tpu.loadgen.distributed.tracefile import read_trace
from production_stack_tpu.loadgen.orchestrator import (Proc, _spawn, _stop,
                                                       free_port,
                                                       launch_engine,
                                                       launch_obsplane,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.loadgen.spec import (ArrivalSpec, SessionSpec,
                                               TrafficMix, WorkloadSpec)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

TRACES_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..",
                 "benchmarks", "traces"))
# replay-determinism gate default: single-model trace the basic stack
# (one router, model-a engines) can serve end to end
BURSTY_TRACE = os.path.join(TRACES_DIR, "bursty_tenant.trace.jsonl")
# capstone default: mixed chat/rag/LoRA/secondary-model trace — needs
# the two-pool fleet (model-a + lora-a in pool-a, model-b in pool-b)
MIXED_TRACE = os.path.join(TRACES_DIR, "mixed_classes.trace.jsonl")

BASE_MODEL = "model-a"
LORA_MODEL = "lora-a"
POOL_B_MODEL = "model-b"


def distload_spec(qps: float, phase_s: float) -> WorkloadSpec:
    """The scaling-gate workload: open-loop constant rate, small
    multi-round chat sessions (fake engines serve chat only)."""
    return WorkloadSpec(
        name="distload", model=BASE_MODEL, seed=22,
        mix=TrafficMix(chat=1.0),
        session=SessionSpec(rounds_min=1, rounds_max=3,
                            system_prompt_tokens=16,
                            question_tokens_mean=12.0,
                            question_tokens_sigma=0.4,
                            question_tokens_max=24,
                            answer_tokens_mean=16.0,
                            answer_tokens_sigma=0.3,
                            answer_tokens_max=16),
        arrival=ArrivalSpec(mode="open", qps_start=qps, qps_end=qps,
                            qps_step=0.0, stage_duration_s=phase_s),
        request_timeout_s=30.0,
    ).validate()


def _dist_block(res: DistResult) -> Dict:
    return {"summary": res.merged_summary,
            "per_worker": res.per_worker,
            "skew": res.skew,
            "violations": res.violations,
            "issued_digest": res.issued_digest}


def scaling_violations(control: Dict, dist: Dict, *, target_qps: float,
                       workers: int, min_workers: int = 3,
                       qps_rel_tol: float = 0.25,
                       pct_rel_tol: float = 0.35,
                       pct_abs_tol_s: float = 0.05) -> List[str]:
    """The scaling gate as a pure function of the two summary blocks —
    the embedded anti-vacuity run reuses it verbatim, so whatever
    tolerance the real gate applies is the tolerance the mismatched
    run must fail."""
    out: List[str] = []
    if workers < min_workers:
        out.append(f"SCALE distributed side ran {workers} workers, "
                   f"gate requires >= {min_workers}")
    csum, dsum = control.get("summary") or {}, dist.get("summary") or {}
    for name, block in (("control", control), ("dist", dist)):
        for v in block.get("violations") or []:
            out.append(f"SCALE {name}: {v}")
        s = block.get("summary") or {}
        if s.get("errors"):
            out.append(f"SCALE {name} saw {s['errors']} request errors")
    for name, s in (("control", csum), ("dist", dsum)):
        offered = s.get("offered_qps", 0.0)
        if abs(offered - target_qps) > qps_rel_tol * target_qps:
            out.append(
                f"SCALE {name} offered {offered:.3f} qps, target "
                f"{target_qps:.3f} (±{qps_rel_tol:.0%}) — "
                + ("rate sharding is broken (workers are not "
                   "superposing to the target)" if name == "dist"
                   else "the control measured the wrong rate"))
    for metric, pcts in (("ttft_s", ("p50", "p90")),
                         ("e2e_s", ("p50",))):
        for p in pcts:
            c = (csum.get(metric) or {}).get(p)
            d = (dsum.get(metric) or {}).get(p)
            if c is None or d is None:
                out.append(f"SCALE {metric}.{p} missing from a summary")
                continue
            tol = max(pct_abs_tol_s, pct_rel_tol * c)
            if abs(d - c) > tol:
                out.append(
                    f"SCALE merged {metric}.{p} {d:.4f}s vs control "
                    f"{c:.4f}s — |delta| {abs(d - c):.4f}s exceeds "
                    f"tol {tol:.4f}s (sharding changed the "
                    f"measurement)")
    return out


def replay_gate_violations(replay: Dict) -> List[str]:
    out: List[str] = []
    runs = replay.get("runs") or []
    if len(runs) < 2:
        out.append("REPLAY fewer than 2 replay runs recorded")
        return out
    digests = [r.get("issued_digest") for r in runs]
    if None in digests:
        out.append("REPLAY a run produced no issued digest")
    elif len(set(digests)) != 1:
        out.append(f"REPLAY digests differ across runs: {digests} — "
                   f"replay is not deterministic")
    expect = replay.get("trace_requests")
    for i, r in enumerate(runs):
        if r.get("summary", {}).get("errors"):
            out.append(f"REPLAY run {i} saw "
                       f"{r['summary']['errors']} errors")
        for v in r.get("violations") or []:
            out.append(f"REPLAY run {i}: {v}")
        launched = r.get("summary", {}).get("launched", 0)
        if expect is not None and launched != expect:
            out.append(f"REPLAY run {i} launched {launched} of the "
                       f"trace's {expect} requests")
    return out


def capstone_violations(cap: Dict,
                        min_chain_fraction: float = 0.95) -> List[str]:
    out: List[str] = []
    if cap.get("summary", {}).get("http_5xx"):
        out.append(f"CAPSTONE {cap['summary']['http_5xx']} raw 5xx "
                   f"under replayed distributed traffic")
    if cap.get("summary", {}).get("errors"):
        out.append(f"CAPSTONE {cap['summary']['errors']} request "
                   f"errors")
    for v in cap.get("violations") or []:
        out.append(f"CAPSTONE {v}")
    stitch = cap.get("stitch") or {}
    if not stitch.get("chains_complete"):
        out.append("CAPSTONE the obsplane stitched zero complete "
                   "chains — the composed demonstration is vacuous")
    elif stitch.get("complete_fraction", 0.0) < min_chain_fraction:
        out.append(f"CAPSTONE stitched-chain completeness "
                   f"{stitch.get('complete_fraction')} < "
                   f"{min_chain_fraction}")
    if not cap.get("pools_served", {}).get(POOL_B_MODEL):
        out.append("CAPSTONE pool-b saw no traffic — the "
                   "heterogeneous-fleet leg of the demonstration "
                   "did not run")
    return out


def distload_violations(record: Dict, *,
                        min_chain_fraction: float = 0.95) -> List[str]:
    """Everything that must hold for DISTLOAD_*.json to mean what it
    claims. Exit-1 surface of ``loadgen distload``."""
    d = record["detail"]
    out: List[str] = list(d.get("control_errors") or [])
    out += scaling_violations(
        d["control"], d["dist"], target_qps=d["target_qps"],
        workers=d["workers"], min_workers=d.get("min_workers", 3),
        qps_rel_tol=d["tolerances"]["qps_rel_tol"],
        pct_rel_tol=d["tolerances"]["pct_rel_tol"],
        pct_abs_tol_s=d["tolerances"]["pct_abs_tol_s"])
    out += replay_gate_violations(d["replay"])
    if d.get("capstone"):
        out += capstone_violations(d["capstone"],
                                   min_chain_fraction=min_chain_fraction)
    av = d.get("anti_vacuity")
    if av is not None and not av.get("violations"):
        out.append("ANTI-VACUITY the mismatched-rate run PASSED the "
                   "scaling gate — the tolerance is too loose to "
                   "certify anything")
    return out


async def _settle(procs: List[Proc], names: List[str],
                  errors: List[str]) -> None:
    for p, name in zip(procs, names):
        if p.popen.poll() is not None:
            errors.append(f"{name} died (exit {p.popen.returncode}, "
                          f"see {p.log_path})")


def _run_dist(assignments: List[WorkerAssignment], work_dir: str,
              timeout_s: float, tag: str) -> DistResult:
    return run_coordinated(assignments, work_dir=work_dir,
                           timeout_s=timeout_s, log_prefix=tag)


async def run_distload(*, engines: int = 2, workers: int = 3,
                       qps: float = 6.0, phase_s: float = 10.0,
                       trace_path: Optional[str] = None,
                       capstone_trace: Optional[str] = None,
                       speedup: float = 4.0,
                       capstone: bool = True,
                       capstone_routers: int = 2,
                       capstone_engines_per_pool: int = 2,
                       anti_vacuity: Optional[str] = None,
                       skip_embedded_anti_vacuity: bool = False,
                       service_jitter: float = 0.25,
                       qps_rel_tol: float = 0.25,
                       pct_rel_tol: float = 0.35,
                       pct_abs_tol_s: float = 0.05,
                       min_chain_fraction: float = 0.95,
                       worker_timeout_s: float = 300.0,
                       startup_timeout_s: float = 60.0,
                       log_dir: str = "loadgen-logs",
                       work_dir: str = "loadgen-logs/distload",
                       platform: str = "cpu") -> Dict:
    """The full rig; returns the BENCH-schema record."""
    trace_path = os.path.abspath(trace_path or BURSTY_TRACE)
    capstone_trace = os.path.abspath(capstone_trace or MIXED_TRACE)
    control_errors: List[str] = []
    os.makedirs(work_dir, exist_ok=True)
    spec = distload_spec(qps, phase_s)
    record_workers = 1 if anti_vacuity == "single-worker" else workers

    engine_args = ["--model", BASE_MODEL, "--adapters", LORA_MODEL,
                   "--ttft", "0.04", "--tokens-per-s", "300",
                   "--num-tokens", "16",
                   "--service-jitter", str(service_jitter)]
    procs: List[Proc] = []
    try:
        engine_procs = [launch_engine("fake", free_port(),
                                      log_dir=log_dir,
                                      extra_args=engine_args)
                        for _ in range(engines)]
        procs.extend(engine_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in engine_procs])
        router = launch_router([e.url for e in engine_procs],
                               BASE_MODEL, free_port(),
                               routing="session", log_dir=log_dir)
        procs.append(router)
        await wait_healthy(router.url, startup_timeout_s,
                           require_endpoints=engines)

        # ------------------------------------------ scaling gate
        logger.info("distload: control (1 worker @ %.1f qps, %gs)",
                    qps, phase_s)
        control_res = await asyncio.to_thread(
            _run_dist,
            synthetic_assignments(spec, router.url, workers=1,
                                  duration_s=phase_s,
                                  warmup_requests=4),
            work_dir, worker_timeout_s, "control")

        logger.info("distload: distributed (%d workers @ %.1f qps "
                    "global%s)", record_workers, qps,
                    ", MISMATCHED per-worker rate" if
                    anti_vacuity == "mismatched-rate" else "")
        dist_assignments = synthetic_assignments(
            spec, router.url, workers=record_workers,
            duration_s=phase_s, warmup_requests=2)
        if anti_vacuity == "mismatched-rate":
            # the vacuity probe: skip the 1/N division — every worker
            # fires at the FULL global rate, so offered load lands at
            # workers * qps and the gate must catch it
            for asn in dist_assignments:
                asn.spec["arrival"]["qps_scale"] = \
                    spec.arrival.qps_scale
        dist_res = await asyncio.to_thread(
            _run_dist, dist_assignments, work_dir, worker_timeout_s,
            "dist")
        await _settle(procs, [p.name for p in procs], control_errors)

        # --------------------------- embedded anti-vacuity (short)
        anti_block: Optional[Dict] = None
        if anti_vacuity is None and not skip_embedded_anti_vacuity:
            short = distload_spec(qps, max(3.0, phase_s / 2))
            av_assignments = synthetic_assignments(
                short, router.url, workers=workers,
                duration_s=max(3.0, phase_s / 2))
            for asn in av_assignments:
                asn.spec["arrival"]["qps_scale"] = \
                    short.arrival.qps_scale
            av_res = await asyncio.to_thread(
                _run_dist, av_assignments, work_dir, worker_timeout_s,
                "anti-vacuity")
            av_violations = scaling_violations(
                _dist_block(control_res), _dist_block(av_res),
                target_qps=qps, workers=workers,
                qps_rel_tol=qps_rel_tol, pct_rel_tol=pct_rel_tol,
                pct_abs_tol_s=pct_abs_tol_s)
            anti_block = {
                "mode": "mismatched-rate",
                "offered_qps": av_res.merged_summary.get("offered_qps"),
                "violations": av_violations,
            }

        # ------------------------------- replay determinism gate
        _, trace_reqs = read_trace(trace_path)
        replay_runs: List[Dict] = []
        for i in range(2):
            logger.info("distload: replay run %d (%d workers, "
                        "speedup %g)", i, workers, speedup)
            rres = await asyncio.to_thread(
                _run_dist,
                replay_assignments(trace_path, router.url,
                                   workers=workers, speedup=speedup),
                work_dir, worker_timeout_s, f"replay{i}")
            replay_runs.append({
                "summary": rres.merged_summary,
                "violations": rres.violations,
                "issued_digest": rres.issued_digest,
                "skew": rres.skew,
            })
        replay_block = {"trace": os.path.basename(trace_path),
                        "trace_requests": len(trace_reqs),
                        "speedup": speedup,
                        "runs": replay_runs}
        await _settle(procs, [p.name for p in procs], control_errors)
    finally:
        _stop(procs)

    # ---------------------------------------------------- capstone
    capstone_block: Optional[Dict] = None
    if capstone:
        capstone_block = await _run_capstone(
            trace_path=capstone_trace, workers=workers, speedup=speedup,
            routers=capstone_routers,
            engines_per_pool=capstone_engines_per_pool,
            service_jitter=service_jitter,
            worker_timeout_s=worker_timeout_s,
            startup_timeout_s=startup_timeout_s, log_dir=log_dir,
            work_dir=work_dir, control_errors=control_errors)

    detail = {
        "workers": record_workers,
        "declared_workers": workers,
        "engines": engines,
        "target_qps": qps,
        "phase_s": phase_s,
        "service_jitter": service_jitter,
        "min_workers": 3,
        "tolerances": {"qps_rel_tol": qps_rel_tol,
                       "pct_rel_tol": pct_rel_tol,
                       "pct_abs_tol_s": pct_abs_tol_s,
                       "min_chain_fraction": min_chain_fraction},
        "anti_vacuity_mode": anti_vacuity,
        "control": _dist_block(control_res),
        "dist": _dist_block(dist_res),
        "anti_vacuity": anti_block,
        "replay": replay_block,
        "capstone": capstone_block,
        "control_errors": control_errors,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    return {
        "metric": "distributed loadgen: merged-percentile parity vs "
                  "single-worker control + deterministic trace replay "
                  "+ composed routers/pools/obsplane capstone",
        "value": (dist_res.merged_summary or {})
        .get("output_tokens_per_s", 0.0),
        "unit": "out_tok/s",
        "platform": platform,
        "detail": detail,
    }


def add_cli_args(sp) -> None:
    """The ``loadgen distload`` flag surface (registered here, not in
    ``__main__.py``, so ``tools/check_flags_documented.py`` can scan
    this file as its own surface)."""
    sp.add_argument("--workers", type=int, default=3,
                    help="loadgen worker processes the coordinator "
                         "shards the schedule across (scaling gate "
                         "requires >= 3)")
    sp.add_argument("--engines", type=int, default=2,
                    help="fake engines behind the basic stack's router")
    sp.add_argument("--qps", type=float, default=6.0,
                    help="global open-loop target rate; each worker "
                         "runs at qps/workers")
    sp.add_argument("--phase", type=float, default=10.0,
                    help="seconds per scaling-gate phase (control and "
                         "distributed)")
    sp.add_argument("--trace", default=None,
                    help="trace replayed for the determinism gate "
                         "(default: the committed bursty_tenant trace)")
    sp.add_argument("--capstone-trace", default=None,
                    help="trace replayed through the capstone fleet "
                         "(default: the committed mixed_classes trace "
                         "— it carries the model-b stream pool-b "
                         "serves)")
    sp.add_argument("--speedup", type=float, default=4.0,
                    help="replay timeline compression (4 = replay a "
                         "40s trace in 10s)")
    sp.add_argument("--no-capstone", action="store_true",
                    help="skip the composed 2-router/2-pool/obsplane "
                         "capstone (tier-1 smoke runs this way)")
    sp.add_argument("--capstone-routers", type=int, default=2)
    sp.add_argument("--capstone-engines-per-pool", type=int, default=2)
    sp.add_argument("--anti-vacuity", default=None,
                    choices=["mismatched-rate", "single-worker"],
                    help="sabotage the run (workers at full global "
                         "rate each, or a 1-worker 'distributed' "
                         "side); the scaling gate must fail and the "
                         "command must exit 1")
    sp.add_argument("--skip-embedded-anti-vacuity", action="store_true",
                    help="skip the short mismatched-rate sub-run the "
                         "record embeds as self-test evidence")
    sp.add_argument("--service-jitter", type=float, default=0.25,
                    help="fake engines' deterministic per-request "
                         "service spread — real latency variance for "
                         "the percentile-parity gate to get wrong")
    sp.add_argument("--qps-rel-tol", type=float, default=0.25,
                    help="offered-load tolerance vs the target rate")
    sp.add_argument("--pct-rel-tol", type=float, default=0.35,
                    help="merged-vs-control percentile tolerance, "
                         "relative part")
    sp.add_argument("--pct-abs-tol", type=float, default=0.05,
                    help="merged-vs-control percentile tolerance, "
                         "absolute floor (seconds)")
    sp.add_argument("--min-chain-fraction", type=float, default=0.95,
                    help="capstone: fraction of obsplane-stitched "
                         "chains that must be complete")
    sp.add_argument("--worker-timeout", type=float, default=300.0,
                    help="coordinator kills a worker past this")
    sp.add_argument("--startup-timeout", type=float, default=60.0)
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--work-dir", default="loadgen-logs/distload",
                    help="assignment/records/summary files per worker")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--output", default=None,
                    help="write DISTLOAD_*.json here (default: "
                         "timestamped)")


async def _run_capstone(*, trace_path: str, workers: int,
                        speedup: float, routers: int,
                        engines_per_pool: int, service_jitter: float,
                        worker_timeout_s: float,
                        startup_timeout_s: float, log_dir: str,
                        work_dir: str,
                        control_errors: List[str]) -> Dict:
    """2 peered pool-routers + two-pool fleet + obsplane under
    multi-worker replayed traffic."""
    procs: List[Proc] = []
    try:
        pool_a = [launch_engine(
            "fake", free_port(), log_dir=log_dir,
            extra_args=["--model", BASE_MODEL, "--adapters", LORA_MODEL,
                        "--strict-models", "--ttft", "0.04",
                        "--tokens-per-s", "300", "--num-tokens", "16",
                        "--service-jitter", str(service_jitter)])
            for _ in range(engines_per_pool)]
        pool_b = [launch_engine(
            "fake", free_port(), log_dir=log_dir,
            extra_args=["--model", POOL_B_MODEL, "--strict-models",
                        "--ttft", "0.04", "--tokens-per-s", "300",
                        "--num-tokens", "16",
                        "--service-jitter", str(service_jitter)])
            for _ in range(engines_per_pool)]
        procs.extend(pool_a + pool_b)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in pool_a + pool_b])
        pools_json = json.dumps({
            "pool-a": {"backends": [e.url for e in pool_a],
                       "models": [BASE_MODEL, LORA_MODEL],
                       "routing_logic": "session"},
            "pool-b": {"backends": [e.url for e in pool_b],
                       "models": [POOL_B_MODEL],
                       "routing_logic": "roundrobin"},
        })
        router_ports = [free_port() for _ in range(routers)]
        router_urls = [f"http://127.0.0.1:{p}" for p in router_ports]
        router_procs: List[Proc] = []
        for i, port in enumerate(router_ports):
            peers = [u for j, u in enumerate(router_urls) if j != i]
            cmd = [sys.executable, "-m",
                   "production_stack_tpu.router.app",
                   "--host", "127.0.0.1", "--port", str(port),
                   "--service-discovery", "static",
                   "--pools", pools_json,
                   "--engine-stats-interval", "1",
                   "--router-id", f"router-{i}"]
            if peers:
                cmd += ["--peer-routers", ",".join(peers),
                        "--peer-gossip-interval", "0.5"]
            router_procs.append(_spawn(f"capstone-router-{port}", cmd,
                                       f"http://127.0.0.1:{port}",
                                       log_dir))
        procs.extend(router_procs)
        await asyncio.gather(*[
            wait_healthy(r.url, startup_timeout_s,
                         require_endpoints=2 * engines_per_pool)
            for r in router_procs])
        obsplane = launch_obsplane(
            router_urls, [e.url for e in pool_a + pool_b], free_port(),
            log_dir=log_dir,
            incident_dir=os.path.join(work_dir, "incidents"),
            extra_args=["--poll-interval", "0.5",
                        "--scrape-timeout", "2"])
        procs.append(obsplane)
        await wait_healthy(obsplane.url, startup_timeout_s)

        # workers pinned round-robin across routers — one coordinated
        # run whose shards enter the fleet through different frontends
        assignments = []
        for i in range(workers):
            assignments.extend(replay_assignments(
                trace_path, router_urls[i % len(router_urls)],
                workers=workers, speedup=speedup)[i:i + 1])
        res = await asyncio.to_thread(
            _run_dist, assignments, work_dir, worker_timeout_s,
            "capstone")
        # let the obsplane's poll loop drain the engines' trace rings
        await asyncio.sleep(2.5)

        stitch: Dict = {}
        pools_served: Dict[str, int] = {}
        async with aiohttp.ClientSession() as s:
            try:
                async with s.get(f"{obsplane.url}/fleet/traces",
                                 timeout=aiohttp.ClientTimeout(
                                     total=5)) as r:
                    if r.status == 200:
                        stitch = (await r.json()).get("stats") or {}
                    else:
                        control_errors.append(
                            f"CAPSTONE GET /fleet/traces -> {r.status}")
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                control_errors.append(
                    f"CAPSTONE /fleet/traces: {type(e).__name__}: {e}")
            # per-pool traffic census from the engines' own counters
            for eng, pool in ([(e, "pool-a") for e in pool_a]
                              + [(e, "pool-b") for e in pool_b]):
                try:
                    async with s.get(f"{eng.url}/load",
                                     timeout=aiohttp.ClientTimeout(
                                         total=5)) as r:
                        mr = (await r.json()).get("model_requests") or {}
                        for m, n in mr.items():
                            pools_served[m] = pools_served.get(m, 0) + n
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError) as e:
                    control_errors.append(
                        f"CAPSTONE {eng.name}/load: "
                        f"{type(e).__name__}: {e}")
        await _settle(procs, [p.name for p in procs], control_errors)
        return {
            "trace": os.path.basename(trace_path),
            "routers": routers,
            "engines_per_pool": engines_per_pool,
            "summary": res.merged_summary,
            "per_worker": res.per_worker,
            "skew": res.skew,
            "violations": res.violations,
            "issued_digest": res.issued_digest,
            "stitch": stitch,
            "pools_served": pools_served,
        }
    finally:
        _stop(procs)
