"""Distributed loadgen coordinator: partition, spawn, merge.

The coordinator owns the three laws the workers must not be trusted
with individually:

1. **Schedule partition** — contiguous session-id shards of the one
   deterministic schedule (synthetic) or ``session_id % N`` shards of
   a trace (replay). Shards are disjoint and covering by construction.
2. **Rate partition** — worker i runs the shared open-loop ramp at
   ``qps_scale = 1/N`` with an independent arrival seed; the merged
   superposition is one Poisson process at the target rate.
3. **Merge-then-quantile** — workers ship RAW records; the coordinator
   folds every sample into one ``LatencyRecordSet`` and only then
   reads percentiles. Per-worker percentiles appear ONLY in the skew
   diagnostics block, labelled as such.

Workers are subprocesses (``python -m ...distributed.worker``) talking
to the stack's public HTTP surface — the same process isolation every
rig in this repo uses, and the same files a multi-host run would ship
over ssh.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

REPO_ROOT = str(Path(__file__).resolve().parents[3])

from production_stack_tpu.loadgen.distributed.shard import (
    WorkerAssignment, shard_sessions, worker_arrival_seed)
from production_stack_tpu.loadgen.distributed.worker import read_records
from production_stack_tpu.loadgen.client import RequestRecord
from production_stack_tpu.loadgen.report import (LatencyRecordSet,
                                                 aggregate)
from production_stack_tpu.loadgen.spec import WorkloadSpec
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


@dataclass
class DistResult:
    """One coordinated run: the merged truth + per-worker evidence."""
    records: List[RequestRecord]
    merged_summary: Dict
    per_worker: List[Dict]
    violations: List[str]
    skew: Dict = field(default_factory=dict)
    issued_digest: Optional[str] = None   # replay runs only

    @property
    def ok(self) -> bool:
        return not self.violations


def synthetic_assignments(spec: WorkloadSpec, base_url: str, *,
                          workers: int,
                          total_sessions: Optional[int] = None,
                          duration_s: Optional[float] = None,
                          api_key: Optional[str] = None,
                          warmup_requests: int = 0
                          ) -> List[WorkerAssignment]:
    """Partition a synthetic workload: session shards + rate shards."""
    spec.validate()
    spec_dict = dataclasses.asdict(spec)
    if total_sessions is None:
        total_sessions = spec.max_sessions
    ranges: List[Tuple[int, Optional[int]]]
    if total_sessions is not None:
        ranges = [(start, end - start)
                  for start, end in shard_sessions(total_sessions,
                                                   workers)]
    else:
        # unbounded (duration-capped) run: give workers disjoint id
        # lanes far apart so shards never collide however many
        # sessions each starts
        ranges = [(i * 10_000_000, None) for i in range(workers)]
    out: List[WorkerAssignment] = []
    for i, (first, count) in enumerate(ranges):
        wspec = json.loads(json.dumps(spec_dict))   # deep copy
        if spec.arrival.mode == "open":
            wspec["arrival"]["qps_scale"] = \
                spec.arrival.qps_scale / workers
        else:
            share = spec.arrival.users // workers + \
                (1 if i < spec.arrival.users % workers else 0)
            wspec["arrival"]["users"] = max(1, share)
        out.append(WorkerAssignment(
            worker_index=i, num_workers=workers, base_url=base_url,
            mode="synthetic", spec=wspec, first_session_id=first,
            session_count=count, duration_s=duration_s,
            arrival_seed=worker_arrival_seed(spec.seed, i),
            api_key=api_key, warmup_requests=warmup_requests))
    return out


def replay_assignments(trace_path: str, base_url: str, *,
                       workers: int, speedup: float = 1.0,
                       api_key: Optional[str] = None
                       ) -> List[WorkerAssignment]:
    return [WorkerAssignment(
        worker_index=i, num_workers=workers, base_url=base_url,
        mode="replay", trace_path=trace_path, speedup=speedup,
        api_key=api_key) for i in range(workers)]


def run_coordinated(assignments: List[WorkerAssignment], *,
                    work_dir: str, timeout_s: float = 900.0,
                    log_prefix: str = "worker") -> DistResult:
    """Spawn one subprocess per assignment, wait, merge raw records.

    A worker that exits nonzero, times out, or leaves no records file
    is a coordinator-level violation (the run measured less than it
    claims) — never silently dropped from the merge.
    """
    os.makedirs(work_dir, exist_ok=True)
    procs: List[Tuple[int, subprocess.Popen, str, str, "object"]] = []
    for asn in assignments:
        asn.validate()
        tag = f"{log_prefix}-{asn.worker_index}"
        asn_path = os.path.join(work_dir, f"{tag}.assignment.json")
        rec_path = os.path.join(work_dir, f"{tag}.records.jsonl")
        sum_path = os.path.join(work_dir, f"{tag}.summary.json")
        with open(asn_path, "w") as f:
            f.write(asn.to_json())
        log = open(os.path.join(work_dir, f"{tag}.log"), "w")
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "production_stack_tpu.loadgen.distributed.worker",
             "--assignment", asn_path, "--records", rec_path,
             "--summary", sum_path],
            stdout=log, stderr=subprocess.STDOUT, cwd=REPO_ROOT,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        procs.append((asn.worker_index, proc, rec_path, sum_path, log))
    violations: List[str] = []
    deadline = time.monotonic() + timeout_s
    merged: List[RequestRecord] = []
    latencies = LatencyRecordSet()
    per_worker: List[Dict] = []
    digests: List[str] = []
    for idx, proc, rec_path, sum_path, log in procs:
        budget = max(1.0, deadline - time.monotonic())
        try:
            rc = proc.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            violations.append(f"DIST worker {idx} timed out after "
                              f"{timeout_s:.0f}s and was killed")
            log.close()
            continue
        log.close()
        if rc != 0:
            violations.append(f"DIST worker {idx} exited {rc} "
                              f"(see {log.name})")
            continue
        if not os.path.exists(rec_path) or not os.path.exists(sum_path):
            violations.append(f"DIST worker {idx} exited 0 but left "
                              f"no records/summary files")
            continue
        records = read_records(rec_path)
        with open(sum_path) as f:
            summary = json.load(f)
        merged.extend(records)
        for r in records:
            latencies.add(r)
        for v in summary.get("violations", []):
            violations.append(f"[worker {idx}] {v}")
        if summary.get("issued_digest"):
            digests.append(summary["issued_digest"])
        ok = [r for r in records if r.ok]
        span = (max((r.finish_time for r in records), default=0.0)
                - min((r.launch_time for r in records), default=0.0))
        per_worker.append({
            "worker_index": idx,
            "launched": summary.get("launched", len(records)),
            "finished": summary.get("finished", len(ok)),
            "errors": summary.get("errors", 0),
            "http_5xx": summary.get("http_5xx", 0),
            "offered_qps": round(len(records) / span, 4)
            if span > 0 else 0.0,
            # per-worker quantiles: skew DIAGNOSTICS only — the
            # merged truth comes from the coordinator's LatencyRecordSet
            "diag_quantiles": LatencyRecordSet.from_records(ok)
            .quantiles(),
        })
    merged_summary = aggregate(merged) if merged else {}
    if merged:
        # the authoritative percentiles: merged raw samples
        merged_summary.update(latencies.quantiles())
    skew: Dict = {}
    rates = [w["offered_qps"] for w in per_worker if w["offered_qps"]]
    if len(rates) > 1:
        skew = {
            "workers": len(per_worker),
            "offered_qps_min": min(rates),
            "offered_qps_max": max(rates),
            "offered_qps_imbalance": round(max(rates) / min(rates), 4)
            if min(rates) > 0 else None,
            "ttft_p50_spread_s": round(
                max(w["diag_quantiles"]["ttft_s"]["p50"]
                    for w in per_worker)
                - min(w["diag_quantiles"]["ttft_s"]["p50"]
                      for w in per_worker), 4),
        }
    issued_digest = None
    if digests:
        # the run's issued multiset = union of worker shards; digests
        # are per-shard, so combine order-independently
        import hashlib
        issued_digest = hashlib.sha256(
            "".join(sorted(digests)).encode()).hexdigest()
    return DistResult(records=merged, merged_summary=merged_summary,
                      per_worker=per_worker, violations=violations,
                      skew=skew, issued_digest=issued_digest)
