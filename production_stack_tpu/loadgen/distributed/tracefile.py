"""Replayable traffic traces: record, validate, synthesize, replay.

Format — ``*.trace.jsonl``, one JSON object per line:

- line 1, the header::

    {"schema": "tpu-loadgen-trace/v1", "name": ..., "seed": ...,
     "requests": N, "sessions": M, "duration_s": ..., "notes": ...}

- every further line, one request of the schedule (offset order)::

    {"offset_s": 1.234, "session_id": 7, "turn_index": 0,
     "kind": "chat", "model": "debug-tiny", "tenant": "acme",
     "question_tokens": 48, "answer_tokens": 96,
     "system_prompt_tokens": 200, "stream": true}

``offset_s`` is seconds since trace start (non-decreasing across the
file); ``turn_index`` is contiguous from 0 within each session;
``tenant`` is optional (absent = untagged traffic). Everything needed
to re-issue the request is ON the line — replay never consults the
spec that produced the trace, so a trace recorded from one stack
replays against any other.

Replay shards sessions across workers by ``session_id % num_workers``
(a session's turns all fire from one worker: multi-turn history and
session-affinity routing key off it) and preserves recorded timing
(``speedup`` compresses it). Two replays of one trace issue the same
request multiset — the determinism gate ``loadgen distload`` enforces.
"""

import asyncio
import hashlib
import heapq
import itertools
import json
import time
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from production_stack_tpu.loadgen.arrival import (arrival_stream,
                                                  replay_stream)
from production_stack_tpu.loadgen.client import LoadClient, RequestRecord
from production_stack_tpu.loadgen.report import aggregate
from production_stack_tpu.loadgen.runner import InvariantTracker
from production_stack_tpu.loadgen.spec import WorkloadSpec
from production_stack_tpu.loadgen.workload import (plan_sessions,
                                                   replay_request_plan)

TRACE_SCHEMA = "tpu-loadgen-trace/v1"

_REQUIRED_FIELDS = ("offset_s", "session_id", "turn_index", "kind",
                    "model", "question_tokens", "answer_tokens")


@dataclass
class TraceRequest:
    """One recorded request: the schedule entry, not the outcome."""
    offset_s: float
    session_id: int
    turn_index: int
    kind: str
    model: str
    question_tokens: int
    answer_tokens: int
    system_prompt_tokens: int = 0
    tenant: Optional[str] = None
    stream: bool = True

    def to_line(self) -> Dict:
        d = asdict(self)
        if d["tenant"] is None:
            del d["tenant"]              # absent, not null: smaller files
        return d


def write_trace(path: str, header: Dict,
                requests: List[TraceRequest]) -> str:
    """Write header + requests (sorted by offset, ties by session/turn
    so the file is byte-deterministic). Fills the header's counts."""
    reqs = sorted(requests, key=lambda r: (r.offset_s, r.session_id,
                                           r.turn_index))
    hdr = {"schema": TRACE_SCHEMA, **header}
    hdr["requests"] = len(reqs)
    hdr["sessions"] = len({r.session_id for r in reqs})
    hdr["duration_s"] = round(reqs[-1].offset_s, 3) if reqs else 0.0
    with open(path, "w") as f:
        f.write(json.dumps(hdr, sort_keys=True) + "\n")
        for r in reqs:
            f.write(json.dumps(r.to_line(), sort_keys=True) + "\n")
    return path


def read_trace(path: str) -> Tuple[Dict, List[TraceRequest]]:
    """Parse + validate: schema version, required fields, offsets
    non-decreasing, per-session turn indexes contiguous from 0. A trace
    that fails any of these would replay as a DIFFERENT workload than
    it claims — refuse it loudly."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty trace")
    header = json.loads(lines[0])
    if header.get("schema") != TRACE_SCHEMA:
        raise ValueError(f"{path}: schema {header.get('schema')!r} != "
                         f"{TRACE_SCHEMA!r}")
    out: List[TraceRequest] = []
    prev_off = 0.0
    turn_seen: Dict[int, int] = {}
    for i, ln in enumerate(lines[1:], start=2):
        d = json.loads(ln)
        missing = [k for k in _REQUIRED_FIELDS if k not in d]
        if missing:
            raise ValueError(f"{path}:{i}: missing fields {missing}")
        r = TraceRequest(**d)
        if r.offset_s < prev_off - 1e-9:
            raise ValueError(f"{path}:{i}: offset {r.offset_s} before "
                             f"previous {prev_off}")
        prev_off = max(prev_off, r.offset_s)
        expect = turn_seen.get(r.session_id, 0)
        if r.turn_index != expect:
            raise ValueError(
                f"{path}:{i}: session {r.session_id} turn "
                f"{r.turn_index}, expected {expect} (turns must be "
                f"contiguous from 0)")
        turn_seen[r.session_id] = expect + 1
        out.append(r)
    declared = header.get("requests")
    if declared is not None and declared != len(out):
        raise ValueError(f"{path}: header claims {declared} requests, "
                         f"file has {len(out)}")
    return header, out


def trace_from_records(records: Iterable[RequestRecord],
                       spec: WorkloadSpec) -> List[TraceRequest]:
    """The recorder: any run's records -> its replayable schedule.

    Arrival offsets come from the measured launch times (so a replay
    reproduces the run's REAL arrival process — queueing delays the
    open loop imposed and all); per-turn shapes are re-derived from the
    spec's deterministic plan (records only carry the total prompt
    size, not the turn split)."""
    recs = [r for r in records if not r.cancelled]
    if not recs:
        return []
    t0 = min(r.launch_time for r in recs)
    plans = {}
    out: List[TraceRequest] = []
    for r in recs:
        if r.session_id not in plans:
            plans[r.session_id] = plan_sessions(spec, 1,
                                                first_id=r.session_id)[0]
        plan = plans[r.session_id]
        if r.turn_index >= len(plan.turns):
            raise ValueError(f"record turn {r.turn_index} beyond "
                             f"session {r.session_id}'s plan")
        turn = plan.turns[r.turn_index]
        out.append(TraceRequest(
            offset_s=round(r.launch_time - t0, 4),
            session_id=r.session_id, turn_index=r.turn_index,
            kind=r.kind,
            model=spec.lora_model if r.kind == "lora" else spec.model,
            question_tokens=turn.question_tokens,
            answer_tokens=turn.answer_tokens,
            system_prompt_tokens=0 if r.kind == "embeddings"
            else spec.session.system_prompt_tokens,
            stream=r.kind != "embeddings"))
    return out


def synthesize_trace(spec: WorkloadSpec, *,
                     duration_s: float,
                     tenants: Optional[List[Tuple[str, float]]] = None,
                     stages: Optional[List[Tuple[float, float]]] = None,
                     service_s_per_token: float = 0.02,
                     service_floor_s: float = 0.2
                     ) -> List[TraceRequest]:
    """A production-shaped schedule synthesized WITHOUT running load:
    arrival offsets from the spec's open-loop stages (the diurnal ramp
    lives in the stages), sessions admitted/resumed by a deterministic
    service model (a session's next turn becomes eligible
    ``service_floor_s + answer_tokens * service_s_per_token`` after the
    previous one fired — the service/think gap a real closed session
    shows). ``tenants`` (name, weight) tags each session by a
    deterministic per-session draw — skewed weights make one tenant
    bursty. ``stages`` overrides the spec's ramp with explicit
    (qps, duration_s) segments — ``ArrivalSpec`` only ramps upward,
    but a diurnal curve goes up AND back down."""
    spec.validate()
    if stages is None:
        stages = spec.arrival.stages()
    import random
    rng = random.Random((spec.seed << 8) ^ 0xa441)
    # (eligible_at, seq, session_state) — seq breaks ties determinist.
    ready: List[Tuple[float, int, Dict]] = []
    seq = itertools.count()
    next_sid = 0
    out: List[TraceRequest] = []

    def tenant_for(sid: int) -> Optional[str]:
        if not tenants:
            return None
        trng = random.Random((spec.seed << 24) ^ sid ^ 0x7E4A)
        names = [n for n, _ in tenants]
        weights = [w for _, w in tenants]
        return trng.choices(names, weights)[0]

    for offset, _qps in arrival_stream(rng, stages):
        if offset >= duration_s:
            break
        state = None
        if ready and ready[0][0] <= offset:
            _, _, state = heapq.heappop(ready)
        if state is None:
            plan = plan_sessions(spec, 1, first_id=next_sid)[0]
            next_sid += 1
            state = {"plan": plan, "turn": 0,
                     "tenant": tenant_for(plan.session_id)}
        plan, turn_i = state["plan"], state["turn"]
        turn = plan.turns[turn_i]
        out.append(TraceRequest(
            offset_s=round(offset, 4),
            session_id=plan.session_id, turn_index=turn_i,
            kind=turn.kind,
            model=spec.lora_model if turn.kind == "lora" else spec.model,
            question_tokens=turn.question_tokens,
            answer_tokens=turn.answer_tokens,
            system_prompt_tokens=0 if turn.kind == "embeddings"
            else spec.session.system_prompt_tokens,
            tenant=state["tenant"],
            stream=turn.kind != "embeddings"))
        state["turn"] += 1
        if state["turn"] < len(plan.turns):
            eligible = offset + service_floor_s + \
                turn.answer_tokens * service_s_per_token
            heapq.heappush(ready, (eligible, next(seq), state))
    return out


def merge_traces(parts: List[List[TraceRequest]], *,
                 session_stride: int = 1_000_000) -> List[TraceRequest]:
    """Superpose independently-synthesized schedules into one trace
    (e.g. chat on model-a + batch on model-b as one fleet workload).
    Part i's session ids are re-based to ``i * session_stride`` so
    sessions never collide; offsets are kept as-is — the parts
    interleave in time exactly as they would as concurrent tenants."""
    out: List[TraceRequest] = []
    for i, part in enumerate(parts):
        for r in part:
            d = asdict(r)
            d["session_id"] = i * session_stride + r.session_id
            out.append(TraceRequest(**d))
    out.sort(key=lambda r: (r.offset_s, r.session_id, r.turn_index))
    return out


def issued_key(r: TraceRequest) -> Tuple:
    """The identity of a request for the determinism gate: everything
    that reaches the wire except timing."""
    return (r.session_id, r.turn_index, r.kind, r.model,
            r.question_tokens, r.answer_tokens, r.tenant or "")


def multiset_digest(keys: Iterable[Tuple]) -> str:
    """Order-independent digest of an issued-request multiset: two
    replays match iff their digests match."""
    blob = json.dumps(sorted(list(k) for k in keys)).encode()
    return hashlib.sha256(blob).hexdigest()


async def replay_workload(requests: List[TraceRequest], base_url: str, *,
                          worker_index: int = 0, num_workers: int = 1,
                          speedup: float = 1.0,
                          api_key: Optional[str] = None,
                          request_timeout_s: float = 600.0,
                          extra_headers: Optional[Dict[str, str]] = None
                          ) -> Dict:
    """Re-issue this worker's shard of a trace with recorded timing.

    Shard = lines whose ``session_id % num_workers == worker_index``.
    Turns within a session fire in order (a turn whose offset arrives
    while the previous turn is still in flight waits for it — exactly
    what the original closed session did). Returns ``{"records",
    "summary", "violations", "issued_digest", "issued": n}``.
    """
    if speedup <= 0:
        raise ValueError(f"speedup must be positive, got {speedup}")
    mine = [r for r in requests
            if r.session_id % num_workers == worker_index]
    by_session: Dict[int, List[TraceRequest]] = {}
    for r in mine:
        by_session.setdefault(r.session_id, []).append(r)
    client = LoadClient(base_url, api_key=api_key,
                        request_timeout_s=request_timeout_s)
    tracker = InvariantTracker()
    records: List[RequestRecord] = []
    ids = itertools.count()
    prev_task: Dict[int, asyncio.Task] = {}
    in_flight: List[asyncio.Task] = []
    issued: List[Tuple] = []
    await client.start()
    try:
        t0 = time.monotonic()
        ordered = sorted(mine, key=lambda x: (x.offset_s, x.session_id,
                                              x.turn_index))
        arrivals = replay_stream((x.offset_s for x in ordered), speedup)
        for (target, _qps), r in zip(arrivals, ordered):
            delay = t0 + target - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            sess = by_session[r.session_id]
            prior = [{"question_tokens": t.question_tokens,
                      "answer_tokens": t.answer_tokens}
                     for t in sess if t.turn_index < r.turn_index]
            plan = replay_request_plan(
                session_id=r.session_id, turn_index=r.turn_index,
                kind=r.kind, model=r.model,
                question_tokens=r.question_tokens,
                answer_tokens=r.answer_tokens,
                system_prompt_tokens=r.system_prompt_tokens,
                prior_turns=prior, tenant=r.tenant, stream=r.stream)
            if extra_headers:
                plan.headers.update(extra_headers)
            issued.append(issued_key(r))
            wait_for = prev_task.get(r.session_id)

            async def fire(plan=plan, wait_for=wait_for) -> None:
                if wait_for is not None:
                    # in-order within the session: the recorded offset
                    # is the earliest fire time, not a license to
                    # overtake the previous turn
                    await asyncio.wait({wait_for})
                rid = next(ids)
                tracker.on_launch(rid)
                rec = await client.execute(plan, rid)
                rec.body = ""
                records.append(rec)
                tracker.on_complete(rec)

            task = asyncio.create_task(fire())
            prev_task[r.session_id] = task
            in_flight.append(task)
        if in_flight:
            await asyncio.gather(*in_flight)
    finally:
        await client.close()
    violations = tracker.finalize(records)
    return {"records": records,
            "summary": aggregate(records),
            "violations": violations,
            "issued": len(issued),
            "issued_digest": multiset_digest(issued)}
