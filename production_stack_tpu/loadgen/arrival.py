"""Arrival processes: when requests launch.

Pure time-generation (no IO) so the statistics are unit-testable: the
drivers in runner.py consume these offsets and do the sleeping.

Open-loop arrivals are a Poisson process — exponential interarrivals at
rate qps — because that is the arrival model under which serving
latency distributions mean anything (requests keep coming while the
server is slow; a closed loop self-throttles and hides the queue). The
QPS ramp concatenates stages, each its own Poisson segment.
"""

import random
from typing import Iterable, Iterator, List, Sequence, Tuple


def poisson_times(rng: random.Random, qps: float,
                  duration_s: float) -> List[float]:
    """Arrival offsets in [0, duration_s) of a Poisson process at rate
    ``qps`` (exponential interarrivals, mean 1/qps)."""
    if qps <= 0:
        raise ValueError(f"qps must be positive, got {qps}")
    out: List[float] = []
    t = rng.expovariate(qps)
    while t < duration_s:
        out.append(t)
        t += rng.expovariate(qps)
    return out


def ramp_times(rng: random.Random,
               stages: Sequence[Tuple[float, float]]
               ) -> List[Tuple[float, float]]:
    """Concatenated Poisson stages -> [(absolute_offset, stage_qps)].

    Each stage (qps, duration_s) contributes its own Poisson arrivals,
    shifted by the cumulative duration of prior stages — the reference
    run.sh QPS 0.1→4.1 sweep as one continuous open-loop schedule.
    """
    out: List[Tuple[float, float]] = []
    base = 0.0
    for qps, duration in stages:
        out.extend((base + t, qps) for t in poisson_times(rng, qps,
                                                          duration))
        base += duration
    return out


def arrival_stream(rng: random.Random,
                   stages: Sequence[Tuple[float, float]],
                   repeat_last: bool = False
                   ) -> Iterator[Tuple[float, float]]:
    """Lazily yield (absolute_offset, qps); with ``repeat_last`` the
    final stage extends forever (duration-bounded soaks outlive the
    declared ramp)."""
    base = 0.0
    stages = list(stages)
    while stages:
        qps, duration = stages.pop(0)
        for t in poisson_times(rng, qps, duration):
            yield (base + t, qps)
        base += duration
        if repeat_last and not stages:
            stages = [(qps, duration)]


def replay_stream(offsets: Iterable[float],
                  speedup: float = 1.0) -> Iterator[Tuple[float, float]]:
    """Recorded arrival offsets as an arrival source: yields
    (absolute_offset, instantaneous_qps_estimate) in the same shape as
    ``arrival_stream`` so drivers consume traces and synthetic ramps
    identically. ``speedup`` > 1 compresses the recorded timeline
    (replay an hour of production in minutes); the qps estimate is the
    reciprocal of the (scaled) gap to the previous arrival — good
    enough for checkpoint lines, never used for pacing."""
    if speedup <= 0:
        raise ValueError(f"speedup must be positive, got {speedup}")
    prev = None
    for off in offsets:
        t = off / speedup
        if prev is not None and t < prev:
            raise ValueError(
                f"replay offsets must be non-decreasing, got {t:.6f} "
                f"after {prev:.6f}")
        gap = (t - prev) if prev is not None else t
        yield (t, round(1.0 / gap, 6) if gap > 0 else 0.0)
        prev = t
