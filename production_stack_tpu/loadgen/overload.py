"""Overload sweep: offered QPS pushed past saturation, goodput measured.

The overload-protection layer's closed loop (ISSUE 4). The orchestrator
launches the real router in front of N engines — real ``debug-tiny``
processes started WITH the protection flags (``--max-waiting-seqs``,
``--max-queue-delay-ms``), or fakes in ``overload`` fault mode — then
drives an OPEN-loop arrival process (fixed offered QPS, concurrency
unbounded: exactly the regime closed-loop storms cannot produce) at a
sweep of rates from below to well past the knee. Every request carries
an ``x-request-deadline-ms`` budget.

Per-point outcome classes:

- ``ok``          — HTTP 200, completed; *goodput* counts only the oks
  that finished **within their deadline** (an accepted-then-late answer
  is worthless to the client that set the budget).
- ``ok_late``     — 200 but past the deadline. The protected stack's
  contract is that this stays ZERO: anything the stack accepts, it
  finishes in budget; everything else it sheds up front.
- ``shed``        — 429/503 with Retry-After (router gate, endpoint
  cap, or engine bounded admission / queue-delay cap — the headroom
  valve) and 504 + x-deadline-expired (WAITING-drop). Expected and
  healthy past the knee.
- ``error``       — any other 5xx / transport failure. Always a bug.

``overload_violations`` encodes the acceptance contract: goodput must
plateau (within ``plateau_tolerance`` of its peak at every offered rate
past the knee) instead of collapsing, zero accepted requests may
violate their deadline, the sweep must actually saturate (sheds > 0 at
the top rate), and nothing may 5xx. Committed records are
``OVERLOAD_*.json`` (BENCH schema); reproduction one-liners live in
docs/benchmarks.md "Overload: goodput under saturation".
"""

import asyncio
import json
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (_stop, free_port,
                                                       launch_engine,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.loadgen.report import percentile
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"

# protection knobs for the engines under test (real-engine mode); the
# unprotected "before" curve launches without them
ENGINE_PROTECTION_ARGS = ["--max-waiting-seqs", "8",
                          "--max-queue-delay-ms", "4000"]
ROUTER_OVERLOAD_ARGS = ["--failover-attempts", "3"]


class _PointCounters:
    def __init__(self):
        self.launched = 0
        self.ok = 0
        self.ok_late = 0
        self.shed_503 = 0
        self.shed_429 = 0
        self.shed_504_deadline = 0
        self.errors = 0
        self.latencies: List[float] = []     # e2e of in-deadline oks
        self.samples: List[str] = []

    def sample(self, text: str) -> None:
        if len(self.samples) < 6:
            self.samples.append(text[:160])


async def _one_request(session: aiohttp.ClientSession, url: str,
                       payload: bytes, deadline_ms: float,
                       timeout: aiohttp.ClientTimeout,
                       c: _PointCounters) -> None:
    t0 = time.monotonic()
    try:
        async with session.post(
                f"{url}{CHAT_PATH}", data=payload,
                headers={"Content-Type": "application/json",
                         "x-request-deadline-ms": str(int(deadline_ms))},
                timeout=timeout) as resp:
            body = await resp.read()
            e2e = time.monotonic() - t0
            if resp.status == 200:
                if e2e <= deadline_ms / 1e3:
                    c.ok += 1
                    c.latencies.append(e2e)
                else:
                    c.ok_late += 1
                    c.sample(f"accepted but late: {e2e * 1e3:.0f}ms > "
                             f"{deadline_ms:.0f}ms budget")
            elif resp.status in (429, 503) and \
                    "Retry-After" in resp.headers:
                if resp.status == 429:
                    c.shed_429 += 1
                else:
                    c.shed_503 += 1
            elif resp.status == 504 and \
                    "x-deadline-expired" in resp.headers:
                c.shed_504_deadline += 1
            else:
                c.errors += 1
                c.sample(f"HTTP {resp.status}: "
                         f"{body[:120].decode('utf-8', 'replace')}")
    except (aiohttp.ClientError, ConnectionError, OSError,
            asyncio.TimeoutError) as e:
        c.errors += 1
        c.sample(f"{type(e).__name__}: {e}")


async def measure_point(url: str, model: str, *, qps: float,
                        duration_s: float, deadline_ms: float,
                        num_tokens: int,
                        settle_s: float = 2.0) -> Dict:
    """One open-loop point: fire at ``qps`` for ``duration_s`` (fixed
    inter-arrival 1/qps — the rate, not the burstiness, is the variable
    under test), then wait for stragglers up to the deadline."""
    c = _PointCounters()
    payload = json.dumps({
        "model": model,
        "messages": [{"role": "user", "content": "overload probe"}],
        "max_tokens": num_tokens,
    }).encode()
    # client timeout well past the deadline: a stack that neither
    # answers nor sheds within 5x budget shows up as an error, not a
    # hang
    timeout = aiohttp.ClientTimeout(
        total=max(30.0, 5.0 * deadline_ms / 1e3))
    tasks: List[asyncio.Task] = []
    interval = 1.0 / qps
    async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0)) as session:
        t0 = time.monotonic()
        next_at = t0
        while True:
            now = time.monotonic()
            if now >= t0 + duration_s:
                break
            if now < next_at:
                await asyncio.sleep(next_at - now)
            next_at += interval
            c.launched += 1
            tasks.append(asyncio.create_task(_one_request(
                session, url, payload, deadline_ms, timeout, c)))
        # the offered window ends here; stragglers drain afterwards.
        # Rates divide by the LAUNCH window, not launch+drain — drain
        # length scales with queue depth, so folding it in would
        # deflate the saturated points relative to the light ones and
        # fake a plateau violation.
        launch_elapsed = time.monotonic() - t0
        if tasks:
            await asyncio.wait(tasks,
                               timeout=timeout.total + settle_s)
        for t in tasks:
            if not t.done():
                t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        drain_elapsed = time.monotonic() - t0 - launch_elapsed
    shed = c.shed_429 + c.shed_503 + c.shed_504_deadline
    elapsed = launch_elapsed
    return {
        "offered_qps": round(qps, 3),
        "duration_s": round(launch_elapsed, 2),
        "drain_s": round(drain_elapsed, 2),
        "launched": c.launched,
        "ok": c.ok,
        "ok_late": c.ok_late,
        "shed": shed,
        "shed_429": c.shed_429,
        "shed_503": c.shed_503,
        "shed_504_deadline": c.shed_504_deadline,
        "errors": c.errors,
        "goodput_qps": round(c.ok / max(elapsed, 1e-9), 3),
        "shed_rate": round(shed / max(c.launched, 1), 4),
        "accepted_p50_ms": round(
            1e3 * percentile(c.latencies, 50), 1),
        "accepted_p99_ms": round(
            1e3 * percentile(c.latencies, 99), 1),
        "error_samples": c.samples,
    }


def overload_violations(record: Dict,
                        plateau_tolerance: float = 0.10) -> List[str]:
    """The sweep's pass/fail contract (CLI exits 1 on any)."""
    d = record["detail"]
    points = d["points"]
    out = []
    late = sum(p["ok_late"] for p in points)
    if late:
        out.append(f"{late} accepted requests finished past their "
                   f"deadline (accepted => in-budget must hold)")
    errors = sum(p["errors"] for p in points)
    if errors:
        out.append(f"{errors} non-shed errors (sheds are structured "
                   f"429/503/504; anything else is a bug)")
    if not points:
        return out + ["no points measured"]
    if points[-1]["shed"] == 0:
        out.append("the top offered rate never shed: the sweep did "
                   "not reach saturation (raise --qps)")
    peak = max(p["goodput_qps"] for p in points)
    knee_idx = max(range(len(points)),
                   key=lambda i: points[i]["goodput_qps"])
    floor = (1.0 - plateau_tolerance) * peak
    for p in points[knee_idx + 1:]:
        if p["goodput_qps"] < floor:
            out.append(
                f"goodput collapsed past the knee: {p['goodput_qps']} "
                f"qps at offered {p['offered_qps']} (< {floor:.2f}, "
                f"{100 * plateau_tolerance:.0f}% under the "
                f"{peak} peak)")
    return out


async def run_overload(*, engines: int = 2,
                       engine: str = "fake",
                       qps_points: Optional[List[float]] = None,
                       duration_s: float = 15.0,
                       deadline_ms: float = 8000.0,
                       num_tokens: int = 8,
                       fake_capacity: int = 4,
                       fake_tokens_per_s: float = 50.0,
                       unprotected: bool = False,
                       plateau_tolerance: float = 0.10,
                       platform: str = "cpu",
                       log_dir: str = "loadgen-logs",
                       startup_timeout_s: float = 420.0,
                       router_extra_args: Optional[List[str]] = None
                       ) -> Dict:
    """Launch router + N engines and sweep offered QPS; return the
    OVERLOAD record (BENCH schema; headline = peak goodput)."""
    if qps_points is None:
        qps_points = [2.0, 4.0, 8.0, 16.0]
    procs = []
    try:
        extra = None
        if engine == "fake":
            # bounded fake queue: the overload fault mode IS the
            # protection under test on the fake path. Service time is
            # modeled as TTFT (the fake only paces token emission on
            # streaming responses; the sweep posts non-streaming)
            service_s = num_tokens / max(fake_tokens_per_s, 1e-9)
            extra = ["--ttft", f"{service_s:.4f}",
                     "--num-tokens", str(num_tokens)]
            if not unprotected:
                extra += ["--fault", "overload",
                          "--fault-arg", str(fake_capacity)]
        elif not unprotected:
            extra = list(ENGINE_PROTECTION_ARGS)
        engine_procs = [launch_engine(engine, free_port(),
                                      log_dir=log_dir,
                                      platform=platform,
                                      extra_args=extra)
                        for _ in range(engines)]
        procs.extend(engine_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in engine_procs])
        model = "fake-model" if engine == "fake" else engine
        router = launch_router(
            [e.url for e in engine_procs], model, free_port(),
            routing="least_loaded", log_dir=log_dir,
            extra_args=(ROUTER_OVERLOAD_ARGS
                        + ["--engine-stats-interval", "1"]
                        + (router_extra_args or [])))
        procs.append(router)
        await wait_healthy(router.url, 60.0, require_endpoints=engines)
        if engine == "fake" and not unprotected:
            # give the stats scraper one interval to pick up the
            # advertised capacity before the first point
            await asyncio.sleep(1.5)

        points: List[Dict] = []
        for qps in qps_points:
            logger.info("overload point: %.1f qps offered for %.0fs "
                        "(deadline %.0fms)", qps, duration_s,
                        deadline_ms)
            p = await measure_point(router.url, model, qps=qps,
                                    duration_s=duration_s,
                                    deadline_ms=deadline_ms,
                                    num_tokens=num_tokens)
            points.append(p)
            logger.info("  -> goodput %.2f qps, %d ok / %d late / "
                        "%d shed / %d errors, accepted p99 %.0fms",
                        p["goodput_qps"], p["ok"], p["ok_late"],
                        p["shed"], p["errors"], p["accepted_p99_ms"])
            await asyncio.sleep(1.0)     # drain between points
    finally:
        _stop(procs)

    peak = max((p["goodput_qps"] for p in points), default=0.0)
    return {
        "metric": "goodput (accepted-and-in-deadline qps) vs offered "
                  "qps past saturation "
                  + ("(UNPROTECTED baseline)" if unprotected else
                     "(overload protection on)"),
        "value": peak,
        "unit": "goodput_qps",
        "platform": platform,
        "detail": {
            "engine": engine, "engines": engines,
            "protected": not unprotected,
            "deadline_ms": deadline_ms,
            "num_tokens": num_tokens,
            "duration_s_per_point": duration_s,
            "plateau_tolerance": plateau_tolerance,
            "engine_args": (None if unprotected else
                            (f"overload fault, capacity {fake_capacity}"
                             if engine == "fake"
                             else " ".join(ENGINE_PROTECTION_ARGS))),
            "points": points,
        },
    }
