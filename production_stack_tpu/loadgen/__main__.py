"""CLI: python -m production_stack_tpu.loadgen
{run,soak,scaleout,overhead,chaos,overload}

run      — drive a workload (preset or --spec JSON file) against a
           running stack; print + write a BENCH-schema JSON report
soak     — duration-bounded mixed-traffic run with invariant checks,
           abort injection, and periodic checkpoint lines; exit 1 on
           any invariant violation
scaleout — launch real router+engine processes at N=1,2,4,... and
           write the aggregate-tokens/s-vs-replicas SCALEOUT_*.json
overhead — launch one engine + the router, drive the identical
           closed-loop storm at both URLs, report router-vs-direct
           req/s and the overhead ratio (ROUTER_OVERHEAD_*.json)
chaos    — launch the router + N engines and kill/restart engines on
           a schedule while storming the router; exit 1 on any
           client-visible 5xx / router transport error
           (CHAOS_*.json)
overload — launch router + N engines (with overload protection) and
           sweep open-loop offered QPS past saturation; exit 1 unless
           goodput plateaus, zero accepted requests violate their
           deadline, and nothing 5xxes (OVERLOAD_*.json)
autoscale — launch router + autoscaler-owned engines and drive an
           open-loop QPS ramp up then down; replicas must track the
           ramp (1 -> N -> 1) with zero client-visible 5xx across
           every scale-up and drain-based scale-down, goodput at the
           peak must track offered load and beat the fixed-N
           comparison baseline (AUTOSCALE_*.json)
kvshare  — launch a shared TPKV cache server + N engines wired to it
           + the router with session affinity deliberately broken;
           drive multi-round QA and exit 1 unless the cross-replica
           tier hit rate clears 60% AND follow-up-round TTFT beats
           the recompute baseline (KVSHARE_*.json)
disagg   — launch the P/D split (cache server + prefill pool + decode
           pool + router with --prefill-backends) AND the aggregated
           baseline at equal engine count; drive a mixed long-prefill/
           short-decode storm at both (SIGKILLing a prefill pod
           mid-run) and exit 1 unless chat ITL p99 improves with zero
           client-visible errors (DISAGG_*.json)
firedrill — launch router + N engines with SLO windows scaled to
           seconds, storm a clean baseline (zero alerts may fire),
           then inject fault scenarios (partial 500s, engine SIGKILL,
           TTFT inflation, overload storm, queue-delay override); each
           must fire its expected burn-rate alert within the detection
           bound and resolve after the fault clears; exit 1 on any
           miss, false fire, or non-resolution (FIREDRILL_*.json;
           --overhead-guard re-runs the r7 A/B with SLO accounting on)
effwatch — launch ONE engine and audit its efficiency accounting
           around a steady storm: real+pad+dead token-step deltas must
           sum to the independent total within tolerance, accounted
           decode tokens/s must reconcile with client-measured
           throughput within 10%, and zero XLA compile events may land
           in the post-warmup steady window; --anti-vacuity mis-sizes
           the accounting window and must fail (EFF_*.json)
multirouter — launch N peered router replicas (breaker/drain gossip,
           QoS tiers, apportioned caps) behind an in-process L4
           splitter; exit 1 unless pair affinity matches the
           single-router control within tolerance, breaker state
           converges across replicas within one probe interval, a
           router SIGKILL costs only the counted in-flight blip, and
           a saturation sweep holds tier-0 goodput while tier-2
           sheds (MULTIROUTER_*.json; --no-shared-state must fail
           the affinity gate)
multitenant — launch TWO named pools (model-a + runtime LoRA
           adapters, model-b) behind one pooled router, each with its
           own per-pool autoscaler sharing one actuation budget; exit
           1 unless routing is 100%% model-correct against strict
           engines, pool-b goodput holds through pool-a's adapter
           churn + engine SIGKILL with zero errors, a bursting tenant
           is shed >=50%% while same-tier peers hold >=95%% goodput,
           and BOTH pool labels appear as applied scale-ups in the
           decision log (TENANT_*.json; --no-tenant-buckets must fail
           the peer-goodput gate)
trace    — launch router + engines (optionally the disagg split),
           storm them, and join client x-trace-ids against the
           router's and engines' /debug/traces rings; exit 1 unless
           >=95%% of sampled requests have a complete span chain,
           unattributed time is <10%% at p50, and nothing errored
           (TRACE_*.json; --overhead-guard re-runs the r7 A/B with
           tracing on)
incident — launch N peered routers + M engines + the obsplane fleet
           flight recorder; a clean baseline must capture zero
           incident bundles while the online stitcher joins chains,
           then each injected fault (one-engine TTFT inflation,
           engine SIGKILL, an aimed shed storm) must fire its alert,
           yield exactly one complete bundle (every fleet process
           represented), and the bundle's attribution must name the
           injected culprit process and the correct phase; exit 1 on
           any spurious capture, miss, or wrong attribution
           (INCIDENT_*.json; --overhead-guard runs the r7 A/B with
           and without the obsplane scraping the serving pair)
fleetdrill — the r20 fleet-pilot closed loop: (1) the same latency
           burn run twice — burn-rate-driven pilot vs queue-delay-only
           control — the pilot must scale on the page alert (reason
           burn_rate, signal source fleet) and resolve with zero shed
           at LOWER replica-seconds; (2) a slow engine must be
           detected, drained, restarted and verified hands-off with
           EXACTLY ONE remediation in the decision log; (3) the same
           injection with the kill-switch down must log
           suppressed_killswitch while the alert keeps burning
           (FLEETDRILL_*.json)
distload — distributed load generation closed loop: launch router +
           fake engines, drive the same open-loop workload as ONE
           worker (control) and as N coordinator-sharded worker
           processes at qps/N each; exit 1 unless the merged offered
           load and merge-then-quantile percentiles match the control
           within tolerance with zero errors, two sharded replays of
           the committed trace issue identical request multisets, and
           (unless --no-capstone) 2 peered pool-routers + the two-pool
           fleet + obsplane under replayed mixed traffic stitch >=95%
           complete chains with zero raw 5xx; the record embeds a
           mismatched-rate sub-run that must FAIL the scaling gate
           (DISTLOAD_*.json; --anti-vacuity must exit 1)
kvmigrate — the kvplane closed loop: a fragmentation storm (one
           replica's pool injected into the fragmented-admission
           regime behind the router) run with and without the kvplane
           planner — migration ON must collapse the engine-census
           fragmented-failure rate to ~0 in the second half at
           constant aggregate blocks, migration OFF must keep failing
           (anti-vacuity) — plus the kvshare storm re-run through the
           raw vs int4 tier codecs: >=2x logical/physical capacity at
           equal bytes with hit TTFT within tolerance
           (KVMIGRATE_*.json)

Reproduction one-liners live in docs/benchmarks.md and BASELINE.md.
"""

import argparse
import asyncio
import json
import re
import sys
import time

from production_stack_tpu.loadgen import report as report_mod
from production_stack_tpu.loadgen.autoscale import (autoscale_violations,
                                                    run_autoscale)
from production_stack_tpu.loadgen.chaos import chaos_violations, run_chaos
from production_stack_tpu.loadgen.disagg import (disagg_violations,
                                                 run_disagg)
from production_stack_tpu.loadgen.distributed.distload import (
    add_cli_args as distload_cli_args, distload_violations, run_distload)
from production_stack_tpu.loadgen.distributed.tracefile import (
    trace_from_records, write_trace)
from production_stack_tpu.loadgen.effwatch import (effwatch_ab_violations,
                                                   effwatch_violations,
                                                   run_effwatch,
                                                   run_effwatch_ab)
from production_stack_tpu.loadgen.firedrill import (SCENARIO_NAMES,
                                                    firedrill_violations,
                                                    run_firedrill)
from production_stack_tpu.loadgen.fleetdrill import (
    SCENARIO_NAMES as FLEETDRILL_SCENARIOS, fleetdrill_violations,
    run_fleetdrill)
from production_stack_tpu.loadgen.incident import (
    SCENARIO_NAMES as INCIDENT_SCENARIOS, incident_violations,
    run_incident)
from production_stack_tpu.loadgen.kvmigrate import (kvmigrate_violations,
                                                    run_kvmigrate)
from production_stack_tpu.loadgen.kvshare import (kvshare_violations,
                                                  run_kvshare)
from production_stack_tpu.loadgen.multirouter import (
    multirouter_violations, run_multirouter)
from production_stack_tpu.loadgen.multitenant import (
    multitenant_violations, run_multitenant)
from production_stack_tpu.loadgen.orchestrator import run_scaleout
from production_stack_tpu.loadgen.overhead import run_overhead
from production_stack_tpu.loadgen.overload import (overload_violations,
                                                   run_overload)
from production_stack_tpu.loadgen.runner import run_workload
from production_stack_tpu.loadgen.spec import WorkloadSpec, preset
from production_stack_tpu.loadgen.trace import run_trace, trace_violations


def parse_duration(text: str) -> float:
    """'120', '120s', '5m', '4.4h' -> seconds."""
    m = re.fullmatch(r"\s*([0-9.]+)\s*([smh]?)\s*", text)
    if not m:
        raise argparse.ArgumentTypeError(f"bad duration {text!r}")
    mult = {"": 1.0, "s": 1.0, "m": 60.0, "h": 3600.0}[m.group(2)]
    return float(m.group(1)) * mult


def _load_spec(args) -> WorkloadSpec:
    if getattr(args, "spec", None):
        spec = WorkloadSpec.from_file(args.spec)
    else:
        spec = preset(args.workload)
    if getattr(args, "model", None):
        spec.model = args.model
    if getattr(args, "seed", None) is not None:
        spec.seed = args.seed
    if getattr(args, "users", None) is not None:
        spec.arrival.users = args.users
    return spec.validate()


def _print_report(result, out: dict) -> None:
    print(json.dumps(out, indent=2))
    if result.violations:
        print(f"INVARIANT VIOLATIONS ({len(result.violations)}):",
              file=sys.stderr)
        for v in result.violations[:20]:
            print(f"  - {v}", file=sys.stderr)


def _record_trace(result, spec, path: str) -> None:
    """The recorder leg of the distributed-loadgen loop: dump the run's
    per-request schedule (measured arrival offsets + planned shapes) as
    a replayable ``*.trace.jsonl``."""
    reqs = trace_from_records(result.records, spec)
    write_trace(path, {"name": spec.name, "seed": spec.seed,
                       "notes": f"recorded from a live {spec.name} run "
                                f"({spec.arrival.mode}-loop)"}, reqs)
    print(f"recorded {len(reqs)} requests to {path} (replay: loadgen "
          f"distload --trace {path}, or distributed.worker in replay "
          f"mode)", file=sys.stderr)


def cmd_run(args) -> int:
    spec = _load_spec(args)
    result = asyncio.run(run_workload(
        spec, args.base_url, api_key=args.api_key,
        duration_s=args.duration, max_sessions=args.max_sessions,
        checkpoint_interval_s=args.checkpoint_interval))
    out = report_mod.bench_schema(
        f"loadgen {spec.name} ({spec.arrival.mode}-loop) via "
        f"{args.base_url}", result.summary,
        detail={"workload": spec.name, "seed": spec.seed,
                "model": spec.model, "arrival_mode": spec.arrival.mode})
    if args.output:
        report_mod.write_json(args.output, out)
    if args.record_trace:
        _record_trace(result, spec, args.record_trace)
    _print_report(result, out)
    return 0 if result.ok else 1


def cmd_soak(args) -> int:
    spec = _load_spec(args)
    # precedence: explicit --duration, else the spec file's own
    # duration_s, else 120 s — a spec configured for a 4.4 h soak must
    # not be silently truncated by the CLI default
    duration = args.duration if args.duration is not None else \
        (spec.duration_s if spec.duration_s is not None else 120.0)
    result = asyncio.run(run_workload(
        spec, args.base_url, api_key=args.api_key,
        duration_s=duration,
        abort_fraction=args.abort_fraction,
        p99_ttft_bound_s=args.p99_ttft_bound,
        checkpoint_interval_s=args.checkpoint_interval,
        checkpoint_path=args.checkpoint_file))
    if args.record_trace:
        _record_trace(result, spec, args.record_trace)
    out = report_mod.bench_schema(
        f"loadgen soak {spec.name} ({duration:.0f}s)",
        result.summary,
        detail={"workload": spec.name, "seed": spec.seed,
                "model": spec.model,
                "abort_fraction": args.abort_fraction,
                "invariant_violations": result.violations,
                "checkpoints": len(result.checkpoints)})
    if args.output:
        report_mod.write_json(args.output, out)
    _print_report(result, out)
    if result.ok:
        print(f"soak PASSED: {result.summary['finished']} requests, "
              f"zero invariant violations")
    return 0 if result.ok else 1


def cmd_distload(args) -> int:
    record = asyncio.run(run_distload(
        engines=args.engines, workers=args.workers, qps=args.qps,
        phase_s=args.phase, trace_path=args.trace,
        capstone_trace=args.capstone_trace, speedup=args.speedup,
        capstone=not args.no_capstone,
        capstone_routers=args.capstone_routers,
        capstone_engines_per_pool=args.capstone_engines_per_pool,
        anti_vacuity=args.anti_vacuity,
        skip_embedded_anti_vacuity=args.skip_embedded_anti_vacuity,
        service_jitter=args.service_jitter,
        qps_rel_tol=args.qps_rel_tol, pct_rel_tol=args.pct_rel_tol,
        pct_abs_tol_s=args.pct_abs_tol,
        min_chain_fraction=args.min_chain_fraction,
        worker_timeout_s=args.worker_timeout,
        startup_timeout_s=args.startup_timeout,
        log_dir=args.log_dir, work_dir=args.work_dir,
        platform=args.platform))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"DISTLOAD_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = distload_violations(
        record, min_chain_fraction=args.min_chain_fraction)
    for v in violations:
        print(f"DISTLOAD VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        dist, ctrl = d["dist"]["summary"], d["control"]["summary"]
        av = d.get("anti_vacuity") or {}
        msg = (f"distload PASSED: {d['workers']} workers offered "
               f"{dist['offered_qps']:.2f} qps (control "
               f"{ctrl['offered_qps']:.2f}, target {d['target_qps']}), "
               f"merged ttft p50 {dist['ttft_s']['p50']*1000:.1f}ms vs "
               f"control {ctrl['ttft_s']['p50']*1000:.1f}ms, replay "
               f"digest stable over "
               f"{len(d['replay']['runs'])} runs")
        if av:
            msg += (f"; embedded mismatched-rate run failed the gate "
                    f"as required ({len(av['violations'])} violations "
                    f"at {av.get('offered_qps', 0):.2f} qps offered)")
        cap = d.get("capstone")
        if cap:
            msg += (f"; capstone stitched "
                    f"{cap['stitch'].get('chains_complete', 0)} chains "
                    f"({cap['stitch'].get('complete_fraction', 0):.0%} "
                    f"complete) across {cap['routers']} routers / 2 "
                    f"pools with 0 raw 5xx")
        print(msg)
    return 1 if violations else 0


def cmd_scaleout(args) -> int:
    spec = _load_spec(args)
    replicas = [int(x) for x in args.replicas.split(",") if x.strip()]
    output = args.output or \
        f"SCALEOUT_{time.strftime('%Y%m%d_%H%M%S')}.json"
    record = asyncio.run(run_scaleout(
        spec, replicas=replicas, engine=args.engine,
        routing=args.routing, duration_s=args.duration,
        users_per_replica=args.users_per_replica,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout,
        checkpoint_interval_s=args.checkpoint_interval, output=output))
    print(json.dumps(record, indent=2))
    # a curve measured through an error storm is not a curve: fail the
    # run (same contract as run/soak, whose exit status BASELINE.md
    # advertises as enforcing the invariants)
    bad = [p for p in record["points"]
           if p["errors"] or p.get("invariant_violations")]
    for p in bad:
        print(f"N={p['replicas']}: {p['errors']} errors, "
              f"{len(p.get('invariant_violations') or [])} invariant "
              f"violations — curve is suspect", file=sys.stderr)
    return 1 if bad else 0


def cmd_overhead(args) -> int:
    record = asyncio.run(run_overhead(
        engine=args.engine, users=args.users, duration_s=args.duration,
        num_tokens=args.num_tokens, stream=args.stream,
        routing=args.routing, platform=args.platform,
        log_dir=args.log_dir, startup_timeout_s=args.startup_timeout,
        snapshot_ttl=args.snapshot_ttl,
        unique_prompts=args.unique_prompts,
        prompt_chars=args.prompt_chars))
    print(json.dumps(record, indent=2))
    if args.output:
        report_mod.write_json(args.output, record)
    d = record["detail"]
    bad = d["direct"]["errors"] + d["router"]["errors"]
    if bad:
        print(f"{bad} requests errored — the A/B is suspect",
              file=sys.stderr)
        return 1
    ratio = d["overhead_ratio"]
    if args.max_ratio and ratio and ratio > args.max_ratio:
        print(f"OVERHEAD VIOLATION: ratio {ratio:.2f}x exceeds the "
              f"--max-ratio {args.max_ratio:g}x band", file=sys.stderr)
        return 1
    return 0


def cmd_chaos(args) -> int:
    record = asyncio.run(run_chaos(
        engines=args.engines, engine=args.engine, users=args.users,
        duration_s=args.duration, kill_interval_s=args.kill_interval,
        downtime_s=args.downtime,
        error_burst_interval_s=args.error_burst_interval or None,
        error_burst=args.error_burst,
        stream_fraction=args.stream_fraction,
        num_tokens=args.num_tokens, routing=args.routing,
        seed=args.seed, p99_bound_s=args.p99_bound,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout,
        cache_server_kill=args.cache_server_kill,
        cache_kill_interval_s=args.cache_kill_interval,
        cache_downtime_s=args.cache_downtime,
        router_kill=args.router_kill,
        router_replicas=args.router_replicas,
        router_kill_interval_s=args.router_kill_interval,
        router_downtime_s=args.router_downtime,
        router_blip_window_s=args.router_blip_window))
    print(json.dumps(record, indent=2))
    output = args.output or f"CHAOS_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = chaos_violations(record)
    for v in violations:
        print(f"CHAOS VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        print(f"chaos PASSED: {d['requests']['ok']} ok, "
              f"{d['kills']} kills/{d['restarts']} restarts, "
              f"zero client-visible 5xx "
              f"(availability {d['availability_pct']:.2f}%, "
              f"{d['requests']['truncated_streams']} mid-stream "
              f"truncations)")
    return 1 if violations else 0


def cmd_overload(args) -> int:
    qps = [float(x) for x in args.qps.split(",") if x.strip()]
    record = asyncio.run(run_overload(
        engines=args.engines, engine=args.engine, qps_points=qps,
        duration_s=args.duration, deadline_ms=args.deadline_ms,
        num_tokens=args.num_tokens, fake_capacity=args.fake_capacity,
        fake_tokens_per_s=args.fake_tokens_per_s,
        unprotected=args.unprotected,
        plateau_tolerance=args.plateau_tolerance,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"OVERLOAD_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    if args.unprotected:
        # the "before" curve EXISTS to show the collapse; don't fail it
        print("unprotected baseline sweep recorded (no contract "
              "enforced)", file=sys.stderr)
        return 0
    violations = overload_violations(
        record, plateau_tolerance=args.plateau_tolerance)
    for v in violations:
        print(f"OVERLOAD VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        top = d["points"][-1]
        print(f"overload PASSED: goodput peak {record['value']} qps, "
              f"plateau held at {top['offered_qps']} qps offered "
              f"({top['goodput_qps']} qps goodput, "
              f"{top['shed']} shed, 0 late, 0 errors)")
    return 1 if violations else 0


def cmd_effwatch(args) -> int:
    mixed = ([int(x) for x in args.mixed_tokens.split(",")]
             if args.mixed_tokens else None)
    common = dict(
        engine=args.engine, users=args.users, duration_s=args.duration,
        warmup_s=args.warmup, num_tokens=args.num_tokens,
        sum_tolerance=args.sum_tolerance,
        rate_tolerance=args.rate_tolerance,
        stagger_s=args.stagger, mixed_tokens=mixed,
        prompt_chars=args.prompt_chars,
        engine_args=args.engine_args.split() if args.engine_args
        else None,
        fake_pad_fraction=args.fake_pad_fraction,
        fake_dead_fraction=args.fake_dead_fraction,
        fake_skew=args.fake_skew,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout)
    output = args.output or \
        f"EFF_{time.strftime('%Y%m%d_%H%M%S')}.json"
    if args.ab:
        if args.anti_vacuity:
            print("--anti-vacuity is a single-run falsifiability "
                  "probe (mis-sized accounting window, gates must "
                  "fail); it has no A/B semantics — run it without "
                  "--ab", file=sys.stderr)
            return 2
        if args.no_window_adapt:
            print("--no-window-adapt is the single-run control side "
                  "by itself; --ab already runs both sides — pick "
                  "one", file=sys.stderr)
            return 2
        record = asyncio.run(run_effwatch_ab(
            live_floor=args.live_floor,
            improve_floor=args.improve_floor,
            rounds=args.rounds, **common))
        print(json.dumps(record, indent=2))
        report_mod.write_json(output, record)
        violations = effwatch_ab_violations(
            record, live_floor=args.live_floor,
            improve_floor=args.improve_floor,
            sum_tolerance=args.sum_tolerance,
            rate_tolerance=args.rate_tolerance)
        for v in violations:
            print(f"EFFWATCH A/B VIOLATION: {v}", file=sys.stderr)
        if not violations:
            d = record["detail"]
            print(f"effwatch A/B PASSED: accounted decode tok/s "
                  f"{d['accounted_decode_tokens_per_s_adapt']} adapt "
                  f"vs {d['accounted_decode_tokens_per_s_control']} "
                  f"control (+{d['improvement_perc']}%), live "
                  f"fraction {d['live_fraction_adapt']} vs "
                  f"{d['live_fraction_control']}, all per-side gates "
                  f"green")
        return 1 if violations else 0
    record = asyncio.run(run_effwatch(
        anti_vacuity=args.anti_vacuity,
        window_adapt=not args.no_window_adapt, **common))
    print(json.dumps(record, indent=2))
    report_mod.write_json(output, record)
    violations = effwatch_violations(
        record, sum_tolerance=args.sum_tolerance,
        rate_tolerance=args.rate_tolerance)
    if args.anti_vacuity:
        # the mis-sized window EXISTS to prove the gates can fail
        if any("diverge" in v for v in violations):
            print("effwatch anti-vacuity PASSED: the mis-sized window "
                  "failed the reconciliation gate as it must",
                  file=sys.stderr)
            return 0
        print("effwatch anti-vacuity FAILED: the reconciliation gate "
              "did not trip on a deliberately mis-sized window",
              file=sys.stderr)
        return 1
    for v in violations:
        print(f"EFFWATCH VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        print(f"effwatch PASSED: accounted {record['value']} decode "
              f"tok/s vs client {d['client_decode_tokens_per_s']} "
              f"(fraction sum {d['fraction_sum']}, live fraction "
              f"{d['live_fraction_steady']}, mbu "
              f"{d['mbu_perc_steady']}%, 0 steady compiles, 0 errors)")
    return 1 if violations else 0


def cmd_autoscale(args) -> int:
    qps = [float(x) for x in args.qps.split(",") if x.strip()]

    def ramp(fixed_replicas=None):
        return run_autoscale(
            engine=args.engine, qps_profile=qps,
            phase_duration_s=args.phase_duration,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            initial_replicas=args.min_replicas,
            deadline_ms=args.deadline_ms, num_tokens=args.num_tokens,
            fake_capacity=args.fake_capacity,
            fake_tokens_per_s=args.fake_tokens_per_s,
            tick_interval_s=args.tick_interval,
            target_utilization=args.target_utilization,
            down_utilization=args.down_utilization,
            target_queue_delay_ms=args.target_queue_delay_ms,
            down_queue_delay_ms=args.down_queue_delay_ms,
            up_cooldown_s=args.up_cooldown,
            down_cooldown_s=args.down_cooldown,
            fixed_replicas=fixed_replicas,
            drain_timeout_s=args.drain_timeout,
            platform=args.platform, log_dir=args.log_dir,
            startup_timeout_s=args.startup_timeout)

    record = asyncio.run(ramp())
    if args.compare_fixed > 0:
        print(f"autoscale ramp done; measuring the fixed-N="
              f"{args.compare_fixed} comparison baseline...",
              file=sys.stderr)
        record["detail"]["comparison"] = asyncio.run(
            ramp(fixed_replicas=args.compare_fixed))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"AUTOSCALE_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = autoscale_violations(
        record, track_fraction=args.track_fraction,
        compare_margin=args.compare_margin)
    for v in violations:
        print(f"AUTOSCALE VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        print(f"autoscale PASSED: replicas "
              f"{d['replicas_initial']} -> "
              f"{d['max_replicas_observed']} -> "
              f"{d['final_replicas']} tracking the ramp, "
              f"{d['scale_ups']} scale-up(s) / {d['scale_downs']} "
              f"drain-safe scale-down(s), peak goodput "
              f"{record['value']} qps, zero client-visible errors")
    return 1 if violations else 0


def cmd_kvshare(args) -> int:
    record = asyncio.run(run_kvshare(
        engines=args.engines, engine=args.engine,
        sessions=args.sessions, rounds=args.rounds,
        system_chars=args.system_chars, round_chars=args.round_chars,
        num_tokens=args.num_tokens,
        prefill_ms_per_char=args.prefill_ms_per_char,
        kv_chunk_chars=args.kv_chunk_chars, routing=args.routing,
        seed=args.seed, no_cache=args.no_cache,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"KVSHARE_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = kvshare_violations(record,
                                    min_hit_rate=args.min_hit_rate)
    for v in violations:
        print(f"KVSHARE VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        ttft = d["ttft_followup_mean_ms"]
        print(f"kvshare PASSED: {record['value']}% tier hit rate with "
              f"affinity broken across {d['engines']} replicas "
              f"(foreign share "
              f"{d['cached']['foreign_share']:.0%}), follow-up TTFT "
              f"{ttft['cached']:.0f}ms vs {ttft['recompute']:.0f}ms "
              f"recompute ({ttft['improvement_pct']:.0f}% faster)")
    return 1 if violations else 0


def cmd_kvmigrate(args) -> int:
    record = asyncio.run(run_kvmigrate(
        storm_duration_s=args.storm_duration,
        storm_workers=args.storm_workers,
        poll_interval_s=args.poll_interval,
        codec=args.codec, sessions=args.sessions, rounds=args.rounds,
        seed=args.seed, platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"KVMIGRATE_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = kvmigrate_violations(
        record, max_on_failure_rate=args.max_on_failure_rate,
        min_off_failure_rate=args.min_off_failure_rate,
        min_capacity_ratio=args.min_capacity_ratio,
        ttft_tolerance=args.ttft_tolerance)
    for v in violations:
        print(f"KVMIGRATE VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        on2 = d["storm"]["on"]["halves"][1]
        off2 = d["storm"]["off"]["halves"][1]
        ratios = d["codec"]["capacity_ratio"]
        print(f"kvmigrate PASSED: migration erased the fragmented "
              f"regime ({on2['failure_rate']:.1%} second-half failure "
              f"rate vs {off2['failure_rate']:.1%} with migration "
              f"OFF, {d['storm']['on']['planner']['moves']} moves, "
              f"aggregate blocks constant); codec "
              f"{d['codec']['name']} capacity "
              f"{ratios[d['codec']['name']]:.2f}x vs raw "
              f"{ratios['raw']:.2f}x at equal logical bytes")
    return 1 if violations else 0


def cmd_disagg(args) -> int:
    record = asyncio.run(run_disagg(
        prefill_engines=args.prefill_engines,
        decode_engines=args.decode_engines, engine=args.engine,
        chat_users=args.chat_users, rag_users=args.rag_users,
        duration_s=args.duration,
        chat_prompt_chars=args.chat_prompt_chars,
        chat_tokens=args.chat_tokens,
        rag_prompt_chars=args.rag_prompt_chars,
        rag_tokens=args.rag_tokens,
        tokens_per_s=args.fake_tokens_per_s,
        prefill_ms_per_char=args.prefill_ms_per_char,
        interference=args.interference,
        kv_chunk_chars=args.kv_chunk_chars,
        headstart_s=args.headstart,
        min_prompt_chars=args.min_prompt_chars,
        routing=args.routing, seed=args.seed, no_split=args.no_split,
        prefill_kill=not args.no_prefill_kill,
        kill_downtime_s=args.kill_downtime,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"DISAGG_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = disagg_violations(
        record,
        min_itl_improvement=(args.min_itl_improvement
                             if args.min_itl_improvement >= 0 else None))
    for v in violations:
        print(f"DISAGG VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        itl = d["chat_itl_p99_ms"]
        chaos = d["split_phase"].get("chaos") or {}
        if itl.get("improvement_pct") is not None:
            itl_msg = (f"chat ITL p99 {itl['split']:.1f}ms split vs "
                       f"{itl['aggregated']:.1f}ms aggregated "
                       f"({itl['improvement_pct']:.0f}% better)")
        else:
            # single-chunk chat streams yield no ITL samples; only
            # reachable with the gate disabled (negative
            # --min-itl-improvement), where the data-path gates carry
            # the contract
            itl_msg = "chat ITL not sampled (single-chunk streams)"
        print(f"disagg PASSED: {itl_msg} at equal engine "
              f"count ({d['prefill_engines']}P+{d['decode_engines']}D), "
              f"{chaos.get('kills', 0)} prefill-pod kill(s) with zero "
              f"client-visible errors")
    return 1 if violations else 0


def cmd_firedrill(args) -> int:
    scenarios = None
    if args.scenarios:
        scenarios = [s.strip() for s in args.scenarios.split(",")
                     if s.strip()]
    record = asyncio.run(run_firedrill(
        engines=args.engines, engine=args.engine, users=args.users,
        baseline_s=args.baseline, window_scale=args.window_scale,
        scenarios=scenarios,
        detect_timeout_s=args.detect_timeout,
        resolve_timeout_s=args.resolve_timeout,
        num_tokens=args.num_tokens,
        fake_tokens_per_s=args.fake_tokens_per_s,
        error_rate=args.error_rate,
        slow_ttft_arg_s=args.slow_ttft_arg,
        ttft_threshold_s=args.ttft_threshold,
        overload_capacity=args.overload_capacity,
        queue_delay_ms=args.queue_delay_ms,
        min_events=args.min_events, routing=args.routing,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout,
        overhead_guard=args.overhead_guard,
        overhead_users=args.overhead_users,
        overhead_duration_s=args.overhead_duration))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"FIREDRILL_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = firedrill_violations(
        record, max_overhead_ratio=(args.max_overhead_ratio
                                    if args.overhead_guard else None))
    for v in violations:
        print(f"FIREDRILL VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        # a real-engine drill may have dropped every /fault-driven
        # scenario: the baseline false-positive gate alone still passes
        detect = [s["detected_in_s"] for s in d["scenarios"]
                  if s["detected_in_s"] is not None]
        scen_msg = (f"{d['detected']}/{len(d['scenarios'])} scenarios "
                    f"detected (worst {max(detect):.1f}s vs "
                    f"{d['detect_timeout_s']:.0f}s bound) and "
                    f"resolved, zero false fires"
                    if detect else "no scenarios run (baseline "
                                   "false-positive gate only)")
        msg = (f"firedrill PASSED: baseline clean "
               f"({d['baseline']['storm']['ok']} ok, 0 alerts), "
               + scen_msg)
        guard = d.get("overhead_guard")
        if guard:
            msg += (f"; SLO-on overhead {guard['overhead_ratio']:.2f}x "
                    f"vs direct")
        print(msg)
    return 1 if violations else 0


def cmd_incident(args) -> int:
    scenarios = None
    if args.scenarios:
        scenarios = [s.strip() for s in args.scenarios.split(",")
                     if s.strip()]
    record = asyncio.run(run_incident(
        engines=args.engines, routers=args.routers, engine=args.engine,
        users=args.users, baseline_s=args.baseline,
        window_scale=args.window_scale, scenarios=scenarios,
        detect_timeout_s=args.detect_timeout,
        resolve_timeout_s=args.resolve_timeout,
        num_tokens=args.num_tokens,
        fake_tokens_per_s=args.fake_tokens_per_s,
        slow_ttft_arg_s=args.slow_ttft_arg,
        ttft_threshold_s=args.ttft_threshold,
        max_inflight=args.max_inflight,
        burst_users=args.burst_users,
        min_events=args.min_events, routing=args.routing,
        platform=args.platform, log_dir=args.log_dir,
        incident_dir=args.incident_dir,
        poll_interval_s=args.poll_interval,
        capture_cooldown_s=args.capture_cooldown,
        startup_timeout_s=args.startup_timeout,
        overhead_guard=args.overhead_guard,
        overhead_users=args.overhead_users,
        overhead_duration_s=args.overhead_duration))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"INCIDENT_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = incident_violations(
        record, max_overhead_ratio=(args.max_overhead_ratio
                                    if args.overhead_guard else None),
        min_chain_fraction=args.min_chain_fraction)
    for v in violations:
        print(f"INCIDENT VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        stitch = d["baseline"]["stitch"]
        msg = (f"incident drill PASSED: baseline clean "
               f"({d['baseline']['storm']['ok']} ok, 0 bundles, "
               f"{stitch.get('chains_complete', 0)} chains stitched "
               f"at {stitch.get('complete_fraction', 0):.0%}), "
               f"{len(d['scenarios'])}/{len(d['scenarios'])} faults "
               f"detected+captured+attributed")
        guard = d.get("overhead_guard")
        if guard:
            msg += (f"; scraped overhead {guard['overhead_ratio']:.2f}x"
                    f" vs unscraped {guard['baseline_ratio']:.2f}x "
                    f"(best of {guard['rounds']} alternating rounds)")
        print(msg)
    return 1 if violations else 0


def cmd_fleetdrill(args) -> int:
    scenarios = None
    if args.scenarios:
        scenarios = [s.strip() for s in args.scenarios.split(",")
                     if s.strip()]
    record = asyncio.run(run_fleetdrill(
        scenarios=scenarios, window_scale=args.window_scale,
        users=args.users, engines=args.engines,
        baseline_s=args.baseline,
        detect_timeout_s=args.detect_timeout,
        resolve_timeout_s=args.resolve_timeout,
        burn_ttft_s=args.burn_ttft,
        queue_ramp_ms_per_s=args.queue_ramp,
        queue_plateau_ms=args.queue_plateau,
        max_replicas=args.max_replicas,
        slow_ttft_arg_s=args.slow_ttft_arg,
        tick_interval_s=args.tick_interval,
        min_events=args.min_events, platform=args.platform,
        log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"FLEETDRILL_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = fleetdrill_violations(record)
    for v in violations:
        print(f"FLEETDRILL VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        parts = []
        burn = d.get("burn")
        if burn:
            parts.append(
                f"burn-rate scale-up saved "
                f"{burn['replica_seconds_saved']} replica-seconds vs "
                f"the queue-delay control (pilot fired "
                f"{burn['pilot']['fired_in_s']}s vs control "
                f"{burn['control']['fired_in_s']}s)")
        rem = d.get("remediate")
        if rem:
            parts.append(
                f"slow engine drained+restarted hands-off in "
                f"{rem['duration_s']}s (1 remediation, outcome "
                f"resolved)")
        if d.get("killswitch"):
            parts.append("kill-switch verifiably suppressed the "
                         "remediation while the alert kept burning")
        print("fleetdrill PASSED: " + "; ".join(parts))
    return 1 if violations else 0


def cmd_trace(args) -> int:
    record = asyncio.run(run_trace(
        engines=args.engines, engine=args.engine, disagg=args.disagg,
        prefill_engines=args.prefill_engines,
        decode_engines=args.decode_engines,
        chat_users=args.chat_users, rag_users=args.rag_users,
        duration_s=args.duration,
        chat_prompt_chars=args.chat_prompt_chars,
        chat_tokens=args.chat_tokens,
        rag_prompt_chars=args.rag_prompt_chars,
        rag_tokens=args.rag_tokens,
        tokens_per_s=args.fake_tokens_per_s,
        prefill_ms_per_char=args.prefill_ms_per_char,
        interference=args.interference,
        kv_chunk_chars=args.kv_chunk_chars,
        headstart_s=args.headstart,
        min_prompt_chars=args.min_prompt_chars,
        routing=args.routing, seed=args.seed,
        ring_entries=args.ring_entries,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout,
        overhead_guard=args.overhead_guard,
        overhead_users=args.overhead_users,
        overhead_duration_s=args.overhead_duration))
    print(json.dumps(record, indent=2))
    output = args.output or f"TRACE_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = trace_violations(
        record, min_chain_fraction=args.min_chain_fraction,
        max_unattributed_pct=args.max_unattributed,
        max_overhead_ratio=(args.max_overhead_ratio
                            if args.overhead_guard else None))
    for v in violations:
        print(f"TRACE VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        j = d["join"]
        msg = (f"trace PASSED: {record['value']}% complete span chains "
               f"({j['complete_chains']}/{j['sampled']} sampled, "
               f"{d['topology']}), unattributed time p50 "
               f"{j['unattributed_p50_pct']}%")
        guard = d.get("overhead_guard")
        if guard:
            msg += (f"; tracing-on overhead "
                    f"{guard['overhead_ratio']:.2f}x vs direct")
        print(msg)
    return 1 if violations else 0


def cmd_multirouter(args) -> int:
    record = asyncio.run(run_multirouter(
        engines=args.engines, routers=args.routers, engine=args.engine,
        sessions=args.sessions, phase_duration_s=args.phase_duration,
        num_tokens=args.num_tokens,
        tokens_per_s=args.fake_tokens_per_s,
        gossip_interval_s=args.gossip_interval,
        settle_s=args.settle, blip_window_s=args.blip_window,
        max_inflight=args.max_inflight,
        tier0_users=args.tier0_users, tier1_users=args.tier1_users,
        tier2_users=args.tier2_users,
        saturation_presat_s=args.presat_duration,
        routing=args.routing,
        shared_state=not args.no_shared_state, seed=args.seed,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout,
        skip_saturation=args.skip_saturation,
        skip_kill=args.skip_kill,
        overhead_guard=args.overhead_guard,
        overhead_users=args.overhead_users,
        overhead_duration_s=args.overhead_duration))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"MULTIROUTER_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = multirouter_violations(
        record, affinity_tolerance=args.affinity_tolerance,
        convergence_bound_s=args.convergence_bound or None,
        min_tier0_hold=args.min_tier0_hold,
        min_tier2_shed=args.min_tier2_shed,
        max_overhead_ratio=(args.max_overhead_ratio
                            if args.overhead_guard else None))
    for v in violations:
        print(f"MULTIROUTER VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        conv = d.get("breaker_convergence") or {}
        kill = d.get("router_kill") or {}
        sat = d.get("saturation") or {}
        sat0 = (sat.get("saturated") or {}).get("tier0") or {}
        sat2 = (sat.get("saturated") or {}).get("tier2") or {}
        msg = (f"multirouter PASSED: pair affinity {record['value']}% "
               f"vs control "
               f"{100 * d['control']['affinity_hit_rate']:.1f}%, "
               f"breaker open spread {conv.get('open_spread_s')}s")
        if kill:
            msg += (f", router kill blip {kill.get('blip_errors')} "
                    f"errors / 0 outside, "
                    f"{kill.get('post_restart_ok')} ok post-restart")
        if sat:
            msg += (f", tier0 {sat0.get('goodput_qps')} qps held while "
                    f"tier2 shed {sat2.get('shed_fraction', 0):.0%}")
        guard = d.get("overhead_guard")
        if guard:
            msg += (f"; shared-state overhead "
                    f"{guard['overhead_ratio']:.2f}x vs baseline "
                    f"{guard['baseline_ratio']:.2f}x")
        print(msg)
    return 1 if violations else 0


def cmd_multitenant(args) -> int:
    record = asyncio.run(run_multitenant(
        baseline_s=args.baseline_duration,
        churn_s=args.churn_duration,
        noisy_s=args.noisy_duration,
        surge_s=args.surge_duration,
        adapter_cycles=args.adapter_cycles,
        initial_a=args.pool_a_replicas, initial_b=args.pool_b_replicas,
        max_a=args.pool_a_max, max_b=args.pool_b_max,
        fake_capacity=args.fake_capacity,
        num_tokens=args.num_tokens,
        tenant_rate=args.tenant_rate,
        tenant_buckets=not args.no_tenant_buckets,
        max_inflight=args.max_inflight,
        noisy_workers=args.noisy_workers,
        tick_interval_s=args.tick_interval,
        platform=args.platform, log_dir=args.log_dir,
        startup_timeout_s=args.startup_timeout))
    print(json.dumps(record, indent=2))
    output = args.output or \
        f"TENANT_{time.strftime('%Y%m%d_%H%M%S')}.json"
    report_mod.write_json(output, record)
    violations = multitenant_violations(
        record, interference_floor=args.interference_floor,
        min_noisy_shed=args.min_noisy_shed,
        peer_floor=args.peer_floor)
    for v in violations:
        print(f"MULTITENANT VIOLATION: {v}", file=sys.stderr)
    if not violations:
        d = record["detail"]
        noisy = d["noisy"]
        routing = d["routing"]
        print(f"multitenant PASSED: {routing['ok_checked']} responses "
              f"100% model-correct across "
              f"{len(d['pools'])} pools, pool-b held "
              f"{record['value']}% of baseline through pool-a "
              f"churn+kill, acme shed "
              f"{noisy['acme_shed_fraction']:.0%} while peers held, "
              f"pools scaled: "
              f"{', '.join(d['autoscaling']['pools_scaled_up'])} "
              f"({d['autoscaling']['budget_deferrals']} budget "
              f"deferrals)")
    return 1 if violations else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "python -m production_stack_tpu.loadgen",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, base_url=True):
        if base_url:
            sp.add_argument("--base-url", required=True,
                            help="router (or engine) URL")
            sp.add_argument("--api-key", default=None)
        sp.add_argument("--workload", default="chat",
                        help="preset: chat | mixed | scaleout | ref-ramp")
        sp.add_argument("--spec", default=None,
                        help="WorkloadSpec JSON file (overrides "
                             "--workload)")
        sp.add_argument("--model", default=None,
                        help="override the spec's model id")
        sp.add_argument("--seed", type=int, default=None)
        sp.add_argument("--users", type=int, default=None,
                        help="override closed-loop user count")
        sp.add_argument("--output", default=None,
                        help="write the JSON report here")
        sp.add_argument("--checkpoint-interval", type=float, default=30.0)

    sp = sub.add_parser("run", help="one workload against a running stack")
    common(sp)
    sp.add_argument("--duration", type=parse_duration, default=None)
    sp.add_argument("--max-sessions", type=int, default=None)
    sp.add_argument("--record-trace", default=None,
                    help="dump this run's per-request schedule as a "
                         "replayable *.trace.jsonl (measured arrival "
                         "offsets + planned shapes)")
    sp.set_defaults(fn=cmd_run)

    sp = sub.add_parser("soak", help="duration-bounded invariant-checked "
                                     "mixed-traffic run")
    common(sp)
    sp.add_argument("--duration", type=parse_duration, default=None,
                    help="e.g. 120s, 30m, 4.4h (default: the spec's "
                         "duration_s, else 120s)")
    sp.add_argument("--abort-fraction", type=float, default=0.02,
                    help="fraction of streams disconnected mid-flight "
                         "(invariant I5)")
    sp.add_argument("--p99-ttft-bound", type=float, default=None,
                    help="seconds; invariant I4 when set")
    sp.add_argument("--checkpoint-file", default=None,
                    help="append checkpoint JSON lines here")
    sp.add_argument("--record-trace", default=None,
                    help="dump this run's per-request schedule as a "
                         "replayable *.trace.jsonl")
    # the soak's whole point is mixed traffic
    sp.set_defaults(fn=cmd_soak, workload="mixed")

    sp = sub.add_parser(
        "distload",
        help="coordinator/worker sharded loadgen closed loop: "
             "N-worker merged percentiles must match the 1-worker "
             "control, trace replay must be deterministic, and the "
             "composed routers/pools/obsplane capstone must stitch "
             "complete chains with zero 5xx")
    distload_cli_args(sp)
    sp.set_defaults(fn=cmd_distload)

    sp = sub.add_parser("scaleout",
                        help="launch router+N engines, measure the "
                             "tokens/s-vs-replicas curve")
    common(sp, base_url=False)
    sp.add_argument("--replicas", default="1,2,4",
                    help="comma-separated replica counts")
    sp.add_argument("--engine", default="debug-tiny",
                    help="engine model name, or 'fake' for the mock")
    sp.add_argument("--routing", default="session",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"])
    sp.add_argument("--duration", type=parse_duration, default=60.0,
                    help="measured window per replica point")
    sp.add_argument("--users-per-replica", type=int, default=None)
    sp.add_argument("--platform", default="cpu",
                    help="JAX_PLATFORMS for engine processes ('' to "
                         "inherit, e.g. for TPU)")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    # the scaleout preset is sized to the engine geometry the
    # orchestrator launches (max-model-len 1024)
    sp.set_defaults(fn=cmd_scaleout, workload="scaleout")

    sp = sub.add_parser("overhead",
                        help="router-vs-direct A/B: launch one engine "
                             "+ the router, storm both URLs, report "
                             "the overhead ratio")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (zero-think mock — measures the "
                         "router, not the model) or a real engine "
                         "model name")
    sp.add_argument("--users", type=int, default=64,
                    help="closed-loop concurrency per side")
    sp.add_argument("--duration", type=parse_duration, default=15.0,
                    help="measured window per side (e.g. 15s)")
    sp.add_argument("--num-tokens", type=int, default=8,
                    help="response length the engine generates")
    sp.add_argument("--stream", action="store_true",
                    help="streaming responses (exercises the chunk "
                         "relay loop; TTFT percentiles reported)")
    sp.add_argument("--routing", default="roundrobin",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"])
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--snapshot-ttl", type=float, default=None,
                    help="router --request-stats-snapshot-ttl override "
                         "(seconds; 0 disables snapshot caching)")
    sp.add_argument("--unique-prompts", action="store_true",
                    help="per-request unique long prompts — the "
                         "cold-prefix worst case for cache-aware "
                         "routing (the r11 no-regression guard pairs "
                         "this with --routing prefix)")
    sp.add_argument("--prompt-chars", type=int, default=768,
                    help="unique-prompt length in chars")
    sp.add_argument("--max-ratio", type=float, default=None,
                    help="exit 1 if the overhead ratio exceeds this "
                         "band (e.g. 2.5 = the r7 band)")
    sp.add_argument("--output", default=None,
                    help="write the JSON report here "
                         "(e.g. ROUTER_OVERHEAD_r07.json)")
    sp.set_defaults(fn=cmd_overhead)

    sp = sub.add_parser("chaos",
                        help="router + N engines with scheduled engine "
                             "kills/restarts; assert zero client-"
                             "visible 5xx for pre-stream failures")
    sp.add_argument("--engines", type=int, default=3,
                    help="engine replica count behind the router")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (chaos measures the router, not the "
                         "model) or a real engine model name")
    sp.add_argument("--users", type=int, default=16,
                    help="closed-loop storm concurrency")
    sp.add_argument("--duration", type=parse_duration, default=60.0)
    sp.add_argument("--kill-interval", type=parse_duration, default=10.0,
                    help="seconds between engine SIGKILLs")
    sp.add_argument("--downtime", type=parse_duration, default=3.0,
                    help="seconds a killed engine stays down")
    sp.add_argument("--error-burst-interval", type=parse_duration,
                    default=7.0,
                    help="seconds between injected backend-500 bursts "
                         "(fake engines only; 0 disables)")
    sp.add_argument("--error-burst", type=int, default=5,
                    help="500s per injected burst")
    sp.add_argument("--stream-fraction", type=float, default=0.3,
                    help="fraction of requests using SSE streaming")
    sp.add_argument("--num-tokens", type=int, default=16)
    sp.add_argument("--routing", default="session",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"])
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--p99-bound", type=parse_duration, default=None,
                    help="seconds; fail the run if p99 latency under "
                         "churn exceeds this")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--cache-server-kill", action="store_true",
                    help="also launch a shared TPKV cache server wired "
                         "into the (fake) engines as their remote KV "
                         "tier and SIGKILL/restart it on its own "
                         "schedule — a dead cache server must cost "
                         "recompute, never a client-visible error")
    sp.add_argument("--cache-kill-interval", type=parse_duration,
                    default=7.0,
                    help="seconds between cache-server SIGKILLs")
    sp.add_argument("--cache-downtime", type=parse_duration, default=2.0,
                    help="seconds the cache server stays down")
    sp.add_argument("--router-kill", action="store_true",
                    help="launch --router-replicas peered routers "
                         "behind an in-process L4 splitter and "
                         "SIGKILL/restart router replicas on their "
                         "own schedule — client errors are then "
                         "allowed only inside each kill's blip window")
    sp.add_argument("--router-replicas", type=int, default=2,
                    help="router replica count with --router-kill")
    sp.add_argument("--router-kill-interval", type=parse_duration,
                    default=15.0,
                    help="seconds between router SIGKILLs")
    sp.add_argument("--router-downtime", type=parse_duration,
                    default=2.0,
                    help="seconds a killed router stays down")
    sp.add_argument("--router-blip-window", type=parse_duration,
                    default=4.0,
                    help="seconds after each router kill during which "
                         "in-flight client errors are tolerated "
                         "(counted, reported)")
    sp.add_argument("--output", default=None,
                    help="write CHAOS_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_chaos)

    sp = sub.add_parser("overload",
                        help="router + N protected engines; sweep "
                             "open-loop offered QPS past saturation "
                             "and assert goodput plateaus")
    sp.add_argument("--engines", type=int, default=2,
                    help="engine replica count behind the router")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (overload fault mode = bounded queue) "
                         "or a real engine model name (launched with "
                         "--max-waiting-seqs/--max-queue-delay-ms)")
    sp.add_argument("--qps", default="2,4,8,16",
                    help="comma-separated offered-QPS sweep (open "
                         "loop; the top rates should be well past "
                         "saturation)")
    sp.add_argument("--duration", type=parse_duration, default=15.0,
                    help="measured window per point")
    sp.add_argument("--deadline-ms", type=float, default=8000.0,
                    help="x-request-deadline-ms each request carries")
    sp.add_argument("--num-tokens", type=int, default=8)
    sp.add_argument("--fake-capacity", type=int, default=4,
                    help="fake engines: bounded-queue capacity")
    sp.add_argument("--fake-tokens-per-s", type=float, default=50.0,
                    help="fake engines: service pacing")
    sp.add_argument("--unprotected", action="store_true",
                    help="launch engines WITHOUT protection flags — "
                         "the collapse baseline (no contract "
                         "enforced, exit 0)")
    sp.add_argument("--plateau-tolerance", type=float, default=0.10,
                    help="goodput past the knee may dip this fraction "
                         "under the peak")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write OVERLOAD_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_overload)

    sp = sub.add_parser("effwatch",
                        help="one engine; audit the efficiency "
                             "accounting (token-step fractions, "
                             "accounted-vs-client decode tokens/s, "
                             "steady-window compile silence) around "
                             "a real storm")
    sp.add_argument("--engine", default="debug-tiny",
                    help="engine model name (real process) or 'fake' "
                         "(synthetic perf block — the engine-free "
                         "smoke)")
    sp.add_argument("--users", type=int, default=6,
                    help="closed-loop concurrent streaming clients")
    sp.add_argument("--duration", type=parse_duration, default=20.0,
                    help="steady measured window")
    sp.add_argument("--warmup", type=parse_duration, default=8.0,
                    help="warmup storm ahead of the measured window "
                         "(same shape, so every executable is "
                         "compiled before the steady scrape)")
    sp.add_argument("--num-tokens", type=int, default=32)
    sp.add_argument("--sum-tolerance", type=float, default=0.02,
                    help="allowed |1 - (real+pad+dead)/total|")
    sp.add_argument("--rate-tolerance", type=float, default=0.10,
                    help="allowed relative gap between accounted and "
                         "client-measured decode tokens")
    sp.add_argument("--anti-vacuity", action="store_true",
                    help="mis-size the accounting window (scrape "
                         "before the warmup storm): the "
                         "reconciliation gate MUST fail; exit 0 iff "
                         "it does")
    sp.add_argument("--ab", action="store_true",
                    help="same-storm A/B: window adaptation on vs "
                         "--no-window-adapt control (fresh engine per "
                         "side); gates on per-side accounting PLUS "
                         "adapt live fraction >= --live-floor and "
                         "accounted tokens/s >= (1 + --improve-floor) "
                         "x control")
    sp.add_argument("--no-window-adapt", action="store_true",
                    help="single run with adaptation disabled (the "
                         "control side by itself)")
    sp.add_argument("--live-floor", type=float, default=0.80,
                    help="A/B: minimum adapt-side whole-window live "
                         "fraction")
    sp.add_argument("--improve-floor", type=float, default=0.20,
                    help="A/B: minimum relative accounted-tokens/s "
                         "improvement over the control")
    sp.add_argument("--stagger", type=float, default=0.0,
                    help="seconds between successive workers' first "
                         "requests (staggered arrivals — the churny "
                         "storm shape)")
    sp.add_argument("--mixed-tokens", default=None,
                    help="comma-separated max_tokens cycled per "
                         "request, offset by worker (mixed short/long "
                         "outputs), e.g. 8,48; overrides --num-tokens "
                         "for the storm bodies")
    sp.add_argument("--engine-args", default=None,
                    help="extra engine CLI flags appended to the "
                         "launch (space-separated; real engines only) "
                         "— geometry overrides for the A/B, e.g. "
                         "'--max-num-seqs 16'")
    sp.add_argument("--prompt-chars", type=int, default=0,
                    help="pad storm prompts to this many characters "
                         "(longer live context — the per-row KV read "
                         "dominates fixed dispatch overhead)")
    sp.add_argument("--rounds", type=int, default=1,
                    help="A/B rounds in alternating ABBA order; gates "
                         "read per-side aggregates across rounds "
                         "(single-host noise control)")
    sp.add_argument("--fake-pad-fraction", type=float, default=0.3,
                    help="fake engine: synthetic padding fraction")
    sp.add_argument("--fake-dead-fraction", type=float, default=0.1,
                    help="fake engine: synthetic dead fraction")
    sp.add_argument("--fake-skew", type=float, default=0.0,
                    help="fake engine: inflate the independent "
                         "token_steps_total by this fraction (breaks "
                         "the sum-to-1 gate on purpose)")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write EFF_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_effwatch)

    sp = sub.add_parser("autoscale",
                        help="router + autoscaler-owned engines; drive "
                             "a QPS ramp up then down and assert "
                             "replicas track it with zero "
                             "client-visible 5xx")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (bounded mock — measures the control "
                         "loop, not the model) or a real engine model "
                         "name (launched with protection flags)")
    sp.add_argument("--qps", default="4,12,24,12,4",
                    help="comma-separated offered-QPS phases, shaped "
                         "up then down")
    sp.add_argument("--phase-duration", type=parse_duration,
                    default=15.0, help="seconds per ramp phase")
    sp.add_argument("--min-replicas", type=int, default=1)
    sp.add_argument("--max-replicas", type=int, default=3)
    sp.add_argument("--deadline-ms", type=float, default=8000.0)
    sp.add_argument("--num-tokens", type=int, default=4)
    sp.add_argument("--fake-capacity", type=int, default=4,
                    help="fake engines: bounded-queue capacity "
                         "(advertised; drives utilization)")
    sp.add_argument("--fake-tokens-per-s", type=float, default=10.0,
                    help="fake engines: service pacing")
    sp.add_argument("--tick-interval", type=float, default=1.0,
                    help="autoscaler control-tick seconds")
    sp.add_argument("--target-utilization", type=float, default=0.85)
    sp.add_argument("--down-utilization", type=float, default=0.45)
    sp.add_argument("--target-queue-delay-ms", type=float,
                    default=500.0)
    sp.add_argument("--down-queue-delay-ms", type=float, default=100.0)
    sp.add_argument("--up-cooldown", type=float, default=4.0)
    sp.add_argument("--down-cooldown", type=float, default=8.0)
    sp.add_argument("--drain-timeout", type=float, default=30.0,
                    help="seconds a scale-down waits for the victim's "
                         "in-flight work before proceeding")
    sp.add_argument("--compare-fixed", type=int, default=1,
                    help="also measure the same ramp with this many "
                         "FIXED replicas as the baseline (0 skips)")
    sp.add_argument("--track-fraction", type=float, default=0.7,
                    help="peak-phase goodput must reach this fraction "
                         "of offered QPS")
    sp.add_argument("--compare-margin", type=float, default=1.3,
                    help="autoscale peak goodput must beat the fixed "
                         "baseline by this factor")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write AUTOSCALE_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_autoscale)

    sp = sub.add_parser("kvshare",
                        help="shared cache server + N engines + router "
                             "with affinity broken; multi-round QA "
                             "must show >60%% cross-replica hit rate "
                             "and TTFT beating recompute")
    sp.add_argument("--engines", type=int, default=2,
                    help="engine replica count behind the router")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (KV simulation against a real cache "
                         "server — measures the sharing data path) or "
                         "a real engine model name (launched with "
                         "--kv-transfer-config; TTFT then includes "
                         "real prefill compute)")
    sp.add_argument("--sessions", type=int, default=4,
                    help="concurrent multi-round QA sessions")
    sp.add_argument("--rounds", type=int, default=6,
                    help="rounds per session (round 1 is cold)")
    sp.add_argument("--system-chars", type=int, default=384,
                    help="per-session system prompt length")
    sp.add_argument("--round-chars", type=int, default=160,
                    help="new user content per round")
    sp.add_argument("--num-tokens", type=int, default=8)
    sp.add_argument("--prefill-ms-per-char", type=float, default=0.5,
                    help="fake engines: TTFT pacing per uncached char")
    sp.add_argument("--kv-chunk-chars", type=int, default=64,
                    help="fake engines: chunk granularity (chars)")
    sp.add_argument("--routing", default="session",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"],
                    help="affinity is broken by ROTATING the session "
                         "key every round; 'session' (default) then "
                         "scatters rounds deterministically across "
                         "replicas")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--no-cache", action="store_true",
                    help="launch the fleet WITHOUT the cache tier: the "
                         "contract must then fail (exit 1) — the "
                         "anti-vacuity check")
    sp.add_argument("--min-hit-rate", type=float, default=0.6,
                    help="cross-replica hit-rate bar")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write KVSHARE_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_kvshare)

    sp = sub.add_parser(
        "kvmigrate",
        help="kvplane closed loop: fragmentation storm with/without "
             "the migration planner (engine-census failure rate must "
             "collapse only when migration is ON, at constant "
             "aggregate blocks) + raw-vs-int4 codec capacity re-run "
             "of the kvshare storm")
    sp.add_argument("--storm-duration", type=parse_duration,
                    default=8.0,
                    help="per-phase storm length; gates read the "
                         "second half, so the planner gets the first "
                         "half to react")
    sp.add_argument("--storm-workers", type=int, default=4,
                    help="closed-loop chat workers through the router")
    sp.add_argument("--poll-interval", type=float, default=0.3,
                    help="planner census poll interval (s)")
    sp.add_argument("--codec", default="int4",
                    choices=["int8", "int4", "fp8"],
                    help="compressed tier codec for the capacity "
                         "phase (the >=2x gate wants int4)")
    sp.add_argument("--sessions", type=int, default=4,
                    help="codec phase: concurrent QA sessions")
    sp.add_argument("--rounds", type=int, default=6,
                    help="codec phase: rounds per session (round 1 "
                         "is cold)")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--max-on-failure-rate", type=float, default=0.02,
                    help="migration ON second-half fragmented-failure "
                         "rate ceiling")
    sp.add_argument("--min-off-failure-rate", type=float, default=0.2,
                    help="anti-vacuity: migration OFF second-half "
                         "failure rate floor")
    sp.add_argument("--min-capacity-ratio", type=float, default=2.0,
                    help="compressed tier logical/physical bytes "
                         "floor")
    sp.add_argument("--ttft-tolerance", type=float, default=0.25,
                    help="compressed hit TTFT may exceed raw by at "
                         "most this fraction")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write KVMIGRATE_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_kvmigrate)

    sp = sub.add_parser("disagg",
                        help="P/D split (prefill pool + decode pool + "
                             "shared cache) vs aggregated serving at "
                             "equal engine count; mixed storm with a "
                             "prefill-pod SIGKILL must show chat ITL "
                             "p99 improving with zero errors")
    sp.add_argument("--prefill-engines", type=int, default=2,
                    help="kv_producer pool size")
    sp.add_argument("--decode-engines", type=int, default=2,
                    help="kv_consumer pool size (the aggregated "
                         "baseline runs prefill+decode engines total)")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (role simulation over the real TPKV "
                         "tier protocol — measures router "
                         "orchestration + transfer path) or a real "
                         "engine model name (--kv-transfer-config "
                         "roles)")
    sp.add_argument("--chat-users", type=int, default=8,
                    help="closed-loop short-prompt/long-decode users "
                         "(the ITL-gated class)")
    sp.add_argument("--rag-users", type=int, default=4,
                    help="closed-loop long-prefill/short-decode users "
                         "(the head-of-line blockers)")
    sp.add_argument("--duration", type=parse_duration, default=30.0,
                    help="measured window per phase (p99 gates want "
                         ">=30s of samples)")
    sp.add_argument("--chat-prompt-chars", type=int, default=96)
    sp.add_argument("--chat-tokens", type=int, default=24)
    sp.add_argument("--rag-prompt-chars", type=int, default=2400)
    sp.add_argument("--rag-tokens", type=int, default=4)
    sp.add_argument("--fake-tokens-per-s", type=float, default=40.0,
                    help="fake engines: decode pacing")
    sp.add_argument("--prefill-ms-per-char", type=float, default=0.4,
                    help="fake engines: prefill pacing per uncached "
                         "char")
    sp.add_argument("--interference", type=float, default=1.5,
                    help="fake engines: decode ticks stretch by "
                         "(1 + this * concurrently-prefilling "
                         "requests) — the contention the split "
                         "removes")
    sp.add_argument("--kv-chunk-chars", type=int, default=64,
                    help="fake engines: chunk granularity (chars)")
    sp.add_argument("--headstart", type=float, default=3.0,
                    help="router --prefill-headstart (should cover one "
                         "long prefill so decode finds the prefix "
                         "published)")
    sp.add_argument("--min-prompt-chars", type=int, default=512,
                    help="router --disagg-min-prompt-chars: chat "
                         "prompts below this skip the prefill stage")
    sp.add_argument("--routing", default="least_loaded",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"])
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--no-split", action="store_true",
                    help="run BOTH phases aggregated: the ITL gate "
                         "must then fail (exit 1) — the anti-vacuity "
                         "check")
    sp.add_argument("--no-prefill-kill", action="store_true",
                    help="skip the mid-run prefill-pod SIGKILL")
    sp.add_argument("--kill-downtime", type=parse_duration, default=3.0,
                    help="seconds the killed prefill pod stays down")
    sp.add_argument("--min-itl-improvement", type=float, default=0.1,
                    help="chat ITL p99 must improve split-vs-"
                         "aggregated by this fraction; negative "
                         "disables the ITL gate (real debug-tiny CPU "
                         "engines are ITL-noise-dominated — the data-"
                         "path gates still apply)")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write DISAGG_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_disagg)

    sp = sub.add_parser("firedrill",
                        help="router + N engines with seconds-scale "
                             "SLO windows; clean baseline must fire "
                             "zero alerts, injected faults must each "
                             "fire their expected burn-rate alert and "
                             "resolve after clearing")
    sp.add_argument("--engines", type=int, default=2,
                    help="engine replica count behind the router")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (the /fault control endpoint drives "
                         "most scenarios) or a real engine model name "
                         "(engine_down only)")
    sp.add_argument("--users", type=int, default=8,
                    help="closed-loop storm concurrency (80%% chat, "
                         "20%% x-slo-class: rag)")
    sp.add_argument("--baseline", type=parse_duration, default=10.0,
                    help="clean-phase duration (the false-positive "
                         "gate)")
    sp.add_argument("--window-scale", type=float, default=0.01,
                    help="router --slo-window-scale: multiplies the "
                         "canonical 5m/30m/1h/6h windows (0.01 -> "
                         "3s/18s/36s/216s)")
    sp.add_argument("--scenarios", default=None,
                    help=f"comma-separated subset of "
                         f"{','.join(SCENARIO_NAMES)} "
                         f"(default: all)")
    sp.add_argument("--detect-timeout", type=parse_duration,
                    default=None,
                    help="seconds an expected alert has to reach "
                         "firing (default: sized to the scaled 1h "
                         "window)")
    sp.add_argument("--resolve-timeout", type=parse_duration,
                    default=None,
                    help="seconds alerts have to resolve after the "
                         "fault clears (default: sized to the scaled "
                         "30m window)")
    sp.add_argument("--num-tokens", type=int, default=4)
    sp.add_argument("--fake-tokens-per-s", type=float, default=400.0)
    sp.add_argument("--error-rate", type=float, default=0.5,
                    help="partial 500 fraction for the error_rate "
                         "scenario")
    sp.add_argument("--slow-ttft-arg", type=float, default=0.4,
                    help="seconds of TTFT inflation for slow_ttft")
    sp.add_argument("--ttft-threshold", type=float, default=0.25,
                    help="drill chat_ttft SLO threshold (seconds; "
                         "clean TTFT must sit well under, slow_ttft "
                         "well over)")
    sp.add_argument("--overload-capacity", type=int, default=1,
                    help="per-engine bounded-queue capacity for the "
                         "overload scenario")
    sp.add_argument("--queue-delay-ms", type=float, default=60000.0,
                    help="injected /load queue-delay override for "
                         "queue_delay")
    sp.add_argument("--min-events", type=int, default=4,
                    help="drill SLO volume floor (router "
                         "--slo-min-events equivalent, inside the "
                         "drill config)")
    sp.add_argument("--routing", default="roundrobin",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"])
    sp.add_argument("--overhead-guard", action="store_true",
                    help="also re-run the r7 router-overhead A/B "
                         "(SLO accounting is on by default) and embed "
                         "it")
    sp.add_argument("--overhead-users", type=int, default=48)
    sp.add_argument("--overhead-duration", type=parse_duration,
                    default=10.0)
    sp.add_argument("--max-overhead-ratio", type=float, default=2.5,
                    help="exit 1 if the SLO-on overhead ratio exceeds "
                         "this band AND the same-host --no-slo "
                         "baseline by >10%% (the r7 contract, "
                         "host-normalized)")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write FIREDRILL_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_firedrill)

    sp = sub.add_parser("incident",
                        help="N peered routers + M engines + the "
                             "obsplane flight recorder: a clean "
                             "baseline captures zero bundles, each "
                             "injected fault fires its alert and "
                             "yields ONE complete bundle whose "
                             "attribution names the culprit process "
                             "and phase")
    sp.add_argument("--engines", type=int, default=3,
                    help="engine replica count behind the routers")
    sp.add_argument("--routers", type=int, default=2,
                    help="peered router replica count (r16 gossip)")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (the /fault endpoint drives "
                         "slow_ttft) or a real engine model name "
                         "(engine_down + shed_storm only)")
    sp.add_argument("--users", type=int, default=8,
                    help="closed-loop storm concurrency, spread "
                         "across the routers (80%% chat, 20%% "
                         "x-slo-class: rag)")
    sp.add_argument("--baseline", type=parse_duration, default=10.0,
                    help="clean-phase duration (the zero-spurious-"
                         "capture gate)")
    sp.add_argument("--window-scale", type=float, default=0.01,
                    help="drill SLO window scale (0.01 -> "
                         "3s/18s/36s/216s)")
    sp.add_argument("--scenarios", default=None,
                    help=f"comma-separated subset of "
                         f"{','.join(INCIDENT_SCENARIOS)} "
                         f"(default: all)")
    sp.add_argument("--detect-timeout", type=parse_duration,
                    default=None,
                    help="seconds the expected alert has to show on "
                         "the obsplane's /fleet view (default: sized "
                         "to the scaled 1h window)")
    sp.add_argument("--resolve-timeout", type=parse_duration,
                    default=None,
                    help="seconds alerts have to resolve after the "
                         "fault clears (default: sized to the scaled "
                         "30m window)")
    sp.add_argument("--num-tokens", type=int, default=4)
    sp.add_argument("--fake-tokens-per-s", type=float, default=400.0)
    sp.add_argument("--slow-ttft-arg", type=float, default=0.4,
                    help="seconds of TTFT inflation injected on ONE "
                         "engine for slow_ttft")
    sp.add_argument("--ttft-threshold", type=float, default=None,
                    help="drill chat_ttft SLO threshold (seconds; "
                         "default 0.25 for the fake fleet, 2.0 for "
                         "real engines — a real prefill would trip "
                         "the fake-calibrated bar on a clean "
                         "baseline)")
    sp.add_argument("--max-inflight", type=int, default=24,
                    help="per-router admission gate: the shed storm "
                         "must blow through it, the baseline storm "
                         "must sit well under it")
    sp.add_argument("--burst-users", type=int, default=64,
                    help="concurrency of the shed-storm burst aimed "
                         "at router 0")
    sp.add_argument("--min-events", type=int, default=4,
                    help="drill SLO volume floor")
    sp.add_argument("--routing", default="roundrobin",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"])
    sp.add_argument("--poll-interval", type=float, default=0.3,
                    help="obsplane fleet scrape interval (seconds)")
    sp.add_argument("--capture-cooldown", type=float, default=5.0,
                    help="obsplane capture cooldown (seconds; the "
                         "fleet quiet->burning edge is the primary "
                         "dedup, this is the flap backstop)")
    sp.add_argument("--incident-dir", default=None,
                    help="bundle directory (default: "
                         "<log-dir>/incidents)")
    sp.add_argument("--min-chain-fraction", type=float, default=0.5,
                    help="baseline stitched-chain completeness floor "
                         "(the anti-vacuity gate on the online join)")
    sp.add_argument("--overhead-guard", action="store_true",
                    help="run the r7 A/B with and without the "
                         "obsplane scraping the serving pair, embed "
                         "both")
    sp.add_argument("--overhead-users", type=int, default=48)
    sp.add_argument("--overhead-duration", type=parse_duration,
                    default=10.0)
    sp.add_argument("--max-overhead-ratio", type=float, default=2.5,
                    help="exit 1 if the scraped-side ratio exceeds "
                         "this band AND the same-host unscraped "
                         "baseline by >10%%")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write INCIDENT_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_incident)

    sp = sub.add_parser("fleetdrill",
                        help="the r20 fleet pilot closed loop: "
                             "burn-rate scale-up must beat the "
                             "queue-delay-only control on "
                             "replica-seconds to resolution; a slow "
                             "engine must be drained+restarted "
                             "hands-off with exactly one remediation "
                             "logged; the kill-switch run must show "
                             "the suppression AND the alert still "
                             "burning")
    sp.add_argument("--scenarios", default=None,
                    help=f"comma-separated subset of "
                         f"{','.join(FLEETDRILL_SCENARIOS)} "
                         f"(default: all)")
    sp.add_argument("--window-scale", type=float, default=0.01,
                    help="drill SLO window scale (0.01 -> "
                         "3s/18s/36s/216s)")
    sp.add_argument("--users", type=int, default=6,
                    help="closed-loop storm concurrency")
    sp.add_argument("--engines", type=int, default=3,
                    help="fixed fleet size for the remediation "
                         "scenarios (the burn scenario scales 1 -> "
                         "--max-replicas)")
    sp.add_argument("--baseline", type=parse_duration, default=6.0,
                    help="clean-phase duration before each injection")
    sp.add_argument("--detect-timeout", type=parse_duration,
                    default=None,
                    help="seconds the page alert has to fire "
                         "(default: sized to the scaled 1h window)")
    sp.add_argument("--resolve-timeout", type=parse_duration,
                    default=None,
                    help="seconds the alert has to resolve after "
                         "relief (default: sized to the scaled 30m "
                         "window)")
    sp.add_argument("--burn-ttft", type=float, default=0.4,
                    help="burn scenario: injected per-request TTFT at "
                         "1 replica (seconds; divided by the live "
                         "replica count — scale-up IS the relief)")
    sp.add_argument("--queue-ramp", type=float, default=60.0,
                    help="burn scenario: queue-delay ramp (ms per "
                         "second of incident, split across replicas) "
                         "— slow enough that the burn-rate alert "
                         "beats the queue-delay threshold")
    sp.add_argument("--queue-plateau", type=float, default=1200.0,
                    help="burn scenario: queue-delay ramp ceiling "
                         "(ms) so the control's trigger stays "
                         "bounded")
    sp.add_argument("--max-replicas", type=int, default=2,
                    help="burn scenario scale-up ceiling")
    sp.add_argument("--slow-ttft-arg", type=float, default=0.6,
                    help="remediation scenarios: TTFT inflation "
                         "injected on ONE engine (seconds)")
    sp.add_argument("--tick-interval", type=float, default=0.5,
                    help="autoscaler control-loop interval (seconds)")
    sp.add_argument("--min-events", type=int, default=4,
                    help="drill SLO volume floor")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write FLEETDRILL_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_fleetdrill)

    sp = sub.add_parser("multirouter",
                        help="N real routers (peer gossip + QoS "
                             "tiers) behind an in-process L4 "
                             "splitter: pair affinity must match the "
                             "single-router control, a router "
                             "SIGKILL must cost only the in-flight "
                             "blip, breakers must converge across "
                             "replicas, and saturation must shed "
                             "low-tier-first")
    sp.add_argument("--engines", type=int, default=3,
                    help="engine replica count behind the routers")
    sp.add_argument("--routers", type=int, default=2,
                    help="router replica count (>= 2)")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (the rig measures the control "
                         "plane, not the model) or a real engine "
                         "model name")
    sp.add_argument("--sessions", type=int, default=12,
                    help="sticky sessions in the affinity storms")
    sp.add_argument("--phase-duration", type=parse_duration,
                    default=20.0, help="seconds per phase")
    sp.add_argument("--num-tokens", type=int, default=8)
    sp.add_argument("--fake-tokens-per-s", type=float, default=60.0,
                    help="fake engines: decode pacing (slow enough "
                         "that router admission is the scarce "
                         "resource in the saturation sweep)")
    sp.add_argument("--gossip-interval", type=float, default=0.25,
                    help="router --peer-gossip-interval")
    sp.add_argument("--settle", type=parse_duration, default=3.0,
                    help="seconds after the one-sided drain before "
                         "the steady affinity window starts")
    sp.add_argument("--blip-window", type=parse_duration, default=3.0,
                    help="seconds after the router kill during which "
                         "in-flight client errors are tolerated")
    sp.add_argument("--max-inflight", type=int, default=8,
                    help="per-router --max-inflight (the saturation "
                         "sweep's scarce resource)")
    sp.add_argument("--tier0-users", type=int, default=4)
    sp.add_argument("--tier1-users", type=int, default=8)
    sp.add_argument("--tier2-users", type=int, default=16,
                    help="background users added for the saturation "
                         "phase")
    sp.add_argument("--presat-duration", type=parse_duration,
                    default=8.0,
                    help="pre-saturation tier0 goodput baseline "
                         "window")
    sp.add_argument("--routing", default="session",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"])
    sp.add_argument("--no-shared-state", action="store_true",
                    help="launch the routers WITHOUT the gossip "
                         "plane: the affinity gate must then fail "
                         "(exit 1) — the anti-vacuity check")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--skip-saturation", action="store_true",
                    help="skip the QoS saturation phase")
    sp.add_argument("--skip-kill", action="store_true",
                    help="skip the router-SIGKILL phase")
    sp.add_argument("--affinity-tolerance", type=float, default=0.05,
                    help="pair affinity may trail the control by "
                         "this much")
    sp.add_argument("--convergence-bound", type=float, default=0.0,
                    help="seconds the per-router breaker open reports "
                         "may spread (0 = one probe interval)")
    sp.add_argument("--min-tier0-hold", type=float, default=0.95,
                    help="tier0 saturated goodput as a fraction of "
                         "pre-saturation")
    sp.add_argument("--min-tier2-shed", type=float, default=0.5,
                    help="tier2 shed fraction the sweep must reach")
    sp.add_argument("--overhead-guard", action="store_true",
                    help="also re-run the r7 A/B through a shared-"
                         "state router vs a same-host plain baseline")
    sp.add_argument("--overhead-users", type=int, default=48)
    sp.add_argument("--overhead-duration", type=parse_duration,
                    default=10.0)
    sp.add_argument("--max-overhead-ratio", type=float, default=2.5,
                    help="exit 1 if the shared-state ratio exceeds "
                         "this band AND the same-host baseline by "
                         ">10%% (the r14 convention)")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write MULTIROUTER_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_multirouter)

    sp = sub.add_parser("multitenant",
                        help="two named pools (multi-model + runtime "
                             "LoRA adapters) behind one router with "
                             "per-tenant buckets and per-pool "
                             "autoscalers on a shared actuation "
                             "budget: routing must be 100%% model-"
                             "correct, pool-a churn+kill must not "
                             "touch pool-b, the noisy tenant must "
                             "shed while tier peers hold, and both "
                             "pools must log applied scale-ups")
    sp.add_argument("--baseline-duration", type=parse_duration,
                    default=6.0, help="reference-goodput window")
    sp.add_argument("--churn-duration", type=parse_duration,
                    default=14.0,
                    help="pool-a adapter churn + fault + SIGKILL "
                         "window")
    sp.add_argument("--noisy-duration", type=parse_duration,
                    default=8.0, help="noisy-tenant burst window")
    sp.add_argument("--surge-duration", type=parse_duration,
                    default=8.0, help="seconds per surge round (up "
                                      "to 3 rounds until both pools "
                                      "scale)")
    sp.add_argument("--adapter-cycles", type=int, default=2,
                    help="load->route->evict adapter cycles during "
                         "churn")
    sp.add_argument("--pool-a-replicas", type=int, default=2)
    sp.add_argument("--pool-b-replicas", type=int, default=1)
    sp.add_argument("--pool-a-max", type=int, default=3)
    sp.add_argument("--pool-b-max", type=int, default=2)
    sp.add_argument("--fake-capacity", type=int, default=4,
                    help="per-engine bounded admission (the overload "
                         "fault's capacity advertisement)")
    sp.add_argument("--num-tokens", type=int, default=4)
    sp.add_argument("--tenant-rate", type=float, default=5.0,
                    help="router --qos-tenant-rate (req/s per "
                         "x-tenant-id inside each tier)")
    sp.add_argument("--no-tenant-buckets", action="store_true",
                    help="launch the router WITHOUT per-tenant "
                         "buckets: acme's burst then saturates "
                         "pool-b and the peer-goodput gate must "
                         "fail (exit 1) — the anti-vacuity check")
    sp.add_argument("--max-inflight", type=int, default=40,
                    help="router-wide admission gate (QoS tiers "
                         "fraction it)")
    sp.add_argument("--noisy-workers", type=int, default=8,
                    help="closed-loop workers the bursting tenant "
                         "runs")
    sp.add_argument("--tick-interval", type=float, default=0.5,
                    help="autoscaler decision tick (s)")
    sp.add_argument("--interference-floor", type=float, default=0.95,
                    help="pool-b churn-phase goodput as a fraction "
                         "of baseline")
    sp.add_argument("--min-noisy-shed", type=float, default=0.5,
                    help="shed fraction the bursting tenant must "
                         "reach")
    sp.add_argument("--peer-floor", type=float, default=0.95,
                    help="ok-fraction each tier peer must keep "
                         "during the burst")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=120.0)
    sp.add_argument("--output", default=None,
                    help="write TENANT_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_multitenant)

    sp = sub.add_parser("trace",
                        help="router + engines (optionally the disagg "
                             "split); storm, then join client "
                             "x-trace-ids against the /debug/traces "
                             "rings — span chains must be complete "
                             "and phases must cover the time")
    sp.add_argument("--engines", type=int, default=2,
                    help="engine count (aggregated topology)")
    sp.add_argument("--engine", default="fake",
                    help="'fake' (deterministic pacing — measures the "
                         "tracing substrate) or a real engine model "
                         "name")
    sp.add_argument("--disagg", action="store_true",
                    help="launch the P/D split (cache server + "
                         "producer pool + consumer pool + "
                         "--prefill-backends) so the chain gate "
                         "covers router->prefill->decode")
    sp.add_argument("--prefill-engines", type=int, default=2)
    sp.add_argument("--decode-engines", type=int, default=2)
    sp.add_argument("--chat-users", type=int, default=6)
    sp.add_argument("--rag-users", type=int, default=3)
    sp.add_argument("--duration", type=parse_duration, default=20.0)
    sp.add_argument("--chat-prompt-chars", type=int, default=96)
    sp.add_argument("--chat-tokens", type=int, default=24)
    sp.add_argument("--rag-prompt-chars", type=int, default=2400)
    sp.add_argument("--rag-tokens", type=int, default=4)
    sp.add_argument("--fake-tokens-per-s", type=float, default=40.0)
    sp.add_argument("--prefill-ms-per-char", type=float, default=0.4)
    sp.add_argument("--interference", type=float, default=1.5)
    sp.add_argument("--kv-chunk-chars", type=int, default=64)
    sp.add_argument("--headstart", type=float, default=3.0)
    sp.add_argument("--min-prompt-chars", type=int, default=512)
    sp.add_argument("--routing", default="least_loaded",
                    choices=["roundrobin", "session", "least_loaded",
                             "prefix"])
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--ring-entries", type=int, default=16384,
                    help="router/engine --trace-ring-entries (must "
                         "hold the storm, or old traces churn out "
                         "before the join reads them)")
    sp.add_argument("--min-chain-fraction", type=float, default=0.95,
                    help="sampled requests that must show a complete "
                         "router->engine span chain")
    sp.add_argument("--max-unattributed", type=float, default=10.0,
                    help="percent of a trace's duration the phase "
                         "spans may leave uncovered at the p50")
    sp.add_argument("--overhead-guard", action="store_true",
                    help="also re-run the r7 router-overhead A/B "
                         "(tracing on, zero-think fake) and embed it")
    sp.add_argument("--overhead-users", type=int, default=48)
    sp.add_argument("--overhead-duration", type=parse_duration,
                    default=10.0)
    sp.add_argument("--max-overhead-ratio", type=float, default=2.5,
                    help="exit 1 if the tracing-on overhead ratio "
                         "exceeds this band (the r7 contract)")
    sp.add_argument("--platform", default="cpu")
    sp.add_argument("--log-dir", default="loadgen-logs")
    sp.add_argument("--startup-timeout", type=float, default=420.0)
    sp.add_argument("--output", default=None,
                    help="write TRACE_*.json here (default: "
                         "timestamped)")
    sp.set_defaults(fn=cmd_trace)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
