"""kvshare mode: cross-replica KV sharing, measured end to end.

The closed loop for ROADMAP item 1 / BASELINE config 3. The orchestrator
launches a shared TPKV cache server, N engine replicas wired to it as
their remote KV tier, and the real router with session affinity
DELIBERATELY broken: every round carries a ROTATED ``x-user-id``
(``kvshare-<session>-r<round>``), so the session policy's consistent
hash scatters consecutive rounds of one conversation across replicas —
deterministically per run, immune to the accidental lockstep
stickiness a global roundrobin falls into when concurrent sessions
advance in phase. Any prefix reuse must therefore flow through the
shared tier, not replica-local state. A
multi-round-QA storm (sessions of R rounds, each round replaying the
full history plus the engine's actual previous answers) measures:

- **cross-replica hit rate**: aggregate tier hit tokens / query tokens
  scraped from every engine's ``/load`` ``kv_cache`` block, with the
  foreign share (hits on chunks the serving replica never published —
  produced elsewhere) reported alongside, and every replica required to
  show foreign hits;
- **TTFT vs recompute**: the identical storm is re-run against a fleet
  launched WITHOUT the cache (same pacing, full prefill); follow-up
  rounds (>= 2 — round 1 is definitionally cold) must get faster.

``kvshare_violations`` is the pass/fail contract the CLI enforces
(exit 1): errors, hit rate <= ``min_hit_rate`` (60% default), no
TTFT improvement, or a replica that never consumed a foreign chunk.
Run with ``--no-cache`` the same contract naturally fails — the
committed acceptance check that the rig cannot pass vacuously.

Engines: the fake (``--kv-remote-url`` simulation — measures the
router + cache-server + tier protocol data path with deterministic
prefill pacing) or real engines (``--kv-transfer-config`` with a
remote tier; TTFT then includes real prefill compute skipped by
injected KV chunks).
"""

import asyncio
import dataclasses
import json
import random
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_cache_server,
                                                       launch_engine,
                                                       launch_router,
                                                       wait_cache_ready,
                                                       wait_healthy)
from production_stack_tpu.loadgen.report import percentile
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"

# real-engine geometry: debug-tiny's character-level tokenizer means
# chars ~ tokens, and the orchestrator's 1024-token max-model-len caps
# the final round's history
REAL_KV_CHUNK_TOKENS = 32


@dataclasses.dataclass
class _SessionResult:
    ttft_by_round: List[List[float]]      # [round][samples] seconds
    errors: int = 0
    error_samples: Optional[List[str]] = None


def _words(rng: random.Random, n_chars: int) -> str:
    out = []
    size = 0
    while size < n_chars:
        w = "w%04x" % rng.randrange(1 << 16)
        out.append(w)
        size += len(w) + 1
    return " ".join(out)[:n_chars]


async def _run_sessions(router_url: str, model: str, *, sessions: int,
                        rounds: int, system_chars: int, round_chars: int,
                        num_tokens: int, seed: int,
                        request_timeout_s: float = 60.0) -> _SessionResult:
    """Concurrent multi-round QA sessions through the router. Every
    round replays the full history INCLUDING the engine's actual
    previous replies (streamed deltas reassembled), so the prompts the
    engines see chain exactly like production multi-round traffic."""
    res = _SessionResult(ttft_by_round=[[] for _ in range(rounds)],
                         errors=0, error_samples=[])
    timeout = aiohttp.ClientTimeout(total=request_timeout_s)

    async def one_session(i: int) -> None:
        rng = random.Random(seed * 7919 + i)
        messages = [{"role": "system",
                     "content": f"session-{i} " + _words(rng,
                                                         system_chars)}]
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as http:
            for r in range(rounds):
                messages.append({"role": "user",
                                 "content": f"round-{r} " +
                                            _words(rng, round_chars)})
                body = json.dumps({"model": model, "messages": messages,
                                   "max_tokens": num_tokens,
                                   "stream": True}).encode()
                t0 = time.monotonic()
                first_at = None
                reply_parts: List[str] = []
                # the affinity break: the session key ROTATES every
                # round, so the session policy's consistent hash sends
                # consecutive rounds of one conversation to
                # (pseudo-randomly) different replicas — deterministic
                # per run, immune to the accidental lockstep stickiness
                # a global roundrobin can fall into when concurrent
                # sessions advance in phase
                headers = {"Content-Type": "application/json",
                           "x-user-id": f"kvshare-{i}-r{r}"}
                try:
                    async with http.post(
                            f"{router_url}{CHAT_PATH}", data=body,
                            headers=headers,
                            timeout=timeout) as resp:
                        if resp.status != 200:
                            res.errors += 1
                            if len(res.error_samples) < 8:
                                res.error_samples.append(
                                    f"HTTP {resp.status}: "
                                    f"{(await resp.text())[:120]}")
                            return
                        async for raw_line in resp.content:
                            line = raw_line.strip()
                            if not line.startswith(b"data:"):
                                continue
                            if first_at is None:
                                first_at = time.monotonic()
                            payload = line[5:].strip()
                            if payload == b"[DONE]":
                                continue
                            try:
                                delta = json.loads(payload)["choices"][0][
                                    "delta"]
                                reply_parts.append(
                                    delta.get("content") or "")
                            except (ValueError, KeyError, IndexError):
                                pass
                except (aiohttp.ClientError, ConnectionError, OSError,
                        asyncio.TimeoutError) as e:
                    res.errors += 1
                    if len(res.error_samples) < 8:
                        res.error_samples.append(
                            f"{type(e).__name__}: {e}")
                    return
                if first_at is None:
                    res.errors += 1
                    return
                res.ttft_by_round[r].append(first_at - t0)
                # the engine's EXACT reply rides into the next round's
                # history (stripped: streamed deltas carry a trailing
                # pad the non-streamed rendering does not)
                messages.append({"role": "assistant",
                                 "content": "".join(reply_parts).strip()})

    await asyncio.gather(*[one_session(i) for i in range(sessions)])
    return res


async def _scrape_kv(engine_urls: List[str]) -> Dict[str, Dict]:
    """Each engine's /load kv_cache block (empty dict when absent)."""
    out: Dict[str, Dict] = {}
    async with aiohttp.ClientSession() as http:
        for url in engine_urls:
            try:
                async with http.get(
                        f"{url}/load",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    data = await r.json()
                    out[url] = data.get("kv_cache") or {}
            except (aiohttp.ClientError, ConnectionError, OSError,
                    asyncio.TimeoutError, ValueError):
                out[url] = {}
    return out


async def _run_phase(*, cached: bool, engines: int, engine: str,
                     sessions: int, rounds: int, system_chars: int,
                     round_chars: int, num_tokens: int,
                     prefill_ms_per_char: float, kv_chunk_chars: int,
                     routing: str, seed: int, platform: str,
                     log_dir: str, startup_timeout_s: float,
                     kv_codec: Optional[str] = None) -> Dict:
    """``kv_codec`` (fake engines only) publishes deterministic
    pseudo-KV chunk bodies through the named REAL tier codec
    (kvcache/codec.py) instead of text bytes — the kvmigrate codec
    phase's lever for measuring tier-capacity ratios against the cache
    server's physical footprint."""
    procs: List[Proc] = []
    try:
        cache_url = None
        if cached:
            cache = launch_cache_server(free_port(), log_dir=log_dir)
            procs.append(cache)
            await wait_cache_ready(cache.url)
            cache_url = cache.url
        if engine == "fake":
            extra = ["--num-tokens", str(num_tokens),
                     "--tokens-per-s", "0",
                     "--prefill-ms-per-char", str(prefill_ms_per_char)]
            if cached:
                extra += ["--kv-remote-url", cache_url,
                          "--kv-chunk-chars", str(kv_chunk_chars)]
                if kv_codec:
                    extra += ["--kv-codec", kv_codec]
        else:
            extra = []
            if cached:
                extra = ["--kv-transfer-config",
                         json.dumps({"kv_role": "kv_both",
                                     "chunk_size": REAL_KV_CHUNK_TOKENS,
                                     "remote_url": cache_url})]
        engine_procs = [launch_engine(engine, free_port(),
                                      log_dir=log_dir, platform=platform,
                                      extra_args=extra)
                        for _ in range(engines)]
        procs.extend(engine_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in engine_procs])
        model = "fake-model" if engine == "fake" else engine
        router = launch_router([e.url for e in engine_procs], model,
                               free_port(), routing=routing,
                               log_dir=log_dir,
                               extra_args=["--engine-stats-interval", "2"])
        procs.append(router)
        await wait_healthy(router.url, 60.0, require_endpoints=engines)

        if engine != "fake":
            # real engines compile a new executable the first time a
            # round's prompt length crosses a prefill/kv bucket — a
            # 20 s compile inside a measured TTFT would swamp the
            # prefill savings in noise. Drive one full throwaway
            # session DIRECTLY at each engine (disjoint seed, so its
            # content never collides with measured sessions) to
            # compile every shape the storm will use.
            for idx, e in enumerate(engine_procs):
                warm = await _run_sessions(
                    e.url, model, sessions=1, rounds=rounds,
                    system_chars=system_chars, round_chars=round_chars,
                    num_tokens=num_tokens,
                    seed=seed + 100003 + idx,
                    request_timeout_s=300.0)
                if warm.errors:
                    logger.warning("kvshare warmup against %s: %d "
                                   "errors — TTFTs may include "
                                   "compiles", e.url, warm.errors)
        # counters are DELTA-scraped around the measured storm so
        # warmup traffic never dilutes the hit rate
        kv_before = await _scrape_kv([e.url for e in engine_procs])

        t0 = time.monotonic()
        res = await _run_sessions(router.url, model, sessions=sessions,
                                  rounds=rounds,
                                  system_chars=system_chars,
                                  round_chars=round_chars,
                                  num_tokens=num_tokens, seed=seed)
        elapsed = time.monotonic() - t0
        kv_after = await _scrape_kv([e.url for e in engine_procs])
        cache_stats = None
        if cached:
            # physical footprint on the shared tier (the denominator
            # of the codec capacity ratio): the cache server's STATS
            # op counts stored — i.e. ENCODED — bytes
            def _cache_stats():
                from production_stack_tpu.kvcache.store import \
                    RemoteStore
                store = RemoteStore(cache_url, connect_timeout=2.0,
                                    io_timeout=5.0)
                try:
                    return store.stats()
                finally:
                    store.close()
            try:
                cache_stats = await asyncio.to_thread(_cache_stats)
            except (OSError, ConnectionError):
                cache_stats = None
        kv = {
            url: {key: stats.get(key, 0)
                  - kv_before.get(url, {}).get(key, 0)
                  for key in ("queries", "query_tokens", "hit_tokens",
                              "foreign_hit_tokens", "bytes_loaded",
                              "bytes_saved")}
            if stats else {}
            for url, stats in kv_after.items()
        }
    finally:
        _stop(procs)

    def stat(vals: List[float]) -> Optional[Dict]:
        if not vals:
            return None
        return {"mean": round(sum(vals) / len(vals) * 1e3, 1),
                "p50": round(percentile(vals, 50) * 1e3, 1),
                "p90": round(percentile(vals, 90) * 1e3, 1)}

    followup = [t for r in res.ttft_by_round[1:] for t in r]
    total_q = sum(e.get("query_tokens", 0) for e in kv.values())
    total_h = sum(e.get("hit_tokens", 0) for e in kv.values())
    total_f = sum(e.get("foreign_hit_tokens", 0) for e in kv.values())
    return {
        "cached": cached,
        "duration_s": round(elapsed, 1),
        "errors": res.errors,
        "error_samples": res.error_samples,
        "completed_rounds": sum(len(r) for r in res.ttft_by_round),
        "ttft_ms_by_round": [stat(r) for r in res.ttft_by_round],
        "ttft_followup": stat(followup),
        "hit_rate": round(total_h / total_q, 4) if total_q else 0.0,
        "foreign_share": round(total_f / total_h, 4) if total_h else 0.0,
        "query_tokens": total_q,
        "hit_tokens": total_h,
        "foreign_hit_tokens": total_f,
        "bytes_saved": sum(e.get("bytes_saved", 0) for e in kv.values()),
        "per_engine_kv": kv,
        "cache_server": cache_stats,
    }


async def run_kvshare(*, engines: int = 2,
                      engine: str = "fake",
                      sessions: int = 4,
                      rounds: int = 6,
                      system_chars: int = 384,
                      round_chars: int = 160,
                      num_tokens: int = 8,
                      prefill_ms_per_char: float = 0.5,
                      kv_chunk_chars: int = 64,
                      routing: str = "session",
                      seed: int = 0,
                      no_cache: bool = False,
                      platform: str = "cpu",
                      log_dir: str = "loadgen-logs",
                      startup_timeout_s: float = 420.0) -> Dict:
    """Run the cached phase (or the bare fleet with ``no_cache``) plus
    the recompute comparison baseline; return the KVSHARE record."""
    kwargs = dict(engines=engines, engine=engine, sessions=sessions,
                  rounds=rounds, system_chars=system_chars,
                  round_chars=round_chars, num_tokens=num_tokens,
                  prefill_ms_per_char=prefill_ms_per_char,
                  kv_chunk_chars=kv_chunk_chars, routing=routing,
                  seed=seed, platform=platform, log_dir=log_dir,
                  startup_timeout_s=startup_timeout_s)
    logger.info("kvshare: %d %s engines via %s routing (affinity "
                "broken), %d sessions x %d rounds%s", engines, engine,
                routing, sessions, rounds,
                " [NO CACHE]" if no_cache else "")
    main = await _run_phase(cached=not no_cache, **kwargs)
    baseline = None
    if not no_cache:
        logger.info("kvshare: measuring the recompute baseline "
                    "(same fleet, no KV tiers)...")
        baseline = await _run_phase(cached=False, **kwargs)

    main_ttft = (main.get("ttft_followup") or {}).get("mean")
    base_ttft = (baseline.get("ttft_followup") or {}).get("mean") \
        if baseline else None
    improvement = None
    if main_ttft and base_ttft:
        improvement = round(100.0 * (1.0 - main_ttft / base_ttft), 1)
    return {
        "metric": "cross-replica KV sharing: tier hit rate and "
                  "follow-up-round TTFT with session affinity broken "
                  "(multi-round QA, session key rotated every round; "
                  "shared TPKV cache server as the cross-replica "
                  "rendezvous)",
        "value": round(100.0 * main["hit_rate"], 1),
        "unit": "% hit rate",
        "platform": platform,
        "detail": {
            "engine": engine, "engines": engines, "routing": routing,
            "sessions": sessions, "rounds": rounds,
            "system_chars": system_chars, "round_chars": round_chars,
            "num_tokens": num_tokens,
            "prefill_ms_per_char": prefill_ms_per_char
            if engine == "fake" else None,
            "kv_chunk": kv_chunk_chars if engine == "fake"
            else REAL_KV_CHUNK_TOKENS,
            "no_cache": no_cache,
            "seed": seed,
            "cached": main,
            "recompute_baseline": baseline,
            "ttft_followup_mean_ms": {
                "cached": main_ttft, "recompute": base_ttft,
                "improvement_pct": improvement},
        },
    }


def kvshare_violations(record: Dict,
                       min_hit_rate: float = 0.6) -> List[str]:
    """The kvshare pass/fail contract (CLI exits 1 on any violation)."""
    d = record["detail"]
    main = d["cached"]
    out: List[str] = []
    if main["errors"]:
        out.append(f"{main['errors']} client-visible errors in the "
                   f"measured storm")
    base = d.get("recompute_baseline")
    if base and base["errors"]:
        out.append(f"{base['errors']} errors in the recompute baseline")
    expected = d["sessions"] * d["rounds"]
    if main["completed_rounds"] < expected:
        out.append(f"only {main['completed_rounds']}/{expected} rounds "
                   f"completed")
    if main["hit_rate"] <= min_hit_rate:
        out.append(f"cross-replica hit rate {main['hit_rate']:.1%} <= "
                   f"the {min_hit_rate:.0%} bar (affinity broken: reuse "
                   f"must flow through the shared tier)")
    if d["engines"] > 1 and not d["no_cache"]:
        cold = [url for url, kv in main["per_engine_kv"].items()
                if not kv.get("foreign_hit_tokens")]
        if cold:
            out.append(f"{len(cold)} replica(s) never consumed a "
                       f"foreign chunk ({', '.join(cold)}) — sharing is "
                       f"not cross-replica")
    ttft = d["ttft_followup_mean_ms"]
    if ttft["cached"] is None or ttft["recompute"] is None:
        out.append("TTFT comparison missing (no follow-up rounds "
                   "measured on one side)")
    elif ttft["cached"] >= ttft["recompute"]:
        out.append(f"follow-up TTFT did not improve: cached "
                   f"{ttft['cached']:.1f}ms >= recompute "
                   f"{ttft['recompute']:.1f}ms")
    return out
