"""Multi-router closed loop: N real routers behind an L4 split.

ROADMAP item 4's acceptance rig (ISSUE 13, ``MULTIROUTER_r16.json``).
Launches N fake engines + R≥2 REAL router processes wired together as
a shared-state control plane (``--peer-routers`` gossip,
``--qos-tiers``, apportioned caps — router/shared_state.py + qos.py),
fronts them with a dumb in-process L4 TCP splitter (round-robin per
connection, connect-failure failover — the loadgen stand-in for a
cloud NLB), and drives four phases:

1. **control** — the affinity storm through ONE router directly: the
   single-router baseline the pair must match.
2. **pair** — the identical storm through the splitter, with the
   asymmetric control-plane event that splits un-gossiped routers:
   an ``/admin/drain`` issued through ONE router only (exactly how an
   operator drains), plus a breaker-convergence probe (a scheduled
   error burst against one engine; both routers must report it open
   within one probe interval of each other). Affinity hit rate =
   mean per-session fraction of steady-window requests on the
   session's modal engine (measured from the ``x-engine-id`` each
   fake stamps). With shared state both routers move the drained
   engine's sessions to the SAME consistent-hash successor; with
   ``--no-shared-state`` the un-drained router keeps routing into
   the drain — the affinity gate MUST fail (anti-vacuity).
3. **router_kill** — SIGKILL one router mid-storm. The splitter
   reroutes new connections on connect failure, so the kill may cost
   only the requests in flight on the dead replica: every client
   error must land inside the kill→recover blip window (counted and
   reported), zero client 5xx outside it, zero steady-state errors
   after the replica returns.
4. **saturation** — a tiered storm (``x-priority-class``) past the
   routers' ``--max-inflight``: tier-0 goodput must hold ≥95% of its
   pre-saturation rate while tier-2 sheds ≥50% — the low-tier-first
   contract, fleet-wide.

``multirouter_violations`` is the pass/fail contract (CLI exits 1 on
any); ``--overhead-guard`` re-runs the r7 A/B through a shared-state
router against a same-host plain baseline (r14 convention: within the
band, or within 10% of the baseline).
"""

import asyncio
import json
import random
import time
from typing import Dict, List, Optional, Tuple

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_engine,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.loadgen.report import percentile
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"

# fail fast, fail over, probe quickly — plus the shared-state plane
ROUTER_BASE_ARGS = ["--request-timeout", "20",
                    "--breaker-threshold", "2",
                    "--breaker-cooldown", "1.5",
                    "--breaker-probe-interval", "0.5",
                    "--failover-attempts", "3"]

QOS_TIERS = "tier0=1.0,tier1=0.85,tier2=0.7"


# ---------------------------------------------------------------- splitter

class L4Splitter:
    """Dumb TCP splitter: new connections round-robin over the router
    replicas; a connect failure tries the next replica (that is ALL a
    cloud L4 does — no health checks, no request awareness). Serves
    one listening port; counts per-backend connections and connect
    failovers so the record shows the kill actually moved traffic."""

    def __init__(self, backends: List[Tuple[str, int]],
                 host: str = "127.0.0.1", port: Optional[int] = None):
        self.backends = list(backends)
        self.host = host
        self.port = port or free_port()
        self._rr = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: Dict[str, int] = {
            f"{h}:{p}": 0 for h, p in self.backends}
        self.connect_failovers = 0
        self.refused = 0

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, client_reader: asyncio.StreamReader,
                      client_writer: asyncio.StreamWriter) -> None:
        upstream = None
        first = self._rr
        self._rr += 1
        for i in range(len(self.backends)):
            h, p = self.backends[(first + i) % len(self.backends)]
            try:
                upstream = await asyncio.open_connection(h, p)
                self.connections[f"{h}:{p}"] += 1
                break
            except OSError:
                self.connect_failovers += 1
                upstream = None
        if upstream is None:
            self.refused += 1
            client_writer.close()
            return
        up_reader, up_writer = upstream

        async def pipe(reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
            try:
                while True:
                    data = await reader.read(65536)
                    if not data:
                        break
                    writer.write(data)
                    await writer.drain()
            except (OSError, asyncio.IncompleteReadError,
                    ConnectionError):
                pass
            finally:
                try:
                    writer.close()
                except OSError:
                    pass

        await asyncio.gather(pipe(client_reader, up_writer),
                             pipe(up_reader, client_writer))


# ---------------------------------------------------------------- storm

class _Rec:
    __slots__ = ("t", "session", "tier", "kind", "engine", "router",
                 "latency_s")

    def __init__(self, t, session, tier, kind, engine, router,
                 latency_s):
        self.t = t                      # completion, monotonic
        self.session = session
        self.tier = tier
        self.kind = kind                # ok | shed | http_5xx |
                                        # http_4xx | transport
        self.engine = engine            # x-engine-id (ok only)
        self.router = router            # x-router-id
        self.latency_s = latency_s


async def _storm(url: str, model: str, *, deadline: float,
                 sessions: List[Tuple[str, str]],
                 num_tokens: int = 8,
                 think_s: float = 0.01,
                 request_timeout_s: float = 20.0,
                 sink: Optional[List[_Rec]] = None) -> List[_Rec]:
    """Closed-loop storm: one worker per (session_id, tier). Fresh
    connection per request (``force_close``) so the splitter's
    per-connection round-robin becomes per-request — both routers see
    every session, which is the whole point. ``sink`` lets a
    concurrent task (the drain scheduler) read records live."""
    recs: List[_Rec] = sink if sink is not None else []
    timeout = aiohttp.ClientTimeout(total=request_timeout_s)

    async def worker(session_id: str, tier: str) -> None:
        # jittered think time: synchronized closed-loop workers phase-
        # lock with the splitter's global connection round-robin, and a
        # phase-locked session sees only ONE router — hiding exactly
        # the cross-router divergence the affinity metric measures
        jitter = random.Random(session_id)
        headers = {"Content-Type": "application/json",
                   "x-user-id": session_id}
        if tier:
            headers["x-priority-class"] = tier
        body = json.dumps({
            "model": model,
            "messages": [{"role": "user",
                          "content": f"multirouter {session_id}"}],
            "max_tokens": num_tokens, "stream": False}).encode()
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0,
                                               force_close=True)) as s:
            while time.monotonic() < deadline:
                t0 = time.monotonic()
                kind, engine, router = "transport", "", ""
                try:
                    async with s.post(f"{url}{CHAT_PATH}", data=body,
                                      headers=headers,
                                      timeout=timeout) as resp:
                        router = resp.headers.get("x-router-id", "")
                        if resp.status == 200:
                            await resp.read()
                            kind = "ok"
                            engine = resp.headers.get("x-engine-id", "")
                        elif resp.status in (429, 503) and \
                                "Retry-After" in resp.headers:
                            await resp.read()
                            kind = "shed"
                        elif resp.status >= 500:
                            await resp.read()
                            kind = "http_5xx"
                        else:
                            await resp.read()
                            kind = "http_4xx"
                except (aiohttp.ClientError, ConnectionError, OSError,
                        asyncio.TimeoutError):
                    kind = "transport"
                now = time.monotonic()
                recs.append(_Rec(now, session_id, tier, kind, engine,
                                 router, now - t0))
                if kind == "shed":
                    await asyncio.sleep(0.1)   # honor the backoff
                else:
                    await asyncio.sleep(think_s *
                                        (0.5 + jitter.random()))

    await asyncio.gather(*(worker(sid, tier) for sid, tier in sessions))
    return recs


def _affinity_hit_rate(recs: List[_Rec], *, after: float,
                       min_requests: int = 3) -> Optional[float]:
    """Mean per-session modal-engine fraction over ok-requests
    completing after ``after`` — 1.0 means every session stuck to one
    engine for the whole steady window, split-brain drags it down."""
    per: Dict[str, Dict[str, int]] = {}
    for r in recs:
        if r.kind == "ok" and r.t >= after and r.engine:
            per.setdefault(r.session, {}) \
               .setdefault(r.engine, 0)
            per[r.session][r.engine] += 1
    rates = []
    for session, engines in per.items():
        total = sum(engines.values())
        if total >= min_requests:
            rates.append(max(engines.values()) / total)
    if not rates:
        return None
    return sum(rates) / len(rates)


def _kinds(recs: List[_Rec]) -> Dict[str, int]:
    out = {"ok": 0, "shed": 0, "http_5xx": 0, "http_4xx": 0,
           "transport": 0}
    for r in recs:
        out[r.kind] += 1
    return out


# ---------------------------------------------------------------- helpers

async def _routers_report_state(router_urls: List[str], engine_url: str,
                                want_open: bool, timeout_s: float,
                                poll_s: float = 0.05) -> Dict[str, float]:
    """Poll every router's /health until each reports ``engine_url``'s
    breaker open (or closed again); returns per-router seconds-to-
    report (inf for routers that never did)."""
    t0 = time.monotonic()
    seen: Dict[str, float] = {}
    async with aiohttp.ClientSession() as s:
        while time.monotonic() - t0 < timeout_s \
                and len(seen) < len(router_urls):
            for url in router_urls:
                if url in seen:
                    continue
                try:
                    async with s.get(f"{url}/health",
                                     timeout=aiohttp.ClientTimeout(
                                         total=2)) as r:
                        body = await r.json()
                except (aiohttp.ClientError, ConnectionError, OSError,
                        asyncio.TimeoutError, ValueError):
                    continue
                st = (body.get("breakers") or {}).get(engine_url, {})
                is_open = st.get("state") in ("open", "half_open")
                if is_open == want_open:
                    seen[url] = time.monotonic() - t0
            await asyncio.sleep(poll_s)
    return {u: seen.get(u, float("inf")) for u in router_urls}


async def _drain(router_url: str, engine_url: str, drain: bool) -> None:
    async with aiohttp.ClientSession() as s:
        async with s.post(f"{router_url}/admin/drain",
                          json={"url": engine_url, "drain": drain},
                          timeout=aiohttp.ClientTimeout(total=5)) as r:
            if r.status != 200:
                raise RuntimeError(
                    f"drain({drain}) via {router_url} -> HTTP {r.status}")


async def _inject_error_burst(engine_url: str, count: int) -> None:
    async with aiohttp.ClientSession() as s:
        async with s.post(f"{engine_url}/fault",
                          json={"mode": "error", "count": count},
                          timeout=aiohttp.ClientTimeout(total=5)) as r:
            if r.status != 200:
                raise RuntimeError(f"fault injection -> HTTP {r.status}")


def _launch_router_replica(idx: int, port: int, engine_urls: List[str],
                           model: str, peer_ports: List[int], *,
                           routing: str, shared_state: bool,
                           max_inflight: int, gossip_interval_s: float,
                           log_dir: str) -> Proc:
    peers = ",".join(f"http://127.0.0.1:{p}" for p in peer_ports)
    extra = list(ROUTER_BASE_ARGS)
    extra += ["--router-id", f"router-{idx}",
              "--qos-tiers", QOS_TIERS,
              "--max-inflight", str(max_inflight),
              "--engine-stats-interval", "1"]
    if peers:
        extra += ["--peer-routers", peers,
                  "--peer-gossip-interval", str(gossip_interval_s)]
    if not shared_state:
        extra += ["--no-shared-state"]
    return launch_router(engine_urls, model, port, routing=routing,
                         log_dir=log_dir, extra_args=extra)


# ---------------------------------------------------------------- run

async def run_multirouter(*, engines: int = 3,
                          routers: int = 2,
                          engine: str = "fake",
                          sessions: int = 12,
                          phase_duration_s: float = 20.0,
                          num_tokens: int = 8,
                          tokens_per_s: float = 60.0,
                          gossip_interval_s: float = 0.25,
                          settle_s: float = 3.0,
                          blip_window_s: float = 3.0,
                          max_inflight: int = 8,
                          tier0_users: int = 4,
                          tier1_users: int = 8,
                          tier2_users: int = 16,
                          saturation_presat_s: float = 8.0,
                          routing: str = "session",
                          shared_state: bool = True,
                          seed: int = 0,
                          platform: str = "cpu",
                          log_dir: str = "loadgen-logs",
                          startup_timeout_s: float = 420.0,
                          skip_saturation: bool = False,
                          skip_kill: bool = False,
                          skip_convergence: bool = False,
                          convergence_storm_s: float = 8.0,
                          overhead_guard: bool = False,
                          overhead_users: int = 48,
                          overhead_duration_s: float = 10.0) -> Dict:
    """Launch the stack, run the four phases, return the MULTIROUTER
    record (BENCH schema; headline value = pair affinity hit rate %)."""
    if routers < 2:
        raise ValueError("the multirouter rig needs >= 2 routers")
    rng = random.Random(seed)
    procs: List[Proc] = []
    router_procs: List[Proc] = []
    detail: Dict = {}
    splitter: Optional[L4Splitter] = None
    try:
        # --- engines ---------------------------------------------------
        engine_extra = None
        if engine == "fake":
            # pace via --ttft: a deterministic per-request service time
            # (num_tokens / tokens_per_s) that applies to the
            # NON-streaming path the storms use — tokens_per_s pacing
            # alone only stretches streamed chunk gaps. The saturation
            # sweep needs real service time, or router admission never
            # becomes the scarce resource
            engine_extra = ["--ttft", str(num_tokens / tokens_per_s),
                            "--tokens-per-s", "0",
                            "--num-tokens", str(num_tokens)]
        engine_procs = [launch_engine(engine, free_port(),
                                      log_dir=log_dir, platform=platform,
                                      extra_args=engine_extra)
                        for _ in range(engines)]
        procs.extend(engine_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in engine_procs])
        model = "fake-model" if engine == "fake" else engine
        engine_urls = [e.url for e in engine_procs]

        # --- routers ---------------------------------------------------
        ports = [free_port() for _ in range(routers)]
        for i, port in enumerate(ports):
            router_procs.append(_launch_router_replica(
                i, port, engine_urls, model,
                [p for p in ports if p != port],
                routing=routing, shared_state=shared_state,
                max_inflight=max_inflight,
                gossip_interval_s=gossip_interval_s, log_dir=log_dir))
        procs.extend(router_procs)
        await asyncio.gather(*[
            wait_healthy(r.url, 60.0, require_endpoints=engines)
            for r in router_procs])
        router_urls = [r.url for r in router_procs]

        splitter = L4Splitter([("127.0.0.1", p) for p in ports])
        await splitter.start()
        logger.info("multirouter: %d engines, %d routers (%s), "
                    "splitter %s, shared_state=%s", engines, routers,
                    ",".join(router_urls), splitter.url, shared_state)

        plain_sessions = [(f"mr-s{i:02d}", "") for i in range(sessions)]

        async def affinity_phase(target_url: str,
                                 drain_via: str) -> Dict:
            """The affinity storm: drain one engine through ONE router
            a third of the way in, never undrain; measure the steady
            window after the drain settles."""
            t0 = time.monotonic()
            deadline = t0 + phase_duration_s
            drain_at = t0 + phase_duration_s / 3.0
            live_recs: List[_Rec] = []
            chosen: Dict[str, str] = {}

            async def drainer():
                await asyncio.sleep(max(0.0, drain_at - time.monotonic()))
                # drain the engine serving the MOST sessions so far:
                # the probe must actually displace traffic, or session
                # hashing can hand it an idle engine and the
                # anti-vacuity split never materializes (flaky)
                counts: Dict[str, int] = {}
                for r in list(live_recs):
                    if r.kind == "ok" and r.engine:
                        counts[r.engine] = counts.get(r.engine, 0) + 1
                victim = engine_urls[rng.randrange(len(engine_urls))]
                if counts:
                    candidate = f"http://{max(counts, key=counts.get)}"
                    if candidate in engine_urls:
                        victim = candidate
                chosen["victim"] = victim
                await _drain(drain_via, victim, True)

            task = asyncio.create_task(drainer())
            try:
                recs = await _storm(target_url, model, deadline=deadline,
                                    sessions=plain_sessions,
                                    num_tokens=num_tokens,
                                    sink=live_recs)
            finally:
                task.cancel()
                await asyncio.gather(task, return_exceptions=True)
            victim = chosen.get("victim")
            # leave the fleet clean for the next phase: undrain via
            # every router (idempotent; end_drain is permissive)
            if victim is not None:
                for url in router_urls:
                    try:
                        await _drain(url, victim, False)
                    except RuntimeError:
                        pass
            await asyncio.sleep(2.5 * gossip_interval_s)
            hit = _affinity_hit_rate(recs, after=drain_at + settle_s)
            by_engine: Dict[str, int] = {}
            for r in recs:
                if r.kind == "ok" and r.engine:
                    by_engine[r.engine] = by_engine.get(r.engine, 0) + 1
            return {"kinds": _kinds(recs),
                    "drained_engine": victim,
                    "drain_at_s": round(drain_at - t0, 2),
                    "steady_after_s": round(drain_at + settle_s - t0, 2),
                    "affinity_hit_rate": round(hit, 4)
                    if hit is not None else None,
                    "requests": len(recs),
                    "requests_by_engine": by_engine}

        # --- phase 1: single-router control ----------------------------
        logger.info("multirouter phase 1/4: single-router control "
                    "(%.0fs)", phase_duration_s)
        control = await affinity_phase(router_urls[0], router_urls[0])
        detail["control"] = control

        # --- phase 2: the pair, drain issued via one router ------------
        logger.info("multirouter phase 2/4: pair behind the splitter "
                    "(%.0fs)", phase_duration_s)
        pair = await affinity_phase(splitter.url, router_urls[0])
        detail["pair"] = pair

        # breaker convergence: burst one engine into 500s while a
        # short storm runs; both routers must report it open within
        # one probe interval of each other. The victim is the engine
        # the pair phase routed MOST traffic to (x-engine-id is
        # host:port — the URL minus scheme), so session hashing can
        # never pick a burst target the storm's sessions skip.
        if not skip_convergence:
            by_engine = pair.get("requests_by_engine") or {}
            burst_victim = engine_urls[0]
            if by_engine:
                busiest = max(by_engine, key=by_engine.get)
                candidate = f"http://{busiest}"
                if candidate in engine_urls:
                    burst_victim = candidate
            t_conv = time.monotonic()
            storm_task = asyncio.create_task(_storm(
                splitter.url, model,
                deadline=t_conv + convergence_storm_s,
                sessions=plain_sessions, num_tokens=num_tokens))
            await asyncio.sleep(0.5)
            await _inject_error_burst(burst_victim, count=12)
            opened = await _routers_report_state(
                router_urls, burst_victim, want_open=True,
                timeout_s=6.0)
            closed = await _routers_report_state(
                router_urls, burst_victim, want_open=False,
                timeout_s=8.0)
            await storm_task
            times = [t for t in opened.values() if t != float("inf")]
            convergence_s = (max(times) - min(times)) if len(times) == \
                len(router_urls) else float("inf")
            detail["breaker_convergence"] = {
                "victim": burst_victim,
                "open_report_s": {u: (round(t, 3) if t != float("inf")
                                      else None)
                                  for u, t in opened.items()},
                "close_report_s": {u: (round(t, 3) if t != float("inf")
                                       else None)
                                   for u, t in closed.items()},
                "open_spread_s": round(convergence_s, 3)
                if convergence_s != float("inf") else None,
                "probe_interval_s": 0.5,
            }

        # --- phase 3: router SIGKILL mid-storm -------------------------
        if not skip_kill:
            logger.info("multirouter phase 3/4: router SIGKILL "
                        "(%.0fs)", phase_duration_s)
            t0 = time.monotonic()
            deadline = t0 + phase_duration_s
            kill_at = t0 + phase_duration_s / 3.0
            victim_idx = len(router_procs) - 1
            events: List[Dict] = []

            async def killer():
                await asyncio.sleep(max(0.0, kill_at - time.monotonic()))
                victim = router_procs[victim_idx]
                victim.popen.kill()
                victim.popen.wait()
                events.append({"t_s": round(time.monotonic() - t0, 2),
                               "event": "router_kill",
                               "url": victim.url})
                logger.info("multirouter: killed %s", victim.url)
                await asyncio.sleep(2.0)
                router_procs[victim_idx] = _launch_router_replica(
                    victim_idx, ports[victim_idx], engine_urls, model,
                    [p for p in ports if p != ports[victim_idx]],
                    routing=routing, shared_state=shared_state,
                    max_inflight=max_inflight,
                    gossip_interval_s=gossip_interval_s,
                    log_dir=log_dir)
                events.append({"t_s": round(time.monotonic() - t0, 2),
                               "event": "router_restart",
                               "url": router_procs[victim_idx].url})
                try:
                    await wait_healthy(router_procs[victim_idx].url,
                                       30.0, require_endpoints=engines)
                    events.append(
                        {"t_s": round(time.monotonic() - t0, 2),
                         "event": "router_healthy",
                         "url": router_procs[victim_idx].url})
                except TimeoutError:
                    logger.warning("multirouter: %s not healthy after "
                                   "restart", router_procs[victim_idx].url)

            ktask = asyncio.create_task(killer())
            try:
                recs = await _storm(splitter.url, model,
                                    deadline=deadline,
                                    sessions=plain_sessions,
                                    num_tokens=num_tokens)
            finally:
                await asyncio.gather(ktask, return_exceptions=True)
            kill_rel = next((e["t_s"] for e in events
                             if e["event"] == "router_kill"), None)
            blip = []
            outside = []
            for r in recs:
                if r.kind in ("transport", "http_5xx"):
                    rel = r.t - t0
                    # the kill stamp lands AFTER popen.wait(); the
                    # dead replica's connections reset the instant the
                    # signal delivers, so the window opens 0.5s early
                    if kill_rel is not None and \
                            kill_rel - 0.5 <= rel <= \
                            kill_rel + blip_window_s:
                        blip.append(r.kind)
                    else:
                        outside.append((round(rel, 2), r.kind))
            detail["router_kill"] = {
                "kinds": _kinds(recs),
                "events": events,
                "kill_fired": kill_rel is not None,
                "blip_window_s": blip_window_s,
                "blip_errors": len(blip),
                "errors_outside_blip": outside[:20],
                "errors_outside_blip_count": len(outside),
                "splitter_connect_failovers": splitter.connect_failovers,
                "splitter_connections": dict(splitter.connections),
                "post_restart_ok": sum(
                    1 for r in recs
                    if r.kind == "ok" and kill_rel is not None
                    and r.t - t0 > kill_rel + blip_window_s),
            }

        # --- phase 4: tiered saturation sweep --------------------------
        if not skip_saturation:
            logger.info("multirouter phase 4/4: QoS saturation sweep "
                        "(%.0fs + %.0fs)", saturation_presat_s,
                        phase_duration_s)
            presat_sessions = \
                [(f"t0-{i}", "tier0") for i in range(tier0_users)] + \
                [(f"t1-{i}", "tier1") for i in range(tier1_users)]
            t0 = time.monotonic()
            pre = await _storm(splitter.url, model,
                               deadline=t0 + saturation_presat_s,
                               sessions=presat_sessions,
                               num_tokens=num_tokens)
            pre_window = saturation_presat_s
            sat_sessions = presat_sessions + \
                [(f"t2-{i}", "tier2") for i in range(tier2_users)]
            t1 = time.monotonic()
            sat = await _storm(splitter.url, model,
                               deadline=t1 + phase_duration_s,
                               sessions=sat_sessions,
                               num_tokens=num_tokens)

            def tier_stats(recs, window_s):
                out = {}
                for tier in ("tier0", "tier1", "tier2"):
                    rows = [r for r in recs if r.tier == tier]
                    kinds = _kinds(rows)
                    total = len(rows)
                    lat = [r.latency_s for r in rows if r.kind == "ok"]
                    out[tier] = {
                        **kinds,
                        "goodput_qps": round(kinds["ok"] / window_s, 2),
                        "shed_fraction": round(kinds["shed"] / total, 4)
                        if total else None,
                        "latency_p50_ms": round(
                            percentile(lat, 50) * 1e3, 1) if lat else None,
                        "latency_p99_ms": round(
                            percentile(lat, 99) * 1e3, 1) if lat else None,
                    }
                return out

            detail["saturation"] = {
                "presat_s": pre_window,
                "saturated_s": phase_duration_s,
                "max_inflight_per_router": max_inflight,
                "qos_tiers": QOS_TIERS,
                "presat": tier_stats(pre, pre_window),
                "saturated": tier_stats(sat, phase_duration_s),
            }
            # per-tier QoS counters off one router's /metrics
            detail["saturation"]["router_qos_metrics"] = \
                await _scrape_qos(router_urls[0])
    finally:
        if splitter is not None:
            await splitter.close()
        current = list(router_procs)
        current.extend(p for p in procs if p not in current)
        _stop(current)

    if overhead_guard:
        detail["overhead_guard"] = await _overhead_guard(
            users=overhead_users, duration_s=overhead_duration_s,
            gossip_interval_s=gossip_interval_s, platform=platform,
            log_dir=log_dir, startup_timeout_s=startup_timeout_s)

    pair_hit = (detail.get("pair") or {}).get("affinity_hit_rate")
    return {
        "metric": "multi-router control plane: pair affinity hit rate "
                  "behind an L4 split vs the single-router control "
                  "(+ router-kill blip containment, breaker "
                  "convergence, QoS tier degradation)",
        "value": round(100.0 * pair_hit, 2) if pair_hit is not None
        else None,
        "unit": "%",
        "platform": platform,
        "detail": {
            "engine": engine, "engines": engines, "routers": routers,
            "routing": routing, "sessions": sessions,
            "shared_state": shared_state,
            "gossip_interval_s": gossip_interval_s,
            "phase_duration_s": phase_duration_s,
            **detail,
        },
    }


async def _scrape_qos(router_url: str) -> Dict[str, float]:
    import re
    wanted = ("tpu:router_qos_sheds_total",
              "tpu:router_qos_preemptions_total",
              "tpu:router_affinity_moves_total",
              "tpu:router_peers")
    out: Dict[str, float] = {}
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{router_url}/metrics",
                             timeout=aiohttp.ClientTimeout(total=5)) as r:
                text = await r.text()
    except (aiohttp.ClientError, ConnectionError, OSError,
            asyncio.TimeoutError):
        return out
    for name in wanted:
        for m in re.finditer(
                rf"^{re.escape(name)}({{[^}}]*}})?\s+([0-9.eE+-]+)",
                text, re.M):
            out[f"{name}{m.group(1) or ''}"] = float(m.group(2))
    return out


async def _overhead_guard(*, users: int, duration_s: float,
                          gossip_interval_s: float, platform: str,
                          log_dir: str,
                          startup_timeout_s: float,
                          rounds: int = 2) -> Dict:
    """r7 band no-regression through one router of a shared-state
    pair: the A/B with gossip + QoS enabled vs the same-host plain
    baseline (the r14 guard convention — band OR baseline+10%).

    Both sides run ``rounds`` times ALTERNATING and each side keeps
    its best round (highest router-side req/s): the router-side
    number swings ±10% run-to-run on a busy host, and a guard that
    fails on a one-sided fluke teaches people to ignore it. Every
    round's numbers are reported."""
    from production_stack_tpu.loadgen.overhead import run_overhead
    # an idle peer replica so the gossip loop has a real conversation
    # (its backend list is a dead port: it serves /peers, routes nothing)
    peer = launch_router(["http://127.0.0.1:9"], "fake-model",
                         free_port(), routing="roundrobin",
                         log_dir=log_dir,
                         extra_args=["--router-id", "guard-peer"])
    shared_runs: List[Dict] = []
    baseline_runs: List[Dict] = []
    try:
        await wait_healthy(peer.url, 30.0)
        for _ in range(max(1, rounds)):
            shared_runs.append(await run_overhead(
                engine="fake", users=users, duration_s=duration_s,
                platform=platform, log_dir=log_dir,
                startup_timeout_s=startup_timeout_s,
                router_extra_args=["--router-id", "guard-shared",
                                   "--peer-routers", peer.url,
                                   "--peer-gossip-interval",
                                   str(gossip_interval_s),
                                   "--qos-tiers", QOS_TIERS]))
            baseline_runs.append(await run_overhead(
                engine="fake", users=users, duration_s=duration_s,
                platform=platform, log_dir=log_dir,
                startup_timeout_s=startup_timeout_s))
    finally:
        _stop([peer])

    def best(runs: List[Dict]) -> Dict:
        return max(runs,
                   key=lambda r: r["detail"]["router"]["req_per_s"])

    def side(run: Dict) -> Dict:
        return {"router_req_per_s": run["detail"]["router"]["req_per_s"],
                "errors": run["detail"]["router"]["errors"]
                + run["detail"]["direct"]["errors"]}

    shared, baseline = best(shared_runs), best(baseline_runs)
    return {
        "users": users, "duration_s": duration_s, "rounds": rounds,
        "overhead_ratio": shared["detail"]["overhead_ratio"],
        "baseline_ratio": baseline["detail"]["overhead_ratio"],
        "shared": side(shared),
        "baseline": side(baseline),
        "all_rounds": {
            "shared": [{"ratio": r["detail"]["overhead_ratio"],
                        **side(r)} for r in shared_runs],
            "baseline": [{"ratio": r["detail"]["overhead_ratio"],
                          **side(r)} for r in baseline_runs]},
    }


# ---------------------------------------------------------------- gates

def multirouter_violations(record: Dict, *,
                           affinity_tolerance: float = 0.05,
                           convergence_bound_s: Optional[float] = None,
                           min_tier0_hold: float = 0.95,
                           min_tier2_shed: float = 0.5,
                           max_overhead_ratio: Optional[float] = None
                           ) -> List[str]:
    """The multirouter contract (CLI exits 1 on any violation)."""
    d = record["detail"]
    out: List[str] = []

    control = d.get("control") or {}
    pair = d.get("pair") or {}
    c_hit, p_hit = control.get("affinity_hit_rate"), \
        pair.get("affinity_hit_rate")
    if c_hit is None or p_hit is None:
        out.append("affinity hit rate unmeasured (too few steady-"
                   "window samples)")
    elif p_hit < c_hit - affinity_tolerance:
        out.append(f"pair affinity hit rate {p_hit:.1%} is more than "
                   f"{affinity_tolerance:.0%} below the single-router "
                   f"control's {c_hit:.1%} — the routers disagree "
                   f"about the endpoint view (split-brain)")
    for phase_name, phase in (("control", control), ("pair", pair)):
        kinds = phase.get("kinds") or {}
        if kinds.get("http_5xx") or kinds.get("transport"):
            out.append(f"{phase_name} phase saw "
                       f"{kinds.get('http_5xx', 0)} client 5xx / "
                       f"{kinds.get('transport', 0)} transport errors "
                       f"(steady state must be clean)")

    conv = d.get("breaker_convergence")
    if conv is not None:
        spread = conv.get("open_spread_s")
        bound = convergence_bound_s if convergence_bound_s is not None \
            else conv.get("probe_interval_s", 1.0)
        if spread is None:
            out.append("breaker never reported open on every router "
                       "(convergence unmeasured)")
        elif spread > bound:
            out.append(f"breaker open-state spread {spread:.2f}s "
                       f"across routers exceeds the {bound:g}s "
                       f"probe-interval bound")

    kill = d.get("router_kill")
    if kill is not None:
        if not kill.get("kill_fired"):
            out.append("the router kill never fired")
        if kill.get("errors_outside_blip_count"):
            out.append(f"{kill['errors_outside_blip_count']} client "
                       f"errors OUTSIDE the kill blip window (first: "
                       f"{kill['errors_outside_blip'][:3]}) — only the "
                       f"bounded in-flight blip may surface")
        if not kill.get("post_restart_ok"):
            out.append("zero successful requests after the killed "
                       "router returned")

    sat = d.get("saturation")
    if sat is not None:
        pre0 = (sat.get("presat") or {}).get("tier0") or {}
        sat0 = (sat.get("saturated") or {}).get("tier0") or {}
        sat2 = (sat.get("saturated") or {}).get("tier2") or {}
        if not pre0.get("goodput_qps"):
            out.append("tier0 pre-saturation goodput unmeasured")
        elif (sat0.get("goodput_qps") or 0.0) < \
                min_tier0_hold * pre0["goodput_qps"]:
            out.append(
                f"tier0 goodput fell to {sat0.get('goodput_qps')} qps "
                f"under saturation ({pre0['goodput_qps']} qps "
                f"pre-saturation; must hold >= {min_tier0_hold:.0%})")
        if (sat2.get("shed_fraction") or 0.0) < min_tier2_shed:
            out.append(
                f"tier2 shed only {sat2.get('shed_fraction'):.0%} "
                f"under saturation (< {min_tier2_shed:.0%}: the sweep "
                f"never actually saturated, or low-tier-first "
                f"shedding is not engaging)")
        for tier in ("tier0", "tier1", "tier2"):
            kinds = (sat.get("saturated") or {}).get(tier) or {}
            if kinds.get("http_5xx") or kinds.get("transport"):
                out.append(f"saturation phase {tier}: "
                           f"{kinds.get('http_5xx', 0)} 5xx / "
                           f"{kinds.get('transport', 0)} transport "
                           f"errors (saturation must shed, not error)")

    guard = d.get("overhead_guard")
    if guard is not None and max_overhead_ratio is not None:
        ratio, base = guard.get("overhead_ratio"), \
            guard.get("baseline_ratio")
        if guard["shared"]["errors"] or guard["baseline"]["errors"]:
            out.append("overhead guard A/B saw errors — the ratio is "
                       "suspect")
        elif ratio is None:
            out.append("overhead guard ratio unmeasured")
        elif ratio > max_overhead_ratio and \
                (base is None or ratio > base * 1.10) and \
                guard["shared"]["router_req_per_s"] < \
                0.9 * guard["baseline"]["router_req_per_s"]:
            # three escapes, any one passes: inside the band, within
            # 10% of the same-host baseline RATIO, or within 10% of
            # the baseline's router-side THROUGHPUT (the ratio's
            # denominator — the direct side — swings with host noise
            # the router never sees)
            out.append(
                f"shared-state overhead ratio {ratio:.2f}x exceeds "
                f"the {max_overhead_ratio:g}x band, the same-host "
                f"baseline {base:.2f}x + 10%, and router-side "
                f"throughput {guard['shared']['router_req_per_s']} "
                f"req/s is more than 10% under the baseline's "
                f"{guard['baseline']['router_req_per_s']}")
    return out
