"""Chaos mode: real router + N engines under scheduled engine churn.

The resilience layer's closed loop. The orchestrator launches the real
router in front of N engine processes (the zero-dependency fake by
default — chaos measures the *router's* failure handling, not model
compute), then drives a closed-loop chat storm while a churn task
kills engine processes with SIGKILL and restarts them on a schedule
(optionally also injecting backend-500 bursts through the fake
engine's ``/fault`` control endpoint).

Every client request is classified:

- ``ok``                    — HTTP 200, body/stream complete
- ``http_5xx``              — a 5xx reached the client. The router's
  pre-stream failover contract says this must be ZERO while at least
  one replica is healthy; the CLI exits 1 otherwise.
- ``truncated_streams``     — status 200 but the stream died before
  ``[DONE]``: the engine died mid-stream. Allowed (bytes cannot be
  replayed), counted, and reported.
- ``transport_errors``      — connect/read failure talking to the
  *router* itself; must also be zero (the router never restarts).
  One caveat: truncating a stream force-closes that client connection,
  so a pooled keep-alive connection can die under a later request's
  pen before any response byte exists. That is an HTTP/1.1 reuse
  race, retry-safe by construction — like every production OpenAI
  client, the storm retries such pre-response connection errors once
  on a fresh connection (counted as ``stale_conn_retries``).

The committed record (``CHAOS_*.json``, BENCH schema) carries
availability as the headline, latency percentiles under churn, the
kill/restart event log, and the router's own resilience counters
scraped from ``/metrics`` at the end.
"""

import asyncio
import json
import random
import re
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_cache_server,
                                                       launch_engine,
                                                       launch_router,
                                                       wait_cache_ready,
                                                       wait_healthy)
from production_stack_tpu.loadgen.report import percentile
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"

# router knobs for a chaos run: fail fast, fail over, re-probe quickly
ROUTER_CHAOS_ARGS = ["--request-timeout", "30",
                     "--breaker-threshold", "2",
                     "--breaker-cooldown", "2",
                     "--breaker-probe-interval", "0.5",
                     "--failover-attempts", "3"]


class _Counters:
    def __init__(self):
        self.launched = 0
        self.ok = 0
        self.http_5xx = 0
        self.http_4xx = 0
        self.truncated_streams = 0
        self.transport_errors = 0
        self.stale_conn_retries = 0
        self.latencies: List[float] = []
        self.ttfts: List[float] = []
        self.samples: List[str] = []
        # absolute monotonic stamps of every transport error: with
        # --router-kill the contract becomes "errors only inside the
        # kill blip windows", which needs to know WHEN each happened
        self.transport_error_times: List[float] = []

    def sample(self, text: str) -> None:
        if len(self.samples) < 8:
            self.samples.append(text[:160])


async def chaos_storm(url: str, model: str, *, users: int,
                      deadline: float, stream_fraction: float,
                      num_tokens: int, seed: int,
                      request_timeout_s: float = 30.0) -> _Counters:
    """Closed-loop storm with per-request outcome classification.
    Workers carry stable ``x-user-id`` headers so session routing has
    real sessions to keep sticky across the churn."""
    c = _Counters()
    timeout = aiohttp.ClientTimeout(total=request_timeout_s)

    async def one(session: aiohttp.ClientSession, user: str,
                  stream: bool) -> None:
        body = json.dumps({
            "model": model,
            "messages": [{"role": "user", "content": f"chaos {user}"}],
            "max_tokens": num_tokens, "stream": stream}).encode()
        c.launched += 1
        t0 = time.monotonic()
        response_started = False
        for attempt_no in (0, 1):
            try:
                async with session.post(
                        f"{url}{CHAT_PATH}", data=body,
                        headers={"Content-Type": "application/json",
                                 "x-user-id": user},
                        timeout=timeout) as resp:
                    response_started = True
                    if resp.status >= 500:
                        c.http_5xx += 1
                        c.sample(f"HTTP {resp.status}: "
                                 f"{(await resp.text())}")
                        return
                    if resp.status >= 400:
                        c.http_4xx += 1
                        c.sample(f"HTTP {resp.status}")
                        return
                    if stream:
                        first_at = None
                        done = False
                        try:
                            async for chunk in resp.content.iter_any():
                                if first_at is None:
                                    first_at = time.monotonic()
                                if b"[DONE]" in chunk:
                                    done = True
                        except (aiohttp.ClientError, ConnectionError,
                                asyncio.TimeoutError):
                            done = False
                        if not done:
                            # 200 + dead stream: engine died mid-relay
                            c.truncated_streams += 1
                            return
                        if first_at is not None:
                            c.ttfts.append(first_at - t0)
                    else:
                        await resp.read()
                    c.ok += 1
                    c.latencies.append(time.monotonic() - t0)
                    return
            except (aiohttp.ClientOSError,
                    aiohttp.ServerDisconnectedError) as e:
                if not response_started and attempt_no == 0:
                    # stale pooled keep-alive connection (the router
                    # force-closed it truncating an earlier stream):
                    # pre-response, so retry once on a fresh socket
                    c.stale_conn_retries += 1
                    continue
                c.transport_errors += 1
                c.transport_error_times.append(time.monotonic())
                c.sample(f"{type(e).__name__}: {e}")
                return
            except (aiohttp.ClientError, ConnectionError, OSError,
                    asyncio.TimeoutError) as e:
                c.transport_errors += 1
                c.transport_error_times.append(time.monotonic())
                c.sample(f"{type(e).__name__}: {e}")
                return

    async def worker(i: int) -> None:
        rng = random.Random(seed * 997 + i)
        user = f"chaos-user-{i}"
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as session:
            while time.monotonic() < deadline:
                stream = rng.random() < stream_fraction
                await one(session, user, stream)
                await asyncio.sleep(0.01)

    await asyncio.gather(*[worker(i) for i in range(users)])
    return c


async def _churn_loop(engines: List[Proc], *, engine_kind: str,
                      kill_interval_s: float, downtime_s: float,
                      deadline: float, log_dir: str, t0: float,
                      events: List[Dict],
                      platform: str = "cpu",
                      engine_extra_args: Optional[List[str]] = None
                      ) -> None:
    """Kill one engine (SIGKILL — no goodbye), wait ``downtime_s``,
    restart it on the same port, round-robin over the fleet."""
    i = 0
    while True:
        await asyncio.sleep(kill_interval_s)
        # leave room for the restart inside the measured window
        if time.monotonic() + downtime_s + 2.0 >= deadline:
            return
        victim_idx = i % len(engines)
        i += 1
        victim = engines[victim_idx]
        port = int(victim.url.rsplit(":", 1)[1])
        victim.popen.kill()
        victim.popen.wait()
        events.append({"t_s": round(time.monotonic() - t0, 2),
                       "event": "kill", "url": victim.url})
        logger.info("chaos: killed %s", victim.url)
        await asyncio.sleep(downtime_s)
        engines[victim_idx] = launch_engine(
            engine_kind, port, log_dir=log_dir, platform=platform,
            extra_args=engine_extra_args)
        events.append({"t_s": round(time.monotonic() - t0, 2),
                       "event": "restart", "url": victim.url})
        logger.info("chaos: restarted %s", victim.url)
        try:
            await wait_healthy(engines[victim_idx].url, 60.0)
        except TimeoutError:
            logger.warning("chaos: %s not healthy after restart",
                           engines[victim_idx].url)


async def _cache_churn_loop(holder: Dict[str, Proc], *,
                            kill_interval_s: float, downtime_s: float,
                            deadline: float, log_dir: str, t0: float,
                            events: List[Dict]) -> None:
    """SIGKILL/restart the shared TPKV cache server on a schedule — a
    replica mid-transfer must degrade to recompute (bounded remote
    timeouts + breaker in kvcache/store.RemoteStore), never surface a
    client-visible error."""
    while True:
        await asyncio.sleep(kill_interval_s)
        if time.monotonic() + downtime_s + 2.0 >= deadline:
            return
        victim = holder["proc"]
        port = int(victim.url.rsplit(":", 1)[1])
        victim.popen.kill()
        victim.popen.wait()
        events.append({"t_s": round(time.monotonic() - t0, 2),
                       "event": "cache_kill", "url": victim.url})
        logger.info("chaos: killed cache server %s", victim.url)
        await asyncio.sleep(downtime_s)
        holder["proc"] = launch_cache_server(port, log_dir=log_dir)
        events.append({"t_s": round(time.monotonic() - t0, 2),
                       "event": "cache_restart", "url": victim.url})
        logger.info("chaos: restarted cache server %s", victim.url)
        try:
            await wait_cache_ready(holder["proc"].url, 30.0)
        except TimeoutError:
            logger.warning("chaos: cache server %s not answering after "
                           "restart", holder["proc"].url)


async def _router_churn_loop(router_procs: List[Proc],
                             router_ports: List[int],
                             engine_urls: List[str], model: str, *,
                             routing: str, kill_interval_s: float,
                             downtime_s: float, deadline: float,
                             log_dir: str, t0: float,
                             events: List[Dict],
                             router_extra_args: Optional[List[str]],
                             engines: int) -> None:
    """SIGKILL/restart ROUTER replicas round-robin (mirroring the
    engine churn scheduler): sequential kill -> downtime -> restart ->
    wait-healthy, so at least one replica is always up and the L4
    splitter's connect-failover carries the traffic."""
    i = 0
    while True:
        await asyncio.sleep(kill_interval_s)
        if time.monotonic() + downtime_s + 5.0 >= deadline:
            return
        victim_idx = i % len(router_procs)
        i += 1
        victim = router_procs[victim_idx]
        victim.popen.kill()
        victim.popen.wait()
        events.append({"t_s": round(time.monotonic() - t0, 2),
                       "event": "router_kill", "url": victim.url})
        logger.info("chaos: killed router %s", victim.url)
        await asyncio.sleep(downtime_s)
        router_procs[victim_idx] = _launch_chaos_router(
            victim_idx, router_ports, engine_urls, model,
            routing=routing, log_dir=log_dir,
            router_extra_args=router_extra_args)
        try:
            await wait_healthy(router_procs[victim_idx].url, 30.0,
                               require_endpoints=engines)
            events.append({"t_s": round(time.monotonic() - t0, 2),
                           "event": "router_restart",
                           "url": router_procs[victim_idx].url})
        except TimeoutError:
            logger.warning("chaos: router %s not healthy after restart",
                           router_procs[victim_idx].url)


def _launch_chaos_router(idx: int, router_ports: List[int],
                         engine_urls: List[str], model: str, *,
                         routing: str, log_dir: str,
                         router_extra_args: Optional[List[str]]) -> Proc:
    port = router_ports[idx]
    peers = ",".join(f"http://127.0.0.1:{p}" for p in router_ports
                     if p != port)
    extra = ROUTER_CHAOS_ARGS + [
        "--router-id", f"chaos-router-{idx}",
        "--peer-routers", peers,
        "--peer-gossip-interval", "0.25",
    ] + (router_extra_args or [])
    return launch_router(engine_urls, model, port, routing=routing,
                         log_dir=log_dir, extra_args=extra)


async def _error_burst_loop(engine_urls: List[str], *,
                            interval_s: float, burst: int,
                            deadline: float, seed: int, t0: float,
                            events: List[Dict]) -> None:
    """Every ``interval_s``, tell one (fake) engine to 500 the next
    ``burst`` inference requests — exercises the backend-5xx failover
    path, not just dead sockets."""
    rng = random.Random(seed ^ 0xc4a05)
    async with aiohttp.ClientSession() as session:
        while time.monotonic() + 1.0 < deadline:
            await asyncio.sleep(interval_s)
            url = rng.choice(engine_urls)
            try:
                async with session.post(
                        f"{url}/fault",
                        json={"mode": "error", "count": burst},
                        timeout=aiohttp.ClientTimeout(total=2)) as r:
                    if r.status == 200:
                        events.append(
                            {"t_s": round(time.monotonic() - t0, 2),
                             "event": f"error_burst x{burst}",
                             "url": url})
            except (aiohttp.ClientError, ConnectionError, OSError,
                    asyncio.TimeoutError):
                pass    # victim currently dead; fine


async def _scrape_router_resilience(router_url: str) -> Dict[str, float]:
    """Pull the router's resilience counters off /metrics (totals only
    — per-endpoint label detail stays in the exposition)."""
    wanted = ("vllm:upstream_failures_total",
              "vllm:upstream_retries_total",
              "vllm:relayed_5xx_total",
              "vllm:breaker_opens_total",
              "vllm:healthy_pods_total")
    out: Dict[str, float] = {}
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                    f"{router_url}/metrics",
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                text = await r.text()
    except (aiohttp.ClientError, ConnectionError, OSError,
            asyncio.TimeoutError):
        return out
    for name in wanted:
        total = 0.0
        for m in re.finditer(
                rf"^{re.escape(name)}(?:{{[^}}]*}})?\s+([0-9.eE+-]+)",
                text, re.M):
            total += float(m.group(1))
        out[name] = total
    return out


async def run_chaos(*, engines: int = 3,
                    engine: str = "fake",
                    users: int = 16,
                    duration_s: float = 60.0,
                    kill_interval_s: float = 10.0,
                    downtime_s: float = 3.0,
                    error_burst_interval_s: Optional[float] = 7.0,
                    error_burst: int = 5,
                    stream_fraction: float = 0.3,
                    num_tokens: int = 16,
                    routing: str = "session",
                    seed: int = 0,
                    p99_bound_s: Optional[float] = None,
                    platform: str = "cpu",
                    log_dir: str = "loadgen-logs",
                    startup_timeout_s: float = 420.0,
                    router_extra_args: Optional[List[str]] = None,
                    cache_server_kill: bool = False,
                    cache_kill_interval_s: float = 7.0,
                    cache_downtime_s: float = 2.0,
                    prefill_ms_per_char: float = 0.2,
                    router_kill: bool = False,
                    router_replicas: int = 2,
                    router_kill_interval_s: float = 15.0,
                    router_downtime_s: float = 2.0,
                    router_blip_window_s: float = 4.0
                    ) -> Dict:
    """Launch router + N engines, storm the router while killing and
    restarting engines on a schedule; return the CHAOS record.

    ``cache_server_kill`` additionally launches a shared TPKV cache
    server wired into (fake) engines as their remote KV tier and
    SIGKILLs/restarts IT on its own schedule — the r11 extension: a
    dying cache server mid-transfer must cost TTFT (recompute), never a
    client-visible error.

    ``router_kill`` (the r16 extension) launches ``router_replicas``
    peered routers behind an in-process L4 splitter instead of one
    router, and SIGKILLs/restarts router replicas round-robin on their
    own schedule: client errors are then allowed ONLY inside each
    kill's ``router_blip_window_s`` (the dead replica's in-flight
    requests), never in steady state."""
    procs: List[Proc] = []
    engine_procs: List[Proc] = []
    router_procs: List[Proc] = []
    events: List[Dict] = []
    engine_extra_args: Optional[List[str]] = None
    cache_holder: Dict[str, Proc] = {}
    splitter = None
    try:
        if cache_server_kill:
            if engine != "fake":
                raise ValueError("cache_server_kill currently drives "
                                 "the fake-engine KV simulation")
            cache = launch_cache_server(free_port(), log_dir=log_dir)
            procs.append(cache)
            cache_holder["proc"] = cache
            await wait_cache_ready(cache.url)
            engine_extra_args = [
                "--kv-remote-url", cache.url,
                "--prefill-ms-per-char", str(prefill_ms_per_char)]
        for _ in range(engines):
            engine_procs.append(launch_engine(
                engine, free_port(), log_dir=log_dir, platform=platform,
                extra_args=engine_extra_args))
        procs.extend(engine_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in engine_procs])
        model = "fake-model" if engine == "fake" else engine
        if router_kill:
            from production_stack_tpu.loadgen.multirouter import (
                L4Splitter)
            router_ports = [free_port() for _ in range(router_replicas)]
            for idx in range(router_replicas):
                router_procs.append(_launch_chaos_router(
                    idx, router_ports, [e.url for e in engine_procs],
                    model, routing=routing, log_dir=log_dir,
                    router_extra_args=router_extra_args))
            procs.extend(router_procs)
            await asyncio.gather(*[
                wait_healthy(r.url, 60.0, require_endpoints=engines)
                for r in router_procs])
            splitter = L4Splitter([("127.0.0.1", p)
                                   for p in router_ports])
            await splitter.start()
            storm_url = splitter.url
            scrape_url = router_procs[0].url
        else:
            router = launch_router(
                [e.url for e in engine_procs], model, free_port(),
                routing=routing, log_dir=log_dir,
                extra_args=ROUTER_CHAOS_ARGS + (router_extra_args or []))
            procs.append(router)
            await wait_healthy(router.url, 60.0,
                               require_endpoints=engines)
            storm_url = router.url
            scrape_url = router.url

        logger.info("chaos: %d users vs router + %d %s engines for "
                    "%.0fs (kill every %.0fs, %.0fs downtime)",
                    users, engines, engine, duration_s,
                    kill_interval_s, downtime_s)
        t0 = time.monotonic()
        deadline = t0 + duration_s
        tasks = [asyncio.create_task(_churn_loop(
            engine_procs, engine_kind=engine,
            kill_interval_s=kill_interval_s, downtime_s=downtime_s,
            deadline=deadline, log_dir=log_dir, t0=t0, events=events,
            platform=platform, engine_extra_args=engine_extra_args))]
        if cache_server_kill:
            tasks.append(asyncio.create_task(_cache_churn_loop(
                cache_holder, kill_interval_s=cache_kill_interval_s,
                downtime_s=cache_downtime_s, deadline=deadline,
                log_dir=log_dir, t0=t0, events=events)))
        if engine == "fake" and error_burst_interval_s:
            tasks.append(asyncio.create_task(_error_burst_loop(
                [e.url for e in engine_procs],
                interval_s=error_burst_interval_s, burst=error_burst,
                deadline=deadline, seed=seed, t0=t0, events=events)))
        if router_kill:
            tasks.append(asyncio.create_task(_router_churn_loop(
                router_procs, router_ports,
                [e.url for e in engine_procs], model, routing=routing,
                kill_interval_s=router_kill_interval_s,
                downtime_s=router_downtime_s, deadline=deadline,
                log_dir=log_dir, t0=t0, events=events,
                router_extra_args=router_extra_args, engines=engines)))
        try:
            c = await chaos_storm(storm_url, model, users=users,
                                  deadline=deadline,
                                  stream_fraction=stream_fraction,
                                  num_tokens=num_tokens, seed=seed)
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        elapsed = time.monotonic() - t0
        router_counters = await _scrape_router_resilience(scrape_url)
        engine_kv = None
        if cache_server_kill:
            from production_stack_tpu.loadgen.kvshare import _scrape_kv
            engine_kv = await _scrape_kv([e.url for e in engine_procs])
    finally:
        # the churn loops swap engine/cache/router Procs in place; stop
        # the CURRENT processes plus anything from the launch-time
        # snapshot (already-dead originals — _stop skips exited pids)
        if splitter is not None:
            await splitter.close()
        current = list(engine_procs) + list(router_procs)
        if cache_holder.get("proc") is not None:
            current.append(cache_holder["proc"])
        current.extend(p for p in procs if p not in current)
        _stop(current)

    kills = len([e for e in events if e["event"] == "kill"])
    restarts = len([e for e in events if e["event"] == "restart"])
    cache_kills = len([e for e in events if e["event"] == "cache_kill"])
    router_kills = len([e for e in events
                        if e["event"] == "router_kill"])
    # classify each transport error against the router-kill blip
    # windows (kill .. restart-healthy + blip slack)
    transport_rel = sorted(round(ts - t0, 2)
                           for ts in c.transport_error_times)
    errors_outside_blip = []
    if router_kill:
        windows = []
        for e in events:
            if e["event"] == "router_kill":
                # the kill stamp lands after popen.wait(); connections
                # reset the instant the signal delivers, so each
                # window opens 0.5s early
                windows.append([e["t_s"] - 0.5,
                                e["t_s"] + router_downtime_s
                                + router_blip_window_s])
        for rel in transport_rel:
            if not any(lo <= rel <= hi for lo, hi in windows):
                errors_outside_blip.append(rel)
    done = c.ok + c.http_5xx + c.http_4xx + c.truncated_streams + \
        c.transport_errors
    availability = 100.0 * c.ok / done if done else 0.0

    def pcts(vals: List[float]) -> Dict:
        return {"p50": round(percentile(vals, 50) * 1e3, 1),
                "p90": round(percentile(vals, 90) * 1e3, 1),
                "p99": round(percentile(vals, 99) * 1e3, 1)}

    return {
        "metric": "client-visible availability under engine churn "
                  "(router pre-stream failover; fake engines killed/"
                  "restarted on schedule)",
        "value": round(availability, 3),
        "unit": "%",
        "platform": platform,
        "detail": {
            "engine": engine, "engines": engines, "users": users,
            "routing": routing,
            "duration_s": round(elapsed, 1),
            "kill_interval_s": kill_interval_s,
            "downtime_s": downtime_s,
            "error_burst_interval_s": error_burst_interval_s
            if engine == "fake" else None,
            "kills": kills, "restarts": restarts,
            "cache_server_kill": cache_server_kill,
            "cache_kills": cache_kills,
            "router_kill": router_kill,
            "router_replicas": router_replicas if router_kill else 1,
            "router_kills": router_kills,
            "router_blip_window_s": router_blip_window_s
            if router_kill else None,
            "transport_error_times_s": transport_rel,
            "errors_outside_blip": errors_outside_blip
            if router_kill else None,
            "splitter_connect_failovers": splitter.connect_failovers
            if splitter is not None else None,
            "engine_kv": engine_kv,
            "requests": {
                "launched": c.launched, "ok": c.ok,
                "http_5xx": c.http_5xx, "http_4xx": c.http_4xx,
                "truncated_streams": c.truncated_streams,
                "transport_errors": c.transport_errors,
                "stale_conn_retries": c.stale_conn_retries,
            },
            "availability_pct": round(availability, 3),
            "req_per_s": round(c.ok / max(elapsed, 1e-9), 1),
            "latency_ms": pcts(c.latencies),
            "ttft_ms": pcts(c.ttfts) if c.ttfts else None,
            "p99_bound_s": p99_bound_s,
            "router_resilience_counters": router_counters,
            "error_samples": c.samples,
            "events": events,
        },
    }


def chaos_violations(record: Dict) -> List[str]:
    """The chaos run's pass/fail contract (CLI exits 1 on any)."""
    d = record["detail"]
    r = d["requests"]
    out = []
    if r["http_5xx"]:
        out.append(f"{r['http_5xx']} client-visible 5xx (pre-stream "
                   f"failures must fail over, not surface)")
    if d.get("router_kill"):
        # router replicas DO die on schedule here: each kill may cost
        # its in-flight blip (counted), but nothing outside a window
        outside = d.get("errors_outside_blip") or []
        if outside:
            out.append(f"{len(outside)} transport errors OUTSIDE the "
                       f"router-kill blip windows (at {outside[:5]}s) "
                       f"— only the dead replica's in-flight requests "
                       f"may surface")
        if not d.get("router_kills"):
            out.append("router churn never killed a router (window "
                       "too short for router_kill_interval?)")
    elif r["transport_errors"]:
        out.append(f"{r['transport_errors']} transport errors talking "
                   f"to the router (the router must not die)")
    if r["ok"] == 0:
        out.append("zero successful requests")
    if not d["kills"]:
        out.append("churn never killed an engine (window too short "
                   "for kill_interval?)")
    if d.get("cache_server_kill") and not d.get("cache_kills"):
        out.append("cache churn never killed the cache server (window "
                   "too short for cache_kill_interval?)")
    bound = d.get("p99_bound_s")
    if bound and d["latency_ms"]["p99"] > bound * 1e3:
        out.append(f"p99 {d['latency_ms']['p99']:.0f}ms exceeds the "
                   f"{bound:g}s bound under churn")
    return out
