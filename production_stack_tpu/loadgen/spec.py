"""Workload specification: what traffic to generate, how it arrives.

One ``WorkloadSpec`` fully determines a run given a seed: the traffic
mix (chat / guided / shaped / embeddings / LoRA), the session shape
(ShareGPT-style turn-length distributions), and the arrival process
(closed-loop user population or open-loop Poisson QPS ramp). Specs
round-trip through JSON so a BASELINE claim can pin the exact workload
next to the number it produced.
"""

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# request kinds the planner can emit; weights live in TrafficMix
KINDS = ("chat", "guided", "shaped", "embeddings", "lora")


@dataclass
class TrafficMix:
    """Relative weights per request kind (normalized at planning time).

    ``lora`` requires ``WorkloadSpec.lora_model`` (the adapter's served
    model id); a nonzero lora weight with no adapter configured is a
    spec error caught in validate().
    """
    chat: float = 1.0
    guided: float = 0.0
    shaped: float = 0.0
    embeddings: float = 0.0
    lora: float = 0.0

    def weights(self) -> List[Tuple[str, float]]:
        total = sum(getattr(self, k) for k in KINDS)
        if total <= 0:
            raise ValueError("traffic mix has no positive weight")
        return [(k, getattr(self, k) / total) for k in KINDS
                if getattr(self, k) > 0]


@dataclass
class SessionSpec:
    """Multi-round chat session shape.

    Turn lengths follow a lognormal (the shape of ShareGPT human-turn
    lengths: many short questions, a long tail), parameterized by the
    target mean so specs stay readable; sigma is the log-space spread.
    """
    rounds_min: int = 2
    rounds_max: int = 8
    system_prompt_tokens: int = 200   # shared prefix (KV-reuse stressor)
    question_tokens_mean: float = 48.0
    question_tokens_sigma: float = 0.6
    question_tokens_max: int = 512
    answer_tokens_mean: float = 96.0
    answer_tokens_sigma: float = 0.4
    answer_tokens_max: int = 256


@dataclass
class ArrivalSpec:
    """How requests hit the server.

    closed — ``users`` concurrent sessions, each issuing its next turn
    when the previous answer lands (plus ``think_time_s``): concurrency
    is the controlled variable, throughput the measurement.

    open — requests launch at Poisson arrival times regardless of
    completions (the serving-benchmark arrival model LMCache and the
    KV-offload study both stress): QPS is the controlled variable,
    latency under load the measurement. The ramp walks qps_start →
    qps_end by qps_step, ``stage_duration_s`` per stage (the reference
    run.sh sweeps 0.1 → 4.1 the same way).
    """
    mode: str = "closed"              # "closed" | "open"
    users: int = 8
    think_time_s: float = 0.0
    qps_start: float = 0.1
    qps_end: float = 4.1
    qps_step: float = 1.0
    stage_duration_s: float = 30.0
    # every stage's qps multiplied by this AFTER the ramp is built —
    # the distributed coordinator hands worker i the shared ramp with
    # qps_scale = 1/N (N Poisson streams at rate/N superpose to the
    # target rate), without perturbing how many stages the ramp has
    qps_scale: float = 1.0

    def stages(self) -> List[Tuple[float, float]]:
        """Open-loop (qps, duration_s) stages."""
        if self.qps_scale <= 0:
            raise ValueError(f"qps_scale {self.qps_scale} must be "
                             f"positive")
        if self.qps_step <= 0:
            # a non-advancing step would loop this builder forever;
            # constant-rate (start == end) is the one sensible reading
            if self.qps_start == self.qps_end:
                return [(round(self.qps_start * self.qps_scale, 6),
                         self.stage_duration_s)]
            raise ValueError(
                f"qps_step {self.qps_step} must be positive to ramp "
                f"{self.qps_start} -> {self.qps_end}")
        out: List[Tuple[float, float]] = []
        q = self.qps_start
        # tolerance so 0.1 + 4 * 1.0 == 4.1 lands despite float drift
        while q <= self.qps_end + 1e-9:
            out.append((round(q * self.qps_scale, 6),
                        self.stage_duration_s))
            q += self.qps_step
        if not out:
            raise ValueError("open-loop ramp has no stages")
        return out


@dataclass
class WorkloadSpec:
    name: str = "chat"
    model: str = "debug-tiny"
    seed: int = 0
    mix: TrafficMix = field(default_factory=TrafficMix)
    session: SessionSpec = field(default_factory=SessionSpec)
    arrival: ArrivalSpec = field(default_factory=ArrivalSpec)
    duration_s: Optional[float] = None   # wall bound; None = finite run
    max_sessions: Optional[int] = None   # finite closed-loop run length
    request_timeout_s: float = 600.0
    lora_model: Optional[str] = None     # served adapter id for kind=lora
    guided_choices: Tuple[str, ...] = ("yes", "no", "maybe")

    def validate(self) -> "WorkloadSpec":
        if self.arrival.mode not in ("closed", "open"):
            raise ValueError(f"arrival.mode {self.arrival.mode!r} must be "
                             f"'closed' or 'open'")
        if self.mix.lora > 0 and not self.lora_model:
            raise ValueError("mix.lora > 0 requires lora_model (the "
                             "adapter's served model id)")
        if self.session.rounds_min < 1 or \
                self.session.rounds_max < self.session.rounds_min:
            raise ValueError("rounds_min/rounds_max malformed")
        self.mix.weights()               # raises on all-zero mix
        if self.arrival.mode == "open":
            self.arrival.stages()        # raises on a malformed ramp
        return self

    # ---------------------------------------------------- JSON round-trip

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_dict(cls, d: Dict) -> "WorkloadSpec":
        d = dict(d)
        if "mix" in d:
            d["mix"] = TrafficMix(**d["mix"])
        if "session" in d:
            d["session"] = SessionSpec(**d["session"])
        if "arrival" in d:
            d["arrival"] = ArrivalSpec(**d["arrival"])
        if "guided_choices" in d:
            d["guided_choices"] = tuple(d["guided_choices"])
        return cls(**d).validate()

    @classmethod
    def from_json(cls, text: str) -> "WorkloadSpec":
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_file(cls, path: str) -> "WorkloadSpec":
        with open(path) as f:
            return cls.from_json(f.read())


def preset(name: str) -> WorkloadSpec:
    """Named workloads the CLI and docs refer to by name."""
    if name == "chat":
        return WorkloadSpec(name="chat").validate()
    if name == "mixed":
        # the soak workload: mostly chat, with guided decoding, shaped
        # sampling, and embeddings exercising the non-default
        # executables. Sized to fit the CPU debug-tiny stack the
        # committed soak runs against (its character-level tokenizer
        # expands a filler word to ~8 model tokens, and the orchestrator
        # launches engines at max-model-len 1024): round-3 prompts stay
        # near ~800 model tokens.
        return WorkloadSpec(
            name="mixed",
            mix=TrafficMix(chat=0.6, guided=0.15, shaped=0.15,
                           embeddings=0.10),
            session=SessionSpec(rounds_min=1, rounds_max=3,
                                system_prompt_tokens=32,
                                question_tokens_mean=16.0,
                                question_tokens_sigma=0.5,
                                question_tokens_max=48,
                                answer_tokens_mean=48.0,
                                answer_tokens_sigma=0.4,
                                answer_tokens_max=64),
        ).validate()
    if name == "scaleout":
        # the replica-curve workload: pure multi-round chat, sized so
        # session histories fit the engines run_scaleout launches
        # itself (same ~8-tokens-per-word arithmetic as "mixed") —
        # a 400 "prompt exceeds max_model_len" storm would measure
        # nothing but the error path
        return WorkloadSpec(
            name="scaleout",
            session=SessionSpec(rounds_min=1, rounds_max=3,
                                system_prompt_tokens=16,
                                question_tokens_mean=12.0,
                                question_tokens_sigma=0.4,
                                question_tokens_max=24,
                                answer_tokens_mean=32.0,
                                answer_tokens_sigma=0.3,
                                answer_tokens_max=48),
        ).validate()
    if name == "ref-ramp":
        # the reference run.sh shape: open-loop Poisson sweep 0.1 -> 4.1
        return WorkloadSpec(
            name="ref-ramp",
            arrival=ArrivalSpec(mode="open", qps_start=0.1, qps_end=4.1,
                                qps_step=1.0, stage_duration_s=30.0),
        ).validate()
    raise ValueError(f"unknown workload preset {name!r} "
                     f"(known: chat, mixed, scaleout, ref-ramp)")
