"""Router-overhead A/B: the same request storm, direct vs via router.

The round-5 prose number (~770 req/s through one router process vs
~3,900 req/s hitting the same fake engine directly — BASELINE.md
"Router data-plane measurement") was produced by an ad-hoc `/tmp`
script that never landed in the repo. This module is the committed,
reproducible form: it launches ONE engine (the zero-think fake by
default, or a real one) plus the real router in front of it, then
drives the identical closed-loop storm at both URLs and reports both
sides plus the overhead ratio in one BENCH-schema record.

Deliberately minimal client: a fixed pre-encoded body, N workers, one
shared session — per-request Python work on the *measuring* side is a
few dict writes, so the number characterizes the router, not the
harness. (The full loadgen workload machinery would tax both sides
equally but caps the ceiling well below the fake engine's.)
"""

import asyncio
import itertools
import json
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (_stop, free_port,
                                                       launch_engine,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.loadgen.report import percentile
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"


def overhead_payload(model: str, num_tokens: int = 8,
                     stream: bool = False) -> bytes:
    """The fixed request body both sides receive, encoded once."""
    return json.dumps({
        "model": model,
        "messages": [{"role": "user", "content": "ping"}],
        "max_tokens": num_tokens,
        "stream": stream,
    }).encode()


def unique_payload_factory(model: str, num_tokens: int = 8,
                           stream: bool = False,
                           prompt_chars: int = 768):
    """Per-request UNIQUE long prompts — the cold-prefix worst case for
    cache-aware routing (every request hashes `prompt_chars` of text,
    walks the prefix ring, misses, and falls back to hash affinity).
    The r11 no-regression guard drives this against --routing prefix
    and asserts the r7 overhead band still holds."""
    counter = itertools.count()
    filler = "pad " * (prompt_chars // 4 + 1)

    def make() -> bytes:
        i = next(counter)
        return json.dumps({
            "model": model,
            "messages": [{"role": "user",
                          "content": f"cold-{i:08d} {filler}"
                                     [:prompt_chars]}],
            "max_tokens": num_tokens,
            "stream": stream,
        }).encode()
    return make


async def measure_side(url: str, payload: bytes, *,
                       users: int = 64,
                       duration_s: float = 15.0,
                       stream: bool = False,
                       warmup_requests: int = 32,
                       api_key: Optional[str] = None,
                       extra_headers: Optional[Dict] = None) -> Dict:
    """Closed-loop storm at one URL: ``users`` workers re-posting
    ``payload`` back to back for ``duration_s``. ``payload`` may be a
    zero-arg callable producing per-request bodies (cold-prefix mode).
    Returns the side's summary (req/s + latency/TTFT percentiles)."""
    headers = {"Content-Type": "application/json", **(extra_headers or {})}
    if api_key:
        headers["Authorization"] = f"Bearer {api_key}"
    target = f"{url}{CHAT_PATH}"
    make_payload = payload if callable(payload) else (lambda: payload)
    latencies: List[float] = []
    ttfts: List[float] = []
    errors: List[str] = []
    timeout = aiohttp.ClientTimeout(total=30)

    async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0)) as session:

        async def one_request(record: bool) -> None:
            t0 = time.monotonic()
            try:
                async with session.post(target, data=make_payload(),
                                        headers=headers,
                                        timeout=timeout) as resp:
                    if resp.status != 200:
                        if record and len(errors) < 5:
                            errors.append(f"HTTP {resp.status}")
                        raise _RequestFailed()
                    if stream:
                        first_at = None
                        async for _chunk in resp.content.iter_any():
                            if first_at is None:
                                first_at = time.monotonic()
                        if record and first_at is not None:
                            ttfts.append(first_at - t0)
                    else:
                        await resp.read()
            except _RequestFailed:
                raise
            except (aiohttp.ClientError, ConnectionError, OSError,
                    asyncio.TimeoutError) as e:
                if record and len(errors) < 5:
                    errors.append(f"{type(e).__name__}: {e}")
                raise _RequestFailed()
            if record:
                latencies.append(time.monotonic() - t0)

        # warmup: absorb connection setup / first-request compiles
        warm_failures = 0
        for i in range(warmup_requests):
            try:
                await one_request(record=False)
            except _RequestFailed:
                warm_failures += 1
        if warm_failures:
            logger.warning("%d/%d warmup requests to %s failed",
                           warm_failures, warmup_requests, url)

        error_count = 0
        deadline = time.monotonic() + duration_s

        async def worker() -> None:
            nonlocal error_count
            while time.monotonic() < deadline:
                try:
                    await one_request(record=True)
                except _RequestFailed:
                    error_count += 1
                    await asyncio.sleep(0.05)   # don't spin an error storm

        started = time.monotonic()
        await asyncio.gather(*[worker() for _ in range(users)])
        elapsed = time.monotonic() - started

    def pcts(values: List[float]) -> Dict:
        return {"p50": round(percentile(values, 50) * 1e3, 3),
                "p90": round(percentile(values, 90) * 1e3, 3),
                "p99": round(percentile(values, 99) * 1e3, 3)}

    return {
        "url": url,
        "finished": len(latencies),
        "errors": error_count,
        "error_samples": errors,
        "duration_s": round(elapsed, 3),
        "req_per_s": round(len(latencies) / max(elapsed, 1e-9), 1),
        "latency_ms": pcts(latencies),
        "ttft_ms": pcts(ttfts) if stream else None,
    }


class _RequestFailed(Exception):
    """Internal: one request failed (already sampled)."""


async def run_overhead(*, engine: str = "fake",
                       users: int = 64,
                       duration_s: float = 15.0,
                       num_tokens: int = 8,
                       stream: bool = False,
                       routing: str = "roundrobin",
                       platform: str = "cpu",
                       log_dir: str = "loadgen-logs",
                       startup_timeout_s: float = 420.0,
                       snapshot_ttl: Optional[float] = None,
                       warmup_requests: int = 32,
                       unique_prompts: bool = False,
                       prompt_chars: int = 768,
                       router_extra_args: Optional[List[str]] = None,
                       companion=None) -> Dict:
    """Launch engine + router, measure both sides, return the A/B
    record (BENCH schema; headline value = router-side req/s).

    ``companion`` (optional) is a callable ``(engine_url, router_url)
    -> async context manager`` entered after the stack is healthy and
    exited after both sides are measured — the hook the obsplane
    overhead guard uses to keep a fleet scraper attached to the
    serving path for the WHOLE measured window."""
    procs = []
    companion_cm = None
    try:
        # zero-think fake: argparse takes the LAST occurrence, so these
        # override launch_engine's paced defaults
        fake_args = ["--tokens-per-s", "0",
                     "--num-tokens", str(num_tokens)] \
            if engine == "fake" else None
        eng = launch_engine(engine, free_port(), log_dir=log_dir,
                            platform=platform, extra_args=fake_args)
        procs.append(eng)
        await wait_healthy(eng.url, startup_timeout_s)
        model = "fake-model" if engine == "fake" else engine
        router = launch_router([eng.url], model, free_port(),
                               routing=routing, log_dir=log_dir,
                               snapshot_ttl=snapshot_ttl,
                               extra_args=router_extra_args)
        procs.append(router)
        await wait_healthy(router.url, 60.0, require_endpoints=1)
        if companion is not None:
            companion_cm = companion(eng.url, router.url)
            await companion_cm.__aenter__()

        if unique_prompts:
            payload = unique_payload_factory(model, num_tokens=num_tokens,
                                             stream=stream,
                                             prompt_chars=prompt_chars)
        else:
            payload = overhead_payload(model, num_tokens=num_tokens,
                                       stream=stream)
        # secured deployments (ENGINE_API_KEY exported): the direct
        # side hits the engine without the router's Bearer injection,
        # so carry the engine key on both sides (the router passes a
        # client Authorization through untouched)
        from production_stack_tpu.router.service_discovery import (
            engine_auth_headers)
        auth = engine_auth_headers()
        logger.info("overhead A/B: %d users, %.0fs per side, "
                    "%d-token %s responses, engine=%s",
                    users, duration_s, num_tokens,
                    "streaming" if stream else "non-streaming", engine)
        direct = await measure_side(eng.url, payload, users=users,
                                    duration_s=duration_s, stream=stream,
                                    warmup_requests=warmup_requests,
                                    extra_headers=auth)
        logger.info("direct:  %.1f req/s (%d finished, %d errors)",
                    direct["req_per_s"], direct["finished"],
                    direct["errors"])
        via = await measure_side(router.url, payload, users=users,
                                 duration_s=duration_s, stream=stream,
                                 warmup_requests=warmup_requests,
                                 extra_headers=auth)
        logger.info("router:  %.1f req/s (%d finished, %d errors)",
                    via["req_per_s"], via["finished"], via["errors"])
    finally:
        if companion_cm is not None:
            await companion_cm.__aexit__(None, None, None)
        _stop(procs)

    ratio = (direct["req_per_s"] / via["req_per_s"]
             if via["req_per_s"] > 0 else None)
    added_p50 = round(via["latency_ms"]["p50"] -
                      direct["latency_ms"]["p50"], 3)
    return {
        "metric": "router data-plane overhead A/B "
                  "(req/s via router vs direct to the same engine)",
        "value": via["req_per_s"],
        "unit": "req/s",
        "platform": platform,
        "detail": {
            "engine": engine,
            "users": users,
            "duration_s": duration_s,
            "num_tokens": num_tokens,
            "stream": stream,
            "routing": routing,
            "unique_prompts": unique_prompts,
            "direct": direct,
            "router": via,
            "overhead_ratio": round(ratio, 3) if ratio else None,
            "added_latency_p50_ms": added_p50,
        },
    }
