"""Deterministic session/turn planning and payload construction.

``plan_sessions(spec, n)`` is a pure function of (spec, n): the same
spec and seed always produce byte-identical plans — a soak or scale-out
run is reproducible evidence, and N=1 vs N=2 replicas face the *same*
traffic. Randomness comes only from ``random.Random(spec.seed)``.

Payloads speak the stack's public OpenAI surface: /v1/chat/completions
(chat / guided / shaped / lora kinds) and /v1/embeddings.
"""

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from production_stack_tpu.loadgen.spec import WorkloadSpec

# deterministic filler vocabulary: cycled by token index, so a payload
# is a function of its length alone (and compresses poorly enough to be
# honest on the wire)
_WORDS = ("alpha", "bravo", "charlie", "delta", "echo", "foxtrot",
          "golf", "hotel", "india", "juliet", "kilo", "lima", "mike",
          "november", "oscar", "papa", "quebec", "romeo", "sierra",
          "tango", "uniform", "victor", "whiskey", "xray", "yankee",
          "zulu")


def filler(n_tokens: int, salt: int = 0) -> str:
    """~n whitespace tokens of deterministic text; ``salt`` rotates the
    word cycle so distinct sessions don't share a prefix by accident."""
    return " ".join(_WORDS[(salt + i) % len(_WORDS)]
                    for i in range(max(n_tokens, 1)))


def _sample_len(rng: random.Random, mean: float, sigma: float,
                cap: int) -> int:
    """Lognormal with the given arithmetic mean (mu backed out of the
    lognormal mean identity), clamped to [1, cap]."""
    import math
    mu = math.log(max(mean, 1.0)) - sigma * sigma / 2.0
    return max(1, min(cap, int(round(rng.lognormvariate(mu, sigma)))))


@dataclass
class TurnPlan:
    kind: str                     # chat | guided | shaped | embeddings | lora
    question_tokens: int
    answer_tokens: int


@dataclass
class SessionPlan:
    session_id: int
    user_id: str                  # x-user-id header (session routing key)
    kind: str
    turns: List[TurnPlan]


def plan_sessions(spec: WorkloadSpec, count: int,
                  first_id: int = 0) -> List[SessionPlan]:
    """The first ``count`` sessions of the spec's infinite schedule,
    starting at session ``first_id`` (planning is resumable: sessions
    [0, k) then [k, n) equals sessions [0, n))."""
    out: List[SessionPlan] = []
    weights = spec.mix.weights()
    kinds = [k for k, _ in weights]
    probs = [w for _, w in weights]
    s = spec.session
    for sid in range(first_id, first_id + count):
        # one RNG per session, keyed by (seed, sid): session sid's plan
        # is independent of how many sessions were planned before it
        rng = random.Random((spec.seed << 20) ^ sid)
        kind = rng.choices(kinds, probs)[0]
        rounds = 1 if kind == "embeddings" else \
            rng.randint(s.rounds_min, s.rounds_max)
        turns = [TurnPlan(
            kind=kind,
            question_tokens=_sample_len(rng, s.question_tokens_mean,
                                        s.question_tokens_sigma,
                                        s.question_tokens_max),
            answer_tokens=_sample_len(rng, s.answer_tokens_mean,
                                      s.answer_tokens_sigma,
                                      s.answer_tokens_max),
        ) for _ in range(rounds)]
        out.append(SessionPlan(session_id=sid, user_id=f"lg-user-{sid}",
                               kind=kind, turns=turns))
    return out


@dataclass
class RequestPlan:
    """One wire-ready request: everything the client needs to fire it."""
    path: str                     # /v1/chat/completions | /v1/embeddings
    body: Dict
    headers: Dict[str, str]
    stream: bool
    kind: str
    session_id: int
    turn_index: int
    max_tokens: int


class SessionState:
    """Plays a SessionPlan turn by turn, accumulating chat history (the
    KV-reuse stressor: every round re-sends the grown prefix)."""

    def __init__(self, plan: SessionPlan, spec: WorkloadSpec):
        self.plan = plan
        self.spec = spec
        self.turn_index = 0
        self.messages: List[Dict] = []

    @property
    def done(self) -> bool:
        return self.turn_index >= len(self.plan.turns)

    def next_request(self) -> RequestPlan:
        assert not self.done
        turn = self.plan.turns[self.turn_index]
        spec = self.spec
        headers = {"x-user-id": self.plan.user_id}
        if turn.kind == "embeddings":
            body = {"model": spec.model,
                    "input": filler(turn.question_tokens,
                                    salt=self.plan.session_id)}
            req = RequestPlan(path="/v1/embeddings", body=body,
                              headers=headers, stream=False,
                              kind=turn.kind,
                              session_id=self.plan.session_id,
                              turn_index=self.turn_index, max_tokens=0)
            self.turn_index += 1
            return req
        if not self.messages:
            self.messages.append({
                "role": "system",
                "content": "Shared context: " + filler(
                    spec.session.system_prompt_tokens,
                    salt=self.plan.session_id)})
        question = (f"Question {self.turn_index + 1}: " +
                    filler(turn.question_tokens,
                           salt=self.plan.session_id + self.turn_index))
        self.messages.append({"role": "user", "content": question})
        body: Dict = {
            "model": spec.lora_model if turn.kind == "lora" else spec.model,
            "messages": list(self.messages),
            "max_tokens": turn.answer_tokens,
            "stream": True,
            "stream_options": {"include_usage": True},
            "temperature": 0.0,
        }
        if turn.kind == "guided":
            body["guided_choice"] = list(spec.guided_choices)
            # a guided answer is one choice, not a story
            body["max_tokens"] = max(
                8, max(len(c.split()) for c in spec.guided_choices) + 2)
        elif turn.kind == "shaped":
            body.update(temperature=0.7, presence_penalty=0.5,
                        frequency_penalty=0.2)
        req = RequestPlan(path="/v1/chat/completions", body=body,
                          headers=headers, stream=True, kind=turn.kind,
                          session_id=self.plan.session_id,
                          turn_index=self.turn_index,
                          max_tokens=body["max_tokens"])
        self.turn_index += 1
        return req

    def record_answer(self, text: str) -> None:
        """Feed the assistant turn back into the history (multi-round)."""
        if self.plan.kind != "embeddings":
            self.messages.append({"role": "assistant",
                                  "content": text or "(no answer)"})


def replay_request_plan(*, session_id: int, turn_index: int, kind: str,
                        model: str, question_tokens: int,
                        answer_tokens: int,
                        system_prompt_tokens: int = 0,
                        prior_turns: Optional[List[Dict]] = None,
                        tenant: Optional[str] = None,
                        stream: bool = True) -> RequestPlan:
    """A wire-ready RequestPlan reconstructed from a trace line.

    Replay rebuilds the conversation history DETERMINISTICALLY from the
    recorded shape — prior questions are the same ``filler`` text the
    original planner produced for (session, turn), prior answers are
    filler of the recorded answer length — so the prompt grows exactly
    like the original session's did (same prefix-reuse pressure, same
    session-affinity key) without needing the original responses.
    ``prior_turns`` is the trace's earlier lines for this session, each
    ``{"question_tokens": int, "answer_tokens": int}``.
    """
    headers = {"x-user-id": f"lg-user-{session_id}"}
    if tenant:
        headers["x-tenant-id"] = tenant
    if kind == "embeddings":
        return RequestPlan(
            path="/v1/embeddings",
            body={"model": model,
                  "input": filler(question_tokens, salt=session_id)},
            headers=headers, stream=False, kind=kind,
            session_id=session_id, turn_index=turn_index, max_tokens=0)
    messages: List[Dict] = []
    if system_prompt_tokens > 0:
        messages.append({"role": "system",
                         "content": "Shared context: "
                         + filler(system_prompt_tokens, salt=session_id)})
    for j, t in enumerate(prior_turns or []):
        messages.append({
            "role": "user",
            "content": f"Question {j + 1}: "
            + filler(int(t["question_tokens"]), salt=session_id + j)})
        messages.append({
            "role": "assistant",
            "content": filler(int(t["answer_tokens"]),
                              salt=session_id + j + 13)})
    messages.append({
        "role": "user",
        "content": f"Question {turn_index + 1}: "
        + filler(question_tokens, salt=session_id + turn_index)})
    body: Dict = {
        "model": model,
        "messages": messages,
        "max_tokens": max(1, answer_tokens),
        "stream": bool(stream),
        "temperature": 0.0,
    }
    if stream:
        body["stream_options"] = {"include_usage": True}
    return RequestPlan(path="/v1/chat/completions", body=body,
                       headers=headers, stream=bool(stream), kind=kind,
                       session_id=session_id, turn_index=turn_index,
                       max_tokens=body["max_tokens"])
