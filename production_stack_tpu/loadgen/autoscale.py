"""Autoscale ramp: offered QPS up then down, replicas must follow.

The autoscaler's closed loop (ISSUE 5). The rig launches the real
router with ``--dynamic-config-json`` hot reload in front of an
initial engine fleet owned by a ``LocalProcessActuator``, starts the
``Autoscaler`` control loop against per-engine ``/load`` signals, and
drives an OPEN-loop QPS ramp through a phase profile shaped up then
down (e.g. 4 -> 12 -> 24 -> 12 -> 4). Requests are classified exactly
like the overload sweep (ok / ok_late / shed / error).

The acceptance contract (``autoscale_violations``; CLI exits 1 on any):

- **zero errors** — no raw 5xx / transport failure may reach a client
  across any scale-up or drain-based scale-down event (structured
  429/503 + Retry-After sheds are counted separately: transient sheds
  while a scale-up is still launching are the system working, not a
  bug);
- the controller actually **scaled up and back down** (replicas
  1 -> N -> 1 tracks the ramp; the fleet ends at min_replicas);
- **goodput tracks offered load** at the ramp's peak: peak-phase
  goodput >= ``track_fraction`` x offered (a fixed fleet saturates at
  one replica's capacity instead);
- when a fixed-N comparison run is attached, autoscale peak goodput
  beats it by ``compare_margin`` x;
- **zero drain timeouts** — every retired replica drained clean.

The committed record is ``AUTOSCALE_*.json`` (BENCH schema; headline =
peak-phase goodput). Reproduction one-liners: docs/benchmarks.md
"Autoscaling: replicas track the ramp".
"""

import asyncio
import os
import time
from typing import Dict, List, Optional

from production_stack_tpu.autoscaler.actuator import LocalProcessActuator
from production_stack_tpu.autoscaler.collector import SignalCollector
from production_stack_tpu.autoscaler.controller import Autoscaler
from production_stack_tpu.autoscaler.policy import (AutoscalerPolicy,
                                                    PolicyConfig)
from production_stack_tpu.loadgen.orchestrator import (_stop, free_port,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.loadgen.overload import (ENGINE_PROTECTION_ARGS,
                                                   measure_point)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

ROUTER_AUTOSCALE_ARGS = ["--failover-attempts", "3",
                         "--engine-stats-interval", "1",
                         "--dynamic-config-interval", "0.3"]


def autoscale_violations(record: Dict, *,
                         track_fraction: float = 0.7,
                         compare_margin: float = 1.3) -> List[str]:
    """The ramp's pass/fail contract (CLI exits 1 on any)."""
    d = record["detail"]
    phases = d["phases"]
    out = []
    if not phases:
        return ["no phases measured"]
    errors = sum(p["errors"] for p in phases)
    if errors:
        out.append(f"{errors} client-visible errors (raw 5xx or "
                   f"transport failures) — scale events must be "
                   f"loss-free")
    late = sum(p["ok_late"] for p in phases)
    if late:
        out.append(f"{late} accepted requests finished past their "
                   f"deadline")
    if not d["fixed"]:
        if d["scale_ups"] == 0:
            out.append("replicas never scaled up: the controller did "
                       "not track the ramp")
        if d["scale_downs"] == 0:
            out.append("replicas never scaled down: ramp-down load "
                       "should have retired capacity")
        if d["final_replicas"] > d["min_replicas"]:
            out.append(f"fleet ended at {d['final_replicas']} replicas "
                       f"(> min {d['min_replicas']}): scale-down never "
                       f"converged")
        if d["drain_timeouts"]:
            out.append(f"{d['drain_timeouts']} scale-downs hit the "
                       f"drain bound instead of draining clean")
    peak = max(phases, key=lambda p: p["offered_qps"])
    floor = track_fraction * peak["offered_qps"]
    if not d["fixed"] and peak["goodput_qps"] < floor:
        out.append(
            f"goodput failed to track offered load at the peak: "
            f"{peak['goodput_qps']} qps at offered "
            f"{peak['offered_qps']} (< {floor:.1f} = "
            f"{100 * track_fraction:.0f}%)")
    comp = d.get("comparison")
    if comp is not None:
        comp_errors = sum(p["errors"]
                          for p in comp["detail"]["phases"])
        if comp_errors:
            out.append(f"{comp_errors} client-visible errors in the "
                       f"fixed-N comparison run (same stack, same "
                       f"loss-free contract)")
        fixed_peak = max(comp["detail"]["phases"],
                         key=lambda p: p["offered_qps"])
        need = compare_margin * fixed_peak["goodput_qps"]
        if peak["goodput_qps"] < need:
            out.append(
                f"autoscale peak goodput {peak['goodput_qps']} qps is "
                f"not a clear win over the fixed-N="
                f"{comp['detail']['replicas_initial']} baseline "
                f"{fixed_peak['goodput_qps']} qps (need >= "
                f"{need:.1f} = {compare_margin}x)")
    return out


async def run_autoscale(*, engine: str = "fake",
                        qps_profile: Optional[List[float]] = None,
                        phase_duration_s: float = 15.0,
                        min_replicas: int = 1,
                        max_replicas: int = 3,
                        initial_replicas: int = 1,
                        deadline_ms: float = 8000.0,
                        num_tokens: int = 4,
                        fake_capacity: int = 4,
                        fake_tokens_per_s: float = 10.0,
                        tick_interval_s: float = 1.0,
                        target_utilization: float = 0.85,
                        down_utilization: float = 0.45,
                        target_queue_delay_ms: float = 500.0,
                        down_queue_delay_ms: float = 100.0,
                        up_cooldown_s: float = 4.0,
                        down_cooldown_s: float = 8.0,
                        up_breach_ticks: int = 2,
                        down_breach_ticks: int = 3,
                        fixed_replicas: Optional[int] = None,
                        settle_timeout_s: float = 45.0,
                        drain_timeout_s: float = 30.0,
                        platform: str = "cpu",
                        log_dir: str = "loadgen-logs",
                        startup_timeout_s: float = 420.0) -> Dict:
    """Launch router + actuator-owned engines (+ the autoscaler unless
    ``fixed_replicas`` pins the fleet) and drive the ramp; return the
    AUTOSCALE record."""
    if qps_profile is None:
        qps_profile = [4.0, 12.0, 24.0, 12.0, 4.0]
    fixed = fixed_replicas is not None
    initial = fixed_replicas if fixed else initial_replicas

    extra = None
    if engine == "fake":
        # bounded fake queue, same modeling as the overload sweep:
        # service time as TTFT, capacity advertised for the router's
        # endpoint cap AND the autoscaler's utilization signal
        service_s = num_tokens / max(fake_tokens_per_s, 1e-9)
        extra = ["--ttft", f"{service_s:.4f}",
                 "--num-tokens", str(num_tokens),
                 "--fault", "overload",
                 "--fault-arg", str(fake_capacity)]
    else:
        extra = list(ENGINE_PROTECTION_ARGS)

    os.makedirs(log_dir, exist_ok=True)
    config_path = os.path.join(
        log_dir, f"autoscale-config{'-fixed' if fixed else ''}.json")
    decision_log = os.path.join(log_dir, "autoscale-decisions.jsonl")

    actuator = LocalProcessActuator(
        engine=engine, dynamic_config_path=config_path,
        routing_logic="least_loaded", log_dir=log_dir,
        platform=platform, engine_extra_args=extra,
        startup_timeout_s=startup_timeout_s,
        drain_timeout_s=drain_timeout_s)
    model = actuator.model
    router = None
    scaler = None
    phases: List[Dict] = []
    try:
        urls = await actuator.start(initial)
        router = launch_router(
            urls, model, free_port(), routing="least_loaded",
            log_dir=log_dir,
            extra_args=ROUTER_AUTOSCALE_ARGS
            + ["--dynamic-config-json", config_path])
        actuator.router_url = router.url
        await wait_healthy(router.url, 60.0, require_endpoints=initial)

        if not fixed:
            policy = AutoscalerPolicy(PolicyConfig(
                min_replicas=min_replicas, max_replicas=max_replicas,
                target_queue_delay_ms=target_queue_delay_ms,
                down_queue_delay_ms=down_queue_delay_ms,
                target_utilization=target_utilization,
                down_utilization=down_utilization,
                up_cooldown_s=up_cooldown_s,
                down_cooldown_s=down_cooldown_s,
                up_breach_ticks=up_breach_ticks,
                down_breach_ticks=down_breach_ticks))
            collector = SignalCollector(actuator.endpoint_urls,
                                        router_url=router.url,
                                        poll_interval_s=tick_interval_s)
            scaler = Autoscaler(policy, actuator, collector,
                                interval_s=tick_interval_s,
                                decision_log_path=decision_log)
            await scaler.start()
            # one settled tick before traffic so the first decision
            # sees real (idle) signals, not an empty poller
            await asyncio.sleep(tick_interval_s)

        for qps in qps_profile:
            replicas_at_start = actuator.replicas
            logger.info("autoscale phase: %.1f qps offered for %.0fs "
                        "(replicas=%d)", qps, phase_duration_s,
                        replicas_at_start)
            p = await measure_point(router.url, model, qps=qps,
                                    duration_s=phase_duration_s,
                                    deadline_ms=deadline_ms,
                                    num_tokens=num_tokens)
            p["replicas_at_start"] = replicas_at_start
            p["replicas_at_end"] = actuator.replicas
            phases.append(p)
            logger.info("  -> goodput %.2f qps, %d ok / %d shed / "
                        "%d errors, replicas %d -> %d",
                        p["goodput_qps"], p["ok"], p["shed"],
                        p["errors"], replicas_at_start,
                        actuator.replicas)

        # ramp is over; give the controller time to retire idle
        # capacity back down to the floor (drain-safe, so this also
        # exercises the scale-down path even on short profiles)
        final_replicas = actuator.replicas
        if not fixed:
            deadline = time.monotonic() + settle_timeout_s
            while time.monotonic() < deadline:
                if actuator.replicas <= min_replicas:
                    break
                await asyncio.sleep(0.5)
            final_replicas = actuator.replicas
            await scaler.close()
            scaler_summary = scaler.summary()
        else:
            scaler_summary = {"ticks": 0, "scale_ups": 0,
                              "scale_downs": 0, "failed_actuations": 0,
                              "max_replicas_observed": initial,
                              "scale_events": []}
    finally:
        if scaler is not None and scaler.healthy():
            await scaler.close()
        if router is not None:
            _stop([router])
        await actuator.close()

    peak = max((p["goodput_qps"] for p in phases), default=0.0)
    drain_timeouts = len([e for e in actuator.events
                          if e[0] == "drain_timeout"])
    return {
        "metric": "goodput under an offered-QPS ramp with "
                  + ("a FIXED fleet (comparison baseline)" if fixed
                     else "closed-loop replica autoscaling"),
        "value": peak,
        "unit": "goodput_qps",
        "platform": platform,
        "detail": {
            "engine": engine,
            "fixed": fixed,
            "qps_profile": qps_profile,
            "phase_duration_s": phase_duration_s,
            "deadline_ms": deadline_ms,
            "num_tokens": num_tokens,
            "replicas_initial": initial,
            "min_replicas": min_replicas,
            "max_replicas": max_replicas,
            "final_replicas": final_replicas,
            "max_replicas_observed": scaler_summary[
                "max_replicas_observed"],
            "scale_ups": scaler_summary["scale_ups"],
            "scale_downs": scaler_summary["scale_downs"],
            "failed_actuations": scaler_summary["failed_actuations"],
            "drain_timeouts": drain_timeouts,
            "decision_ticks": scaler_summary["ticks"],
            "scale_events": scaler_summary["scale_events"],
            "actuator_events": [list(e) for e in actuator.events],
            "policy": (None if fixed else {
                "target_utilization": target_utilization,
                "down_utilization": down_utilization,
                "target_queue_delay_ms": target_queue_delay_ms,
                "down_queue_delay_ms": down_queue_delay_ms,
                "up_cooldown_s": up_cooldown_s,
                "down_cooldown_s": down_cooldown_s,
                "up_breach_ticks": up_breach_ticks,
                "down_breach_ticks": down_breach_ticks,
                "tick_interval_s": tick_interval_s,
            }),
            "engine_args": (f"overload fault, capacity {fake_capacity}, "
                            f"{fake_tokens_per_s} tok/s"
                            if engine == "fake"
                            else " ".join(ENGINE_PROTECTION_ARGS)),
            "phases": phases,
        },
    }
