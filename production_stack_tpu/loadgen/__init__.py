"""loadgen: committed load-generation, soak, and DP scale-out measurement.

The measurement layer the serving stack's perf claims rest on — in-repo
so every BASELINE number is a one-command reproduction from a fresh
clone (the reference commits its request generator and multi-server
sweep the same way: src/tests/perftest/request_generator.py,
run-multi-server.sh).

Pieces (each importable on its own):

- ``spec``     — dataclass workload specs (traffic mix, session shape,
                 arrival process) + JSON round-trip and named presets
- ``workload`` — deterministic, seeded session/turn planning and
                 OpenAI-protocol payload construction
- ``arrival``  — closed-loop and open-loop (Poisson, QPS ramp) arrival
                 processes
- ``client``   — asyncio streaming client with per-request TTFT / ITL /
                 e2e capture and abort injection
- ``runner``   — drives a workload against a base URL; soak invariants
                 and periodic checkpoint lines
- ``report``   — aggregation into BENCH-schema JSON and SCALEOUT_*.json
- ``orchestrator`` — launches N engine processes + the router and
                 measures the aggregate-tokens/s-vs-replicas curve
- ``overhead`` — router-vs-direct A/B storm (data-plane overhead ratio)
- ``chaos``    — engine kill/restart churn under storm (availability)
- ``overload`` — open-loop offered-QPS sweep past saturation (goodput
                 plateau, deadline compliance, structured sheds)
- ``autoscale`` — offered-QPS ramp against the closed-loop autoscaler
                 (replicas track the ramp, drain-safe scale-down,
                 fixed-N comparison)
- ``kvshare`` / ``disagg`` / ``trace`` / ``firedrill`` / ``effwatch``
                 — the r11–r15 closed loops (cross-replica KV sharing,
                 P/D split A/B, span-chain joins, SLO fire drill,
                 efficiency-accounting audit)
- ``multirouter`` — N peered router replicas behind an in-process L4
                 splitter (affinity vs single-router control, breaker
                 convergence, router-SIGKILL blip containment, QoS
                 tier degradation)

CLI: ``python -m production_stack_tpu.loadgen
{run,soak,scaleout,overhead,chaos,overload,autoscale,kvshare,disagg,
trace,firedrill,effwatch,multirouter} ...``
(docs/benchmarks.md has the cookbook).

Talks to the stack only through its public HTTP surfaces; no imports
from engine/ or router/ internals.
"""

from production_stack_tpu.loadgen.spec import (ArrivalSpec, SessionSpec,
                                               TrafficMix, WorkloadSpec)

__all__ = ["ArrivalSpec", "SessionSpec", "TrafficMix", "WorkloadSpec"]
