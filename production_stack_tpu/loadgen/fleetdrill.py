"""fleetdrill: prove the fleet pilot closes BOTH loops off ``/fleet``.

The r20 fleet pilot makes two promises, and each is easy to fake:

- **burn-rate-driven scale-up** is only worth having if it beats the
  queue-delay loop it augments — so the drill runs the SAME latency
  burn twice, once with the pilot (``FleetSignalCollector`` +
  ``burn_rate_input``) and once with an embedded queue-delay-only
  control, and the pilot must resolve the alert with zero shed at
  LOWER replica-seconds (the fleet spends less total capacity-time
  burning because the page alert fires seconds before the queue-delay
  threshold crossing).
- **bounded auto-remediation** is only safe if the kill-switch is
  real — so alongside the hands-off drain->restart->verify scenario,
  an anti-vacuity run repeats the SAME injection with the kill-switch
  down and must show the remediation suppressed (logged
  ``suppressed_killswitch``) and the alert still burning.

Scenarios (all three run by default; ``exit 1`` on any violation):

1. ``burn`` — a latency burn whose severity is inversely proportional
   to fleet size (the drill's load model: per-engine ``slow_ttft`` =
   burn / replicas and a queue-delay ramp split across replicas,
   pushed via ``POST /fault`` — fake engines have no load-dependent
   latency of their own). The pilot's page alert (reason
   ``burn_rate``, ``signal_source: fleet``) scales up before the
   control's queue-delay threshold trips; both runs must resolve, the
   pilot with zero shed and strictly fewer replica-seconds from
   injection to resolution.
2. ``remediate`` — ``slow_ttft`` on ONE engine of a fixed fleet; the
   obsplane captures the incident, its attribution names the culprit,
   and the armed remediator drains it, restarts it, resets its
   breaker and verifies the alert resolves — hands-off, zero
   client-visible errors, EXACTLY ONE executed remediation in the
   decision log.
3. ``killswitch`` — the same injection with ``enabled=False``: the
   attempt must be logged ``suppressed_killswitch``, nothing may
   actuate, and the alert must still be firing when the drill checks
   — then the drill clears the fault itself and the alert must
   resolve (proving the suppressed run left a resolvable fleet, not a
   wedged one).

Committed record: ``FLEETDRILL_r20.json`` via
``benchmarks/run_fleetdrill.sh``.
"""

import asyncio
import json
import os
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.autoscaler.collector import (
    FleetSignalCollector, SignalCollector)
from production_stack_tpu.autoscaler.actuator import (Actuator,
                                                      LocalProcessActuator)
from production_stack_tpu.autoscaler.controller import Autoscaler
from production_stack_tpu.autoscaler.policy import (AutoscalerPolicy,
                                                    PolicyConfig)
from production_stack_tpu.autoscaler.remediator import (RemediationPolicy,
                                                        Remediator)
from production_stack_tpu.loadgen.firedrill import (_Control,
                                                    drill_slo_config)
from production_stack_tpu.loadgen.incident import (_FleetStorm,
                                                   _obsplane_get,
                                                   _wait_fleet)
from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_engine,
                                                       launch_obsplane,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.slo import WINDOWS
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

SCENARIO_NAMES = ("burn", "remediate", "killswitch")

ALERT = "chat_ttft_page"

# breaker effectively off (the drill's faults must reach the SLO
# engine, not be masked by r8 resilience), fast stats + SLO eval +
# dynamic-config reload — the firedrill shape plus the autoscaler's
# hot-reload knob
ROUTER_FLEETDRILL_ARGS = ["--failover-attempts", "1",
                          "--breaker-threshold", "1000000",
                          "--breaker-failure-rate", "1.01",
                          "--engine-stats-interval", "0.5",
                          "--request-timeout", "20",
                          "--slo-eval-interval", "0.25",
                          "--dynamic-config-interval", "0.3"]

FAKE_ARGS = ["--tokens-per-s", "400", "--num-tokens", "4"]


class _FixedActuator(Actuator):
    """A pinned fleet for the remediation scenarios: the remediator
    rides the autoscaler loop, but nothing may scale."""

    def __init__(self, count: int):
        self._replicas = count

    @property
    def replicas(self) -> int:
        return self._replicas

    async def apply(self, target: int, victims=None) -> None:
        raise RuntimeError("fixed fleet must not scale")


async def _firing(control: _Control, router_url: str) -> List[str]:
    body = await control.alerts(router_url)
    return list((body or {}).get("firing") or [])


def _storm_phase(storm: _FleetStorm, phase: str) -> dict:
    return storm.totals().get(phase) or {
        "launched": 0, "ok": 0, "http_5xx": 0, "http_4xx": 0,
        "shed": 0, "transport_errors": 0, "samples": []}


# ---------------------------------------------------------------- burn

async def _burn_run(*, pilot: bool,
                    window_scale: float,
                    users: int,
                    baseline_s: float,
                    detect_timeout_s: float,
                    resolve_timeout_s: float,
                    burn_ttft_s: float,
                    queue_ramp_ms_per_s: float,
                    queue_plateau_ms: float,
                    max_replicas: int,
                    tick_interval_s: float,
                    min_events: int,
                    log_dir: str,
                    startup_timeout_s: float) -> Dict:
    """One latency-burn pass: pilot (burn-rate input off /fleet) or the
    embedded queue-delay-only control. Same stack, same load model,
    same gates — only the signal path differs."""
    tag = "pilot" if pilot else "control"
    slo_cfg_path = os.path.join(log_dir, f"fleetdrill_slo_{tag}.json")
    with open(slo_cfg_path, "w") as f:
        json.dump(drill_slo_config(window_scale,
                                   min_events=min_events), f, indent=2)
    config_path = os.path.join(log_dir, f"fleetdrill-config-{tag}.json")
    decision_log = os.path.join(log_dir,
                                f"fleetdrill-decisions-{tag}.jsonl")

    actuator = LocalProcessActuator(
        engine="fake", dynamic_config_path=config_path,
        routing_logic="least_loaded", log_dir=log_dir,
        engine_extra_args=list(FAKE_ARGS),
        startup_timeout_s=startup_timeout_s, drain_timeout_s=20.0)
    procs: List[Proc] = []
    storm = None
    scaler = None
    obs_url = None
    try:
        urls = await actuator.start(1)
        router = launch_router(
            urls, actuator.model, free_port(), routing="least_loaded",
            log_dir=log_dir,
            extra_args=ROUTER_FLEETDRILL_ARGS
            + ["--slo-config", slo_cfg_path,
               "--dynamic-config-json", config_path])
        procs.append(router)
        actuator.router_url = router.url
        await wait_healthy(router.url, 60.0, require_endpoints=1)

        if pilot:
            # --engines-config makes the obsplane's scraped engine set
            # follow the elastic fleet (a scaled-up replica the
            # aggregator cannot see would hold the settling gate
            # forever); captures are off — this scenario measures the
            # scale input, the remediation scenarios own the bundles
            obsplane = launch_obsplane(
                [router.url], urls, free_port(), log_dir=log_dir,
                incident_dir=os.path.join(log_dir,
                                          "fleetdrill-burn-incidents"),
                extra_args=["--poll-interval", "0.3",
                            "--scrape-timeout", "2",
                            "--engines-config", config_path,
                            "--no-capture-on-alert"])
            procs.append(obsplane)
            await wait_healthy(obsplane.url, 60.0)
            obs_url = obsplane.url

        policy_cfg = PolicyConfig(
            min_replicas=1, max_replicas=max_replicas,
            target_queue_delay_ms=800.0, down_queue_delay_ms=100.0,
            target_utilization=0.95, down_utilization=0.10,
            up_cooldown_s=3.0, down_cooldown_s=120.0,
            up_breach_ticks=2, down_breach_ticks=20,
            burn_rate_input=pilot,
            # an un-breached phase bound: exercises the pilot's phase-
            # percentile input path without adding a second trigger
            phase_p95_targets=({"engine.prefill": 30000.0}
                               if pilot else None)).validate()
        if pilot:
            collector = FleetSignalCollector(
                actuator.endpoint_urls, obsplane_url=obs_url,
                router_url=router.url,
                poll_interval_s=tick_interval_s, freshness_s=5.0)
        else:
            collector = SignalCollector(
                actuator.endpoint_urls, router_url=router.url,
                poll_interval_s=tick_interval_s)
        scaler = Autoscaler(AutoscalerPolicy(policy_cfg), actuator,
                            collector, interval_s=tick_interval_s,
                            decision_log_path=decision_log)
        await scaler.start()

        async with aiohttp.ClientSession() as control_session:
            control = _Control(control_session)
            # idle-fleet pacing: baseline requests carry the RELIEVED
            # TTFT (burn / max_replicas, under the threshold) so the
            # baseline request rate matches the scaled-up fleet's.
            # Without it the fast baseline floods the page alert's
            # long window with good events and the burn cannot cross
            # 14.4% before the queue-delay threshold trips — the race
            # this scenario exists to measure would be unwinnable.
            pace_s = round(burn_ttft_s / max_replicas, 4)
            for u in actuator.endpoint_urls():
                await control.post_fault(u, {"mode": "slow_ttft",
                                             "arg": pace_s,
                                             "count": -1})
            storm = _FleetStorm([router.url], actuator.model,
                                users=users, num_tokens=4)
            storm.start()
            await asyncio.sleep(baseline_s)
            baseline_firing = await _firing(control, router.url)

            # ------------------------------------------ the load model
            # fake engines have no load-dependent latency, so the drill
            # IS the queueing model: per-engine TTFT = burn / replicas
            # (floored at the idle pacing set above)
            # (adding a replica halves every engine's latency, exactly
            # the relief a real scale-up buys) and a slow queue-delay
            # ramp split the same way — slow enough that the burn-rate
            # page alert beats the 800 ms threshold crossing by seconds
            storm.phase = "burn"
            t_inject = time.monotonic()
            stop_model = asyncio.Event()

            async def load_model():
                while not stop_model.is_set():
                    reps = max(1, actuator.replicas)
                    elapsed = time.monotonic() - t_inject
                    qd = min(queue_ramp_ms_per_s * elapsed,
                             queue_plateau_ms) / reps
                    body = {"mode": "slow_ttft",
                            "arg": round(max(pace_s,
                                             burn_ttft_s / reps), 4),
                            "count": -1,
                            "queue_delay_ms": round(qd, 1)}
                    for u in actuator.endpoint_urls():
                        await control.post_fault(u, body)
                    try:
                        await asyncio.wait_for(stop_model.wait(), 0.4)
                    except asyncio.TimeoutError:
                        pass

            model_task = asyncio.create_task(load_model())

            # ------------------- fire -> resolve, integrating replicas
            fired_in = resolved_in = None
            replica_seconds = 0.0
            last = time.monotonic()
            deadline = t_inject + detect_timeout_s + resolve_timeout_s
            while time.monotonic() < deadline:
                now = time.monotonic()
                replica_seconds += actuator.replicas * (now - last)
                last = now
                firing = await _firing(control, router.url)
                if ALERT in firing and fired_in is None:
                    fired_in = round(now - t_inject, 2)
                if fired_in is not None and ALERT not in firing:
                    resolved_in = round(now - t_inject, 2)
                    break
                if fired_in is None and \
                        now - t_inject > detect_timeout_s:
                    break
                await asyncio.sleep(0.3)

            stop_model.set()
            await model_task
            for u in actuator.endpoint_urls():
                await control.post_fault(u, {"mode": None,
                                             "queue_delay_ms": None})
            storm.phase = "settle"
            await asyncio.sleep(1.0)
            await storm.stop()
            control_errors = list(control.errors)

        await scaler.close()
        first_up = next((d for d in scaler.timeline()
                         if d.get("direction") == "up"), None)
        summary = scaler.summary()
        fleet_stats = None
        if pilot:
            fleet_stats = {"fleet_polls": collector.fleet_polls,
                           "fleet_failures": collector.fleet_failures,
                           "last_source": collector.last_source}
        return {
            "pilot": pilot,
            "baseline_firing": baseline_firing,
            "fired_in_s": fired_in,
            "resolved_in_s": resolved_in,
            "replica_seconds": round(replica_seconds, 1),
            "max_replicas_observed": summary["max_replicas_observed"],
            "scale_ups": summary["scale_ups"],
            "first_up_reason": (first_up or {}).get("reason"),
            "first_up_source": (first_up or {}).get("signal_source"),
            "fleet_collector": fleet_stats,
            "storm": _storm_phase(storm, "burn"),
            "control_errors": control_errors,
        }
    finally:
        if storm is not None and not storm._stopping:
            await storm.stop()
        if scaler is not None and scaler.healthy():
            await scaler.close()
        _stop(procs)
        await actuator.close()


# ---------------------------------------------- remediate / killswitch

async def _remediation_run(*, armed: bool,
                           window_scale: float,
                           engines: int,
                           users: int,
                           baseline_s: float,
                           detect_timeout_s: float,
                           resolve_timeout_s: float,
                           slow_ttft_arg_s: float,
                           tick_interval_s: float,
                           min_events: int,
                           log_dir: str,
                           startup_timeout_s: float) -> Dict:
    """One incident-loop pass: ``slow_ttft`` on engine 0 of a fixed
    fleet. ``armed=True`` is the hands-off drain->restart->verify run;
    ``armed=False`` is the kill-switch anti-vacuity run (suppression
    logged, alert must persist, drill cleans up)."""
    tag = "remediate" if armed else "killswitch"
    slo_cfg_path = os.path.join(log_dir, f"fleetdrill_slo_{tag}.json")
    with open(slo_cfg_path, "w") as f:
        json.dump(drill_slo_config(window_scale,
                                   min_events=min_events), f, indent=2)
    incident_dir = os.path.join(log_dir, f"fleetdrill-{tag}-incidents")

    procs: List[Proc] = []
    engine_procs: List[Proc] = []
    storm = None
    scaler = None
    remediator = None
    try:
        for _ in range(engines):
            engine_procs.append(launch_engine(
                "fake", free_port(), log_dir=log_dir,
                extra_args=list(FAKE_ARGS)))
        procs.extend(engine_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in engine_procs])
        urls = [e.url for e in engine_procs]

        # roundrobin, deliberately: it keeps routing a full 1/Nth of
        # traffic at the slow engine, so the bad fraction (1/N) burns
        # the 1% budget at page rate — least_loaded would starve the
        # victim of requests and mask the very incident being injected
        router = launch_router(
            urls, "fake-model", free_port(), routing="roundrobin",
            log_dir=log_dir,
            extra_args=ROUTER_FLEETDRILL_ARGS
            + ["--slo-config", slo_cfg_path])
        procs.append(router)
        await wait_healthy(router.url, 60.0, require_endpoints=engines)

        obsplane = launch_obsplane(
            [router.url], urls, free_port(), log_dir=log_dir,
            incident_dir=incident_dir,
            extra_args=["--poll-interval", "0.3",
                        "--scrape-timeout", "2",
                        "--capture-cooldown", "5",
                        "--attribution-lookback",
                        str(detect_timeout_s + 15.0)])
        procs.append(obsplane)
        await wait_healthy(obsplane.url, 60.0)

        async def restart_fn(url: str) -> bool:
            """The drill's process owner: kill the sick engine, relaunch
            on the SAME port (clean — faults live in process memory, so
            a restart IS the fix, like a real wedged runtime)."""
            url = url.rstrip("/")
            idx = urls.index(url)
            await asyncio.to_thread(_stop, [engine_procs[idx]])
            port = int(url.rsplit(":", 1)[1])
            newp = launch_engine("fake", port, log_dir=log_dir,
                                 extra_args=list(FAKE_ARGS))
            procs.append(newp)
            engine_procs[idx] = newp
            try:
                await wait_healthy(newp.url, 60.0)
            except TimeoutError:
                return False
            return True

        remediator = Remediator(
            obsplane_url=obsplane.url, router_urls=[router.url],
            policy=RemediationPolicy(
                enabled=armed,
                # the phase-excess attribution rule convicts with
                # MEDIUM confidence (only process death and shed deltas
                # earn "high") — the floor is an explicit drill knob,
                # not a default
                confidence_floor="medium",
                max_per_window=1, window_s=600.0, cooldown_s=60.0,
                drain_timeout_s=15.0, drain_poll_s=0.25,
                verify_timeout_s=resolve_timeout_s,
                verify_poll_s=0.5),
            restart_fn=restart_fn,
            engine_urls_fn=lambda: urls)
        policy_cfg = PolicyConfig(
            min_replicas=engines, max_replicas=engines,
            target_queue_delay_ms=1e9,
            down_queue_delay_ms=0.0).validate()
        collector = FleetSignalCollector(
            lambda: urls, obsplane_url=obsplane.url,
            router_url=router.url, poll_interval_s=tick_interval_s,
            freshness_s=5.0)
        scaler = Autoscaler(
            AutoscalerPolicy(policy_cfg), _FixedActuator(engines),
            collector, interval_s=tick_interval_s,
            decision_log_path=os.path.join(
                log_dir, f"fleetdrill-decisions-{tag}.jsonl"),
            remediator=remediator)
        await scaler.start()

        async with aiohttp.ClientSession() as control_session:
            control = _Control(control_session)
            storm = _FleetStorm([router.url], "fake-model",
                                users=users, num_tokens=4)
            storm.start()
            await asyncio.sleep(baseline_s)
            baseline_fleet = await _obsplane_get(control, obsplane.url,
                                                 "/fleet") or {}
            baseline_firing = [a.get("name") for a in
                               baseline_fleet.get("firing_alerts", [])]
            baseline_incidents = len(baseline_fleet.get("incidents",
                                                        []))

            victim = engine_procs[0].url
            storm.phase = tag
            t0 = time.monotonic()
            injected_ok = await control.post_fault(
                victim, {"mode": "slow_ttft", "arg": slow_ttft_arg_s,
                         "count": -1})

            detected_in = await _wait_fleet(
                control, obsplane.url,
                lambda p: any(a.get("name") == ALERT
                              for a in p.get("firing_alerts", [])),
                detect_timeout_s)

            # wait for the remediator's verdict (the executed path
            # blocks its autoscaler tick through drain + restart +
            # verify, so the budget covers the whole runbook)
            rem_deadline = time.monotonic() + detect_timeout_s \
                + resolve_timeout_s + 30.0
            while time.monotonic() < rem_deadline:
                if scaler.remediation_events:
                    break
                await asyncio.sleep(0.3)
            remediations = [dict(r) for r in scaler.remediation_events]
            executed = [r for r in remediations if "executed_at" in r]

            if armed:
                # hands-off: the restart itself cleared the fault; the
                # alert must resolve with NO drill-side intervention
                resolved_in = await _wait_fleet(
                    control, obsplane.url,
                    lambda p: not p.get("firing_alerts"),
                    resolve_timeout_s)
                still_firing = None
                cleanup_resolved = None
            else:
                # anti-vacuity: nothing may have actuated, and the
                # alert must STILL be burning when the drill looks
                fleet_now = await _obsplane_get(control, obsplane.url,
                                                "/fleet") or {}
                still_firing = any(
                    a.get("name") == ALERT
                    for a in fleet_now.get("firing_alerts", []))
                resolved_in = None
                # then prove the fleet was resolvable, not wedged:
                # clear the fault by hand and watch the alert leave
                await control.post_fault(victim, {"mode": None})
                cleanup_resolved = await _wait_fleet(
                    control, obsplane.url,
                    lambda p: not p.get("firing_alerts"),
                    resolve_timeout_s) is not None

            storm.phase = "settle"
            await asyncio.sleep(1.0)
            fleet_end = await _obsplane_get(control, obsplane.url,
                                            "/fleet") or {}
            await storm.stop()
            control_errors = list(control.errors)
            elapsed = round(time.monotonic() - t0, 1)

        await scaler.close()
        # late records (a verify that finished after the poll loop)
        remediations = [dict(r) for r in scaler.remediation_events]
        executed = [r for r in remediations if "executed_at" in r]
        return {
            "armed": armed,
            "victim": victim,
            "injected_ok": injected_ok,
            "baseline_firing": baseline_firing,
            "baseline_incidents": baseline_incidents,
            "detected_in_s": detected_in,
            "resolved_in_s": resolved_in,
            "still_firing_after_suppression": still_firing,
            "cleanup_resolved": cleanup_resolved,
            "remediations": remediations,
            "executed_count": len(executed),
            "incidents_total": len(fleet_end.get("incidents", [])),
            "firing_at_end": [a.get("name") for a in
                              fleet_end.get("firing_alerts", [])],
            "storm": _storm_phase(storm, tag),
            "duration_s": elapsed,
            "control_errors": control_errors,
        }
    finally:
        if storm is not None and not storm._stopping:
            await storm.stop()
        if scaler is not None and scaler.healthy():
            await scaler.close()
        if remediator is not None:
            await remediator.close()
        _stop(procs)


# ------------------------------------------------------------- the rig

async def run_fleetdrill(*, scenarios: Optional[List[str]] = None,
                         window_scale: float = 0.01,
                         users: int = 6,
                         engines: int = 3,
                         baseline_s: float = 6.0,
                         detect_timeout_s: Optional[float] = None,
                         resolve_timeout_s: Optional[float] = None,
                         burn_ttft_s: float = 0.4,
                         queue_ramp_ms_per_s: float = 60.0,
                         queue_plateau_ms: float = 1200.0,
                         max_replicas: int = 2,
                         slow_ttft_arg_s: float = 0.6,
                         tick_interval_s: float = 0.5,
                         min_events: int = 4,
                         platform: str = "cpu",
                         log_dir: str = "loadgen-logs",
                         startup_timeout_s: float = 420.0) -> Dict:
    """Run the fleet-pilot drill scenarios; return the FLEETDRILL
    record."""
    if scenarios is None:
        scenarios = list(SCENARIO_NAMES)
    unknown = [s for s in scenarios if s not in SCENARIO_NAMES]
    if unknown:
        raise ValueError(f"unknown scenarios {unknown}; "
                         f"options: {list(SCENARIO_NAMES)}")
    long_w = WINDOWS["1h"] * window_scale
    ticket_short_w = WINDOWS["30m"] * window_scale
    if detect_timeout_s is None:
        detect_timeout_s = max(15.0, 0.85 * long_w + 10.0)
    if resolve_timeout_s is None:
        resolve_timeout_s = max(15.0, ticket_short_w + 25.0)
    os.makedirs(log_dir, exist_ok=True)

    t0 = time.monotonic()
    detail: Dict[str, object] = {
        "window_scale": window_scale,
        "windows_s": {lbl: round(w * window_scale, 2)
                      for lbl, w in WINDOWS.items()},
        "min_events": min_events,
        "users": users,
        "baseline_s": baseline_s,
        "detect_timeout_s": round(detect_timeout_s, 1),
        "resolve_timeout_s": round(resolve_timeout_s, 1),
        "tick_interval_s": tick_interval_s,
        "scenarios_run": list(scenarios),
    }
    if "burn" in scenarios:
        burn_kw = dict(window_scale=window_scale, users=users,
                       baseline_s=baseline_s,
                       detect_timeout_s=detect_timeout_s,
                       resolve_timeout_s=resolve_timeout_s,
                       burn_ttft_s=burn_ttft_s,
                       queue_ramp_ms_per_s=queue_ramp_ms_per_s,
                       queue_plateau_ms=queue_plateau_ms,
                       max_replicas=max_replicas,
                       tick_interval_s=tick_interval_s,
                       min_events=min_events, log_dir=log_dir,
                       startup_timeout_s=startup_timeout_s)
        logger.info("fleetdrill burn: pilot run (burn-rate input off "
                    "/fleet)...")
        pilot = await _burn_run(pilot=True, **burn_kw)
        logger.info("fleetdrill burn: control run (queue-delay "
                    "only)...")
        ctl = await _burn_run(pilot=False, **burn_kw)
        detail["burn"] = {
            "burn_ttft_s": burn_ttft_s,
            "queue_ramp_ms_per_s": queue_ramp_ms_per_s,
            "queue_plateau_ms": queue_plateau_ms,
            "max_replicas": max_replicas,
            "pilot": pilot, "control": ctl,
            "replica_seconds_saved": (
                None if pilot["resolved_in_s"] is None
                or ctl["resolved_in_s"] is None
                else round(ctl["replica_seconds"]
                           - pilot["replica_seconds"], 1)),
        }
        logger.info(
            "fleetdrill burn: pilot fired=%s resolved=%s rs=%.1f "
            "(reason=%s source=%s) | control fired=%s resolved=%s "
            "rs=%.1f (reason=%s)",
            pilot["fired_in_s"], pilot["resolved_in_s"],
            pilot["replica_seconds"], pilot["first_up_reason"],
            pilot["first_up_source"], ctl["fired_in_s"],
            ctl["resolved_in_s"], ctl["replica_seconds"],
            ctl["first_up_reason"])
    rem_kw = dict(window_scale=window_scale, engines=engines,
                  users=users, baseline_s=baseline_s,
                  detect_timeout_s=detect_timeout_s,
                  resolve_timeout_s=resolve_timeout_s,
                  slow_ttft_arg_s=slow_ttft_arg_s,
                  tick_interval_s=tick_interval_s,
                  min_events=min_events, log_dir=log_dir,
                  startup_timeout_s=startup_timeout_s)
    if "remediate" in scenarios:
        logger.info("fleetdrill remediate: armed hands-off run...")
        detail["remediate"] = await _remediation_run(armed=True,
                                                     **rem_kw)
        r = detail["remediate"]
        logger.info("fleetdrill remediate: detected=%s executed=%d "
                    "resolved=%s outcomes=%s", r["detected_in_s"],
                    r["executed_count"], r["resolved_in_s"],
                    [x.get("outcome") for x in r["remediations"]])
    if "killswitch" in scenarios:
        logger.info("fleetdrill killswitch: suppressed anti-vacuity "
                    "run...")
        detail["killswitch"] = await _remediation_run(armed=False,
                                                      **rem_kw)
        k = detail["killswitch"]
        logger.info("fleetdrill killswitch: detected=%s outcomes=%s "
                    "still_firing=%s cleanup_resolved=%s",
                    k["detected_in_s"],
                    [x.get("outcome") for x in k["remediations"]],
                    k["still_firing_after_suppression"],
                    k["cleanup_resolved"])

    detail["duration_s"] = round(time.monotonic() - t0, 1)
    saved = (detail.get("burn") or {}).get("replica_seconds_saved")
    return {
        "metric": "fleet pilot: burn-rate scale-up beats the "
                  "queue-delay control on replica-seconds to "
                  "resolution; bounded remediation drains and restarts "
                  "the attributed culprit hands-off; the kill-switch "
                  "verifiably suppresses",
        "value": saved if saved is not None else 0.0,
        "unit": "replica_seconds_saved",
        "platform": platform,
        "detail": detail,
    }


def fleetdrill_violations(record: Dict) -> List[str]:
    """The drill's pass/fail contract (CLI exits 1 on any)."""
    d = record["detail"]
    out: List[str] = []

    def storm_errors(run: dict, who: str, gate_shed: bool) -> None:
        s = run["storm"]
        if s["http_5xx"] or s["transport_errors"]:
            out.append(f"{who}: {s['http_5xx']} 5xx / "
                       f"{s['transport_errors']} transport errors "
                       f"reached clients")
        if gate_shed and s["shed"]:
            out.append(f"{who}: {s['shed']} requests shed — the gate "
                       f"is zero shed")
        if s["ok"] == 0:
            out.append(f"{who}: storm finished zero requests — the "
                       f"scenario measured nothing")
        if run["control_errors"]:
            out.append(f"{who}: {len(run['control_errors'])} control-"
                       f"plane errors (first: "
                       f"{run['control_errors'][0]})")
        if run["baseline_firing"]:
            out.append(f"{who}: alerts firing during the clean "
                       f"baseline: {run['baseline_firing']}")

    burn = d.get("burn")
    if burn is not None:
        for who in ("pilot", "control"):
            run = burn[who]
            storm_errors(run, f"burn/{who}", gate_shed=(who == "pilot"))
            if run["fired_in_s"] is None:
                out.append(f"burn/{who}: {ALERT} never fired within "
                           f"{d['detect_timeout_s']}s")
            elif run["resolved_in_s"] is None:
                out.append(f"burn/{who}: {ALERT} fired but never "
                           f"resolved — the scale-up did not relieve "
                           f"the burn")
            if run["scale_ups"] == 0:
                out.append(f"burn/{who}: never scaled up")
        pilot, ctl = burn["pilot"], burn["control"]
        if pilot["first_up_reason"] != "burn_rate":
            out.append(f"burn/pilot: first scale-up reason was "
                       f"{pilot['first_up_reason']!r}, not "
                       f"'burn_rate' — the alert was not the trigger")
        if pilot["first_up_source"] != "fleet":
            out.append(f"burn/pilot: scale-up decision consumed signal "
                       f"source {pilot['first_up_source']!r}, not "
                       f"'fleet'")
        if ctl["first_up_reason"] == "burn_rate":
            out.append("burn/control: the queue-delay-only control "
                       "scaled on 'burn_rate' — the comparison is "
                       "vacuous")
        if pilot["resolved_in_s"] is not None \
                and ctl["resolved_in_s"] is not None \
                and pilot["replica_seconds"] >= ctl["replica_seconds"]:
            out.append(
                f"burn: pilot consumed {pilot['replica_seconds']} "
                f"replica-seconds to resolution vs the control's "
                f"{ctl['replica_seconds']} — the burn-rate input "
                f"bought nothing")
    rem = d.get("remediate")
    if rem is not None:
        storm_errors(rem, "remediate", gate_shed=True)
        if not rem["injected_ok"]:
            out.append("remediate: fault injection failed")
        if rem["detected_in_s"] is None:
            out.append(f"remediate: {ALERT} never fired within "
                       f"{d['detect_timeout_s']}s")
        if rem["baseline_incidents"]:
            out.append(f"remediate: {rem['baseline_incidents']} "
                       f"incident bundles captured during the clean "
                       f"baseline")
        if rem["executed_count"] != 1:
            out.append(f"remediate: {rem['executed_count']} executed "
                       f"remediations in the decision log, expected "
                       f"exactly 1")
        resolved = [r for r in rem["remediations"]
                    if r.get("outcome") == "resolved"]
        if len(resolved) != 1:
            out.append(f"remediate: outcomes "
                       f"{[r.get('outcome') for r in rem['remediations']]}"
                       f" — expected exactly one 'resolved'")
        else:
            r = resolved[0]
            if (r.get("target") or "").rstrip("/") != \
                    rem["victim"].rstrip("/"):
                out.append(f"remediate: remediation targeted "
                           f"{r.get('target')!r}, the injection hit "
                           f"{rem['victim']!r}")
            if r.get("action") != "drain_restart":
                out.append(f"remediate: action {r.get('action')!r}, "
                           f"expected 'drain_restart'")
        if rem["resolved_in_s"] is None:
            out.append(f"remediate: alert did not resolve hands-off "
                       f"within {d['resolve_timeout_s']}s of the "
                       f"remediation")
        if rem["firing_at_end"]:
            out.append(f"remediate: alerts still firing at scenario "
                       f"end: {rem['firing_at_end']}")
    ks = d.get("killswitch")
    if ks is not None:
        storm_errors(ks, "killswitch", gate_shed=True)
        if not ks["injected_ok"]:
            out.append("killswitch: fault injection failed")
        if ks["detected_in_s"] is None:
            out.append(f"killswitch: {ALERT} never fired within "
                       f"{d['detect_timeout_s']}s")
        suppressed = [r for r in ks["remediations"]
                      if r.get("outcome") == "suppressed_killswitch"]
        if not suppressed:
            out.append(f"killswitch: no 'suppressed_killswitch' record "
                       f"in the decision log (outcomes: "
                       f"{[r.get('outcome') for r in ks['remediations']]})"
                       f" — the suppression is unproven")
        if ks["executed_count"] != 0:
            out.append(f"killswitch: {ks['executed_count']} "
                       f"remediations EXECUTED with the kill-switch "
                       f"down")
        if ks["still_firing_after_suppression"] is not True:
            out.append("killswitch: the alert was not still firing "
                       "after the suppressed attempt — the "
                       "anti-vacuity gate is vacuous itself")
        if ks["cleanup_resolved"] is not True:
            out.append("killswitch: the alert did not resolve after "
                       "the drill cleared the fault by hand — the "
                       "suppressed run left a wedged fleet")
    return out
