"""incident mode: prove the fleet flight recorder closes the loop.

The obsplane (production_stack_tpu/obsplane) is only worth shipping if
(a) a clean fleet yields ZERO spurious incident bundles while its
online stitcher is demonstrably joining chains, and (b) when a real
fault burns a real SLO, the alert arrives WITH the fleet-wide evidence
attached: exactly one self-contained bundle in which every fleet
process is represented and the machine-written attribution names the
injected culprit process and the correct phase. This rig closes that
loop with the r14 firedrill machinery scaled to a fleet:

1. **Fleet**: N peered routers (r16 gossip) + M engines + the
   obsplane, all real subprocesses; SLO windows scaled to seconds
   (firedrill's ``drill_slo_config``), resilience masking disabled
   (the drill measures detection + attribution, not hiding).
2. **Baseline** (false-positive gate): a mixed chat/rag storm across
   every router; zero bundles may be captured, zero alerts fire, the
   storm sees zero 5xx — and the stitcher must show complete chains
   (an obsplane that stitches nothing would pass every other gate
   vacuously).
3. **Scenarios**, each: inject -> the expected alert fires (observed
   through the obsplane's OWN ``/fleet`` view) -> exactly one bundle
   appears -> the bundle holds every fleet process AND its attribution
   names the injected process and phase -> clear -> resolve -> settle:

   - ``slow_ttft``    — TTFT inflation on ONE engine ->
     ``chat_ttft_page``; attribution must name that engine, phase
     ``prefill`` (the per-process phase scoreboard)
   - ``engine_down``  — SIGKILL one engine, no goodbye ->
     ``chat_availability_page``; attribution must name the corpse,
     phase ``down`` (the unreachable-process rule)
   - ``shed_storm``   — a concurrency burst aimed at ONE router past
     its ``--max-inflight`` -> ``shed_rate_page``; attribution must
     name that router, phase ``admission`` (the shed-delta rule)

``--overhead-guard`` runs the r7 A/B twice — once with an obsplane
scraping the serving pair at the drill's poll interval, once without —
and fails only when the scraped side breaks the band AND exceeds the
same-host unscraped baseline by >10% (the multirouter guard shape).

Committed record: ``INCIDENT_r18.json`` via
``benchmarks/run_incident.sh``; exit 1 on any spurious capture, missed
alert, missing/extra bundle, incomplete bundle, or wrong attribution.
"""

import asyncio
import json
import os
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.firedrill import (
    ROUTER_FIREDRILL_ARGS, _Control, drill_slo_config)
from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_engine,
                                                       launch_obsplane,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.slo import WINDOWS
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"

SCENARIO_NAMES = ("slow_ttft", "engine_down", "shed_storm")
# scenarios driving the fake's /fault endpoint; a real-engine drill
# keeps the process-level kill and the router-side shed storm
_FAKE_ONLY = ("slow_ttft",)

EXPECTED = {
    # scenario -> (alert, culprit role, phase)
    "slow_ttft": ("chat_ttft_page", "engine", "prefill"),
    "engine_down": ("chat_availability_page", "engine", "down"),
    "shed_storm": ("shed_rate_page", "router", "admission"),
}


class _FleetStorm:
    """Closed-loop mixed chat/rag storm spread across N router URLs
    (worker i pins to router i mod N), phase-tagged outcome counters —
    the firedrill storm shape, fleet-wide."""

    def __init__(self, router_urls: List[str], model: str, *,
                 users: int, num_tokens: int,
                 request_timeout_s: float = 20.0):
        self.urls = list(router_urls)
        self.model = model
        self.users = users
        self.num_tokens = num_tokens
        self.timeout = aiohttp.ClientTimeout(total=request_timeout_s)
        self.phase = "baseline"
        self.counters: Dict[str, dict] = {}
        self._tasks: List[asyncio.Task] = []
        self._stopping = False

    def _c(self) -> dict:
        c = self.counters.get(self.phase)
        if c is None:
            c = self.counters[self.phase] = {
                "launched": 0, "ok": 0, "http_5xx": 0, "http_4xx": 0,
                "shed": 0, "transport_errors": 0, "samples": []}
        return c

    async def _one(self, session: aiohttp.ClientSession, url: str,
                   i: int, n: int) -> None:
        rag = (n % 5) == 0
        headers = {"Content-Type": "application/json"}
        if rag:
            headers["x-slo-class"] = "rag"
        body = json.dumps({
            "model": self.model,
            "messages": [{"role": "user",
                          "content": f"incident u{i} r{n}"
                                     + (" ctx " * 40 if rag else "")}],
            "max_tokens": self.num_tokens, "stream": False}).encode()
        c = self._c()
        c["launched"] += 1
        try:
            async with session.post(f"{url}{CHAT_PATH}", data=body,
                                    headers=headers,
                                    timeout=self.timeout) as resp:
                await resp.read()
                if resp.status < 400:
                    c["ok"] += 1
                elif resp.status in (429, 503) and \
                        "Retry-After" in resp.headers:
                    c["shed"] += 1
                elif resp.status >= 500:
                    c["http_5xx"] += 1
                    if len(c["samples"]) < 5:
                        c["samples"].append(f"HTTP {resp.status}")
                else:
                    c["http_4xx"] += 1
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            c["transport_errors"] += 1
            if len(c["samples"]) < 5:
                c["samples"].append(f"{type(e).__name__}: {e}")

    async def _worker(self, i: int) -> None:
        url = self.urls[i % len(self.urls)]
        n = i
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as session:
            while not self._stopping:
                await self._one(session, url, i, n)
                n += self.users
                await asyncio.sleep(0.02)

    def start(self) -> None:
        self._tasks = [asyncio.create_task(self._worker(i))
                       for i in range(self.users)]

    async def stop(self) -> None:
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)

    def totals(self) -> dict:
        return dict(self.counters)


class _Burst:
    """The shed-storm lever: ``users`` concurrent workers hammering
    ONE router back to back (no think time) until stopped — admission
    pressure, aimed, so the shed delta lands on a known process."""

    def __init__(self, url: str, model: str, users: int,
                 num_tokens: int):
        self.url = url
        self.model = model
        self.users = users
        self.num_tokens = num_tokens
        self._tasks: List[asyncio.Task] = []
        self._stopping = False
        self.launched = 0
        self.shed = 0

    async def _worker(self, i: int) -> None:
        body = json.dumps({
            "model": self.model,
            "messages": [{"role": "user", "content": f"burst {i}"}],
            "max_tokens": self.num_tokens, "stream": False}).encode()
        timeout = aiohttp.ClientTimeout(total=20)
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as session:
            while not self._stopping:
                self.launched += 1
                try:
                    async with session.post(
                            f"{self.url}{CHAT_PATH}", data=body,
                            headers={"Content-Type":
                                     "application/json"},
                            timeout=timeout) as resp:
                        await resp.read()
                        if resp.status in (429, 503):
                            self.shed += 1
                            await asyncio.sleep(0.01)
                except (aiohttp.ClientError, ConnectionError, OSError,
                        asyncio.TimeoutError):
                    await asyncio.sleep(0.05)

    def start(self) -> None:
        self._tasks = [asyncio.create_task(self._worker(i))
                       for i in range(self.users)]

    async def stop(self) -> None:
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)


async def _obsplane_get(control: _Control, url: str,
                        path: str) -> Optional[dict]:
    try:
        async with control.session.get(
                f"{url}{path}",
                timeout=aiohttp.ClientTimeout(total=5)) as r:
            if r.status == 200:
                return await r.json()
            control.errors.append(f"GET {path} -> HTTP {r.status}")
    except (aiohttp.ClientError, ConnectionError, OSError,
            asyncio.TimeoutError) as e:
        control.errors.append(f"GET {path} -> {type(e).__name__}: {e}")
    return None


async def _wait_fleet(control: _Control, obs_url: str, predicate,
                      timeout_s: float,
                      poll_s: float = 0.3) -> Optional[float]:
    """Poll the obsplane's /fleet until ``predicate(payload)``;
    seconds it took, or None on timeout."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        payload = await _obsplane_get(control, obs_url, "/fleet")
        if payload is not None and predicate(payload):
            return round(time.monotonic() - t0, 2)
        await asyncio.sleep(poll_s)
    return None


def bundle_completeness(bundle: dict,
                        expected: Dict[str, str]) -> List[str]:
    """Every fleet process must be represented in the bundle with the
    payloads its role owes (last-known state for a dead process) —
    returns what is missing. ``expected`` is {url: role}."""
    missing = []
    processes = (bundle.get("fleet") or {}).get("processes") or {}
    for url, role in expected.items():
        p = processes.get(url.rstrip("/"))
        if p is None:
            missing.append(f"{url}: absent from bundle")
            continue
        if role == "router":
            if p.get("health") is None:
                missing.append(f"{url}: no /health snapshot")
            if p.get("alerts") is None:
                missing.append(f"{url}: no /alerts snapshot")
        else:
            if p.get("load") is None:
                missing.append(f"{url}: no /load snapshot")
            if p.get("perf") is None:
                missing.append(f"{url}: no /debug/perf snapshot")
    return missing


async def run_incident(*, engines: int = 3,
                       routers: int = 2,
                       engine: str = "fake",
                       users: int = 8,
                       baseline_s: float = 10.0,
                       window_scale: float = 0.01,
                       scenarios: Optional[List[str]] = None,
                       detect_timeout_s: Optional[float] = None,
                       resolve_timeout_s: Optional[float] = None,
                       num_tokens: int = 4,
                       fake_tokens_per_s: float = 400.0,
                       slow_ttft_arg_s: float = 0.4,
                       ttft_threshold_s: Optional[float] = None,
                       max_inflight: int = 24,
                       burst_users: int = 64,
                       min_events: int = 4,
                       routing: str = "roundrobin",
                       platform: str = "cpu",
                       log_dir: str = "loadgen-logs",
                       incident_dir: Optional[str] = None,
                       poll_interval_s: float = 0.3,
                       capture_cooldown_s: float = 5.0,
                       startup_timeout_s: float = 420.0,
                       overhead_guard: bool = False,
                       overhead_users: int = 48,
                       overhead_duration_s: float = 10.0) -> Dict:
    """Launch the fleet + obsplane, storm, run the fault scenarios;
    return the INCIDENT record."""
    if scenarios is None:
        scenarios = list(SCENARIO_NAMES)
    if engine != "fake":
        dropped = [s for s in scenarios if s in _FAKE_ONLY]
        if dropped:
            logger.warning("real-engine incident drill: dropping "
                           "fake-only scenarios %s", dropped)
        scenarios = [s for s in scenarios if s not in _FAKE_ONLY]
    unknown = [s for s in scenarios if s not in SCENARIO_NAMES]
    if unknown:
        raise ValueError(f"unknown scenarios {unknown}; "
                         f"options: {list(SCENARIO_NAMES)}")
    if not scenarios:
        # a drill with zero scenarios would pass every gate vacuously
        raise ValueError("no scenarios left to run (real-engine mode "
                         "drops the fake-only ones — pick from "
                         f"{[s for s in SCENARIO_NAMES if s not in _FAKE_ONLY]})")
    if ttft_threshold_s is None:
        # the 0.25s bar is calibrated for the zero-think fake; a real
        # debug-tiny on a CPU host prefills in hundreds of ms, so the
        # same bar fires chat_ttft_page on a CLEAN baseline and the
        # spurious-capture gate (correctly) fails the drill
        ttft_threshold_s = 0.25 if engine == "fake" else 2.0

    long_w = WINDOWS["1h"] * window_scale
    ticket_short_w = WINDOWS["30m"] * window_scale
    if detect_timeout_s is None:
        detect_timeout_s = max(15.0, 0.85 * long_w + 10.0)
    if resolve_timeout_s is None:
        # floor: the ticket pair's short window must flush its bad
        # events (36s at scale 0.02) plus the scaled resolve hold —
        # and a real engine's post-restart tail (requests launched
        # against the warming replica) eats several more seconds, so
        # the slack is sized past the firedrill default
        resolve_timeout_s = max(15.0, ticket_short_w + 25.0)
    settle_s = ticket_short_w + 1.0

    os.makedirs(log_dir, exist_ok=True)
    if incident_dir is None:
        incident_dir = os.path.join(log_dir, "incidents")
    slo_cfg = drill_slo_config(window_scale, min_events=min_events,
                               ttft_threshold_s=ttft_threshold_s)
    slo_cfg_path = os.path.join(log_dir, "incident_slo_config.json")
    with open(slo_cfg_path, "w") as f:
        json.dump(slo_cfg, f, indent=2)

    procs: List[Proc] = []
    engine_procs: List[Proc] = []
    router_procs: List[Proc] = []
    fake_args = ["--tokens-per-s", str(fake_tokens_per_s),
                 "--num-tokens", str(num_tokens)] \
        if engine == "fake" else None
    record_scenarios: List[dict] = []
    storm = None
    try:
        for _ in range(engines):
            engine_procs.append(launch_engine(
                engine, free_port(), log_dir=log_dir, platform=platform,
                extra_args=fake_args))
        procs.extend(engine_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in engine_procs])
        model = "fake-model" if engine == "fake" else engine

        router_ports = [free_port() for _ in range(routers)]
        router_urls = [f"http://127.0.0.1:{p}" for p in router_ports]
        for i, port in enumerate(router_ports):
            peers = [u for j, u in enumerate(router_urls) if j != i]
            extra = (ROUTER_FIREDRILL_ARGS
                     + ["--slo-config", slo_cfg_path,
                        "--max-inflight", str(max_inflight),
                        "--router-id", f"router-{i}"])
            if peers:
                extra += ["--peer-routers", ",".join(peers),
                          "--peer-gossip-interval", "0.5"]
            router_procs.append(launch_router(
                [e.url for e in engine_procs], model, port,
                routing=routing, log_dir=log_dir, extra_args=extra))
        procs.extend(router_procs)
        await asyncio.gather(*[
            wait_healthy(r.url, 60.0, require_endpoints=engines)
            for r in router_procs])

        obsplane = launch_obsplane(
            router_urls, [e.url for e in engine_procs], free_port(),
            log_dir=log_dir, incident_dir=incident_dir,
            extra_args=["--poll-interval", str(poll_interval_s),
                        "--scrape-timeout", "2",
                        "--capture-cooldown", str(capture_cooldown_s),
                        "--attribution-lookback",
                        str(detect_timeout_s + 15.0)])
        procs.append(obsplane)
        await wait_healthy(obsplane.url, 60.0)

        logger.info("incident drill: %d users vs %d routers + %d %s "
                    "engines + obsplane, window_scale %g, scenarios %s",
                    users, routers, engines, engine, window_scale,
                    scenarios)
        async with aiohttp.ClientSession() as control_session:
            control = _Control(control_session)
            storm = _FleetStorm(router_urls, model, users=users,
                                num_tokens=num_tokens)
            storm.start()
            t0 = time.monotonic()

            # ---------------------------------------------- baseline
            await asyncio.sleep(baseline_s)
            baseline_fleet = await _obsplane_get(control, obsplane.url,
                                                 "/fleet") or {}
            baseline_traces = await _obsplane_get(
                control, obsplane.url, "/fleet/traces") or {}
            baseline_incidents = len(baseline_fleet.get("incidents",
                                                        []))
            baseline_firing = list(baseline_fleet.get("firing_alerts",
                                                      []))
            baseline_states = {
                url: p.get("state")
                for url, p in (baseline_fleet.get("processes")
                               or {}).items()}

            expected_procs = {r.url: "router" for r in router_procs}
            expected_procs.update(
                {e.url: "engine" for e in engine_procs})

            # ---------------------------------------------- scenarios
            seen_incidents = baseline_incidents
            burst: Optional[_Burst] = None
            killed: Dict[str, int] = {}

            async def inject(name: str) -> (bool, str):
                nonlocal burst
                if name == "slow_ttft":
                    victim = engine_procs[-1]
                    ok = await control.post_fault(
                        victim.url, {"mode": "slow_ttft",
                                     "arg": slow_ttft_arg_s,
                                     "count": -1})
                    return ok, victim.url
                if name == "engine_down":
                    victim = engine_procs[0]
                    victim.popen.kill()
                    victim.popen.wait()
                    killed[name] = 0
                    logger.info("incident: killed %s", victim.url)
                    return True, victim.url
                if name == "shed_storm":
                    target = router_procs[0]
                    burst = _Burst(target.url, model, burst_users,
                                   num_tokens)
                    burst.start()
                    return True, target.url
                raise AssertionError(name)

            async def clear(name: str) -> bool:
                nonlocal burst
                if name == "slow_ttft":
                    return await control.post_fault(
                        engine_procs[-1].url, {"mode": None})
                if name == "engine_down":
                    idx = killed.pop(name)
                    port = int(engine_procs[idx].url.rsplit(":", 1)[1])
                    engine_procs[idx] = launch_engine(
                        engine, port, log_dir=log_dir,
                        platform=platform, extra_args=fake_args)
                    # the finally-block _stop() walks `procs`, which
                    # holds the ORIGINAL (now dead) Proc — the
                    # replacement must join it or it leaks past the
                    # drill
                    procs.append(engine_procs[idx])
                    try:
                        # a REAL engine re-pays its XLA warmup here:
                        # the restart gets the same budget as launch
                        await wait_healthy(engine_procs[idx].url,
                                           startup_timeout_s)
                    except TimeoutError:
                        control.errors.append(
                            f"{engine_procs[idx].url} not healthy "
                            f"after restart")
                        return False
                    return True
                if name == "shed_storm":
                    if burst is not None:
                        await burst.stop()
                        burst = None
                    return True
                raise AssertionError(name)

            for name in scenarios:
                expected_alert, _role, expected_phase = EXPECTED[name]
                storm.phase = name
                await asyncio.sleep(0.5)
                injected_ok, culprit_url = await inject(name)
                injected_at = time.monotonic()

                detected_in = await _wait_fleet(
                    control, obsplane.url,
                    lambda p: any(a.get("name") == expected_alert
                                  for a in p.get("firing_alerts", [])),
                    detect_timeout_s)

                # the capture rides the SAME firing transition the
                # detection saw; give the poll loop a couple of beats
                captured_in = await _wait_fleet(
                    control, obsplane.url,
                    lambda p: len(p.get("incidents", []))
                    > seen_incidents,
                    max(10.0, 5 * poll_interval_s + 5.0)) \
                    if detected_in is not None else None

                fleet_now = await _obsplane_get(control, obsplane.url,
                                                "/fleet") or {}
                incidents_now = fleet_now.get("incidents", [])
                new_bundles = incidents_now[seen_incidents:]
                seen_incidents = len(incidents_now)

                bundle = None
                completeness: List[str] = []
                attribution = {}
                if len(new_bundles) >= 1:
                    bundle = await _obsplane_get(
                        control, obsplane.url,
                        f"/fleet/incidents/"
                        f"{new_bundles[0]['incident_id']}")
                if bundle is not None:
                    completeness = bundle_completeness(bundle,
                                                       expected_procs)
                    attribution = bundle.get("attribution") or {}

                cleared_ok = await clear(name)
                resolved_in = await _wait_fleet(
                    control, obsplane.url,
                    lambda p: not p.get("firing_alerts"),
                    resolve_timeout_s) if detected_in is not None \
                    else None

                storm.phase = "settle"
                await asyncio.sleep(settle_s)
                post_settle_quiet = await _wait_fleet(
                    control, obsplane.url,
                    lambda p: not p.get("firing_alerts"),
                    resolve_timeout_s)
                # fold captures that arrived during settle into THIS
                # scenario's count (a late ticket-pair capture would
                # otherwise blame the next scenario)
                fleet_settled = await _obsplane_get(
                    control, obsplane.url, "/fleet") or fleet_now
                late = len(fleet_settled.get("incidents", [])) \
                    - seen_incidents
                seen_incidents += max(0, late)

                record_scenarios.append({
                    "name": name,
                    "expected_alert": expected_alert,
                    "expected_process": culprit_url,
                    "expected_phase": expected_phase,
                    "injected_ok": injected_ok,
                    "cleared_ok": cleared_ok,
                    "t_inject_s": round(injected_at - t0, 2),
                    "detected_in_s": detected_in,
                    "captured_in_s": captured_in,
                    "bundles_captured": len(new_bundles) + max(0, late),
                    "bundle_id": (new_bundles[0]["incident_id"]
                                  if new_bundles else None),
                    "bundle_missing": completeness,
                    "attribution": {
                        k: attribution.get(k)
                        for k in ("process", "role", "phase",
                                  "confidence", "reason")},
                    "attribution_process_ok":
                        (attribution.get("process") or "").rstrip("/")
                        == culprit_url.rstrip("/"),
                    "attribution_phase_ok":
                        attribution.get("phase") == expected_phase,
                    "resolved_in_s": resolved_in,
                    "post_settle_quiet": post_settle_quiet is not None,
                })
                logger.info(
                    "incident %s: detected=%s captured=%s bundle=%s "
                    "attribution=%s/%s ok=%s/%s resolved=%s",
                    name, detected_in, captured_in,
                    record_scenarios[-1]["bundle_id"],
                    attribution.get("process"), attribution.get("phase"),
                    record_scenarios[-1]["attribution_process_ok"],
                    record_scenarios[-1]["attribution_phase_ok"],
                    resolved_in)

            storm.phase = "final"
            await asyncio.sleep(1.0)
            final_fleet = await _obsplane_get(control, obsplane.url,
                                              "/fleet") or {}
            await storm.stop()
            if burst is not None:
                await burst.stop()
            storm_totals = storm.totals()
            control_errors = list(control.errors)
            elapsed = time.monotonic() - t0
    finally:
        if storm is not None and not storm._stopping:
            await storm.stop()
        _stop(procs)

    overhead = None
    if overhead_guard:
        overhead = await _run_overhead_guard(
            users=overhead_users, duration_s=overhead_duration_s,
            num_tokens=num_tokens, platform=platform, log_dir=log_dir,
            startup_timeout_s=startup_timeout_s,
            poll_interval_s=poll_interval_s)

    closed = [s for s in record_scenarios
              if s["detected_in_s"] is not None
              and s["bundles_captured"] == 1
              and not s["bundle_missing"]
              and s["attribution_process_ok"]
              and s["attribution_phase_ok"]
              and s["resolved_in_s"] is not None]
    baseline_storm = storm_totals.get(
        "baseline", {"launched": 0, "ok": 0, "http_5xx": 0,
                     "http_4xx": 0, "shed": 0, "transport_errors": 0,
                     "samples": []})
    return {
        "metric": "fleet flight recorder: injected faults fire their "
                  "alert and yield one complete incident bundle whose "
                  "attribution names the culprit process and phase "
                  "(zero spurious captures on a clean fleet)",
        "value": round(100.0 * len(closed)
                       / max(1, len(record_scenarios)), 1),
        "unit": "% scenarios detected+captured+attributed+resolved",
        "platform": platform,
        "detail": {
            "engine": engine, "engines": engines, "routers": routers,
            "users": users, "routing": routing,
            "duration_s": round(elapsed, 1),
            "window_scale": window_scale,
            "windows_s": {lbl: round(w * window_scale, 2)
                          for lbl, w in WINDOWS.items()},
            "min_events": min_events,
            "baseline_s": baseline_s,
            "detect_timeout_s": round(detect_timeout_s, 1),
            "resolve_timeout_s": round(resolve_timeout_s, 1),
            "settle_s": round(settle_s, 1),
            "poll_interval_s": poll_interval_s,
            "capture_cooldown_s": capture_cooldown_s,
            "max_inflight": max_inflight,
            "burst_users": burst_users,
            "incident_dir": incident_dir,
            "baseline": {
                "storm": baseline_storm,
                "bundles_captured": baseline_incidents,
                "firing_alerts": baseline_firing,
                "process_states": baseline_states,
                "stitch": (baseline_traces.get("stats") or {}),
                "fleet_percentile_classes": sorted(
                    (baseline_traces.get("fleet_percentiles")
                     or {}).keys()),
            },
            "scenarios": record_scenarios,
            "final": {
                "firing_alerts": list(final_fleet.get("firing_alerts",
                                                      [])),
                "bundles_total": len(final_fleet.get("incidents", [])),
                "captures_suppressed": final_fleet.get(
                    "captures_suppressed", 0),
                "stitch": final_fleet.get("chains", {}),
                "scrape_errors_total": final_fleet.get(
                    "scrape_errors_total", {}),
            },
            "storm": storm_totals,
            "control_errors": control_errors,
            "overhead_guard": overhead,
        },
    }


async def _run_overhead_guard(*, users: int, duration_s: float,
                              num_tokens: int, platform: str,
                              log_dir: str, startup_timeout_s: float,
                              poll_interval_s: float,
                              rounds: int = 2) -> dict:
    """The r7 A/B with the obsplane scraping the serving pair vs the
    same host without it. Both sides run ``rounds`` times ALTERNATING
    and each keeps its best round (highest router-side req/s) — the
    multirouter guard convention: single-host ratios swing ±10%
    run-to-run, and a guard that fails on a one-sided fluke teaches
    people to ignore it. Every round's numbers are reported."""
    from production_stack_tpu.loadgen.overhead import run_overhead

    class _Companion:
        def __init__(self, engine_url: str, router_url: str):
            self.engine_url = engine_url
            self.router_url = router_url
            self.proc: Optional[Proc] = None

        async def __aenter__(self):
            self.proc = launch_obsplane(
                [self.router_url], [self.engine_url], free_port(),
                log_dir=log_dir,
                incident_dir=os.path.join(log_dir, "guard-incidents"),
                extra_args=["--poll-interval", str(poll_interval_s),
                            "--scrape-timeout", "2",
                            "--no-capture-on-alert"])
            await wait_healthy(self.proc.url, 30.0)
            return self

        async def __aexit__(self, *exc):
            _stop([self.proc])

    logger.info("incident: overhead guard — %d alternating r7 A/B "
                "rounds with the obsplane scraping the serving pair "
                "at %.1fs vs without...", max(1, rounds),
                poll_interval_s)
    scraped_runs: List[Dict] = []
    plain_runs: List[Dict] = []
    for _ in range(max(1, rounds)):
        scraped_runs.append(await run_overhead(
            engine="fake", users=users, duration_s=duration_s,
            num_tokens=num_tokens, platform=platform, log_dir=log_dir,
            startup_timeout_s=startup_timeout_s,
            companion=_Companion))
        plain_runs.append(await run_overhead(
            engine="fake", users=users, duration_s=duration_s,
            num_tokens=num_tokens, platform=platform, log_dir=log_dir,
            startup_timeout_s=startup_timeout_s))

    def best(runs: List[Dict]) -> Dict:
        return max(runs,
                   key=lambda r: r["detail"]["router"]["req_per_s"])

    def side(run: Dict) -> Dict:
        return {"router_req_per_s":
                run["detail"]["router"]["req_per_s"],
                "errors": run["detail"]["router"]["errors"]
                + run["detail"]["direct"]["errors"]}

    scraped, plain = best(scraped_runs), best(plain_runs)
    return {
        "users": users, "duration_s": duration_s,
        "rounds": max(1, rounds),
        "overhead_ratio": scraped["detail"]["overhead_ratio"],
        "baseline_ratio": plain["detail"]["overhead_ratio"],
        "scraped": side(scraped),
        "baseline": side(plain),
        "all_rounds": {
            "scraped": [{"ratio": r["detail"]["overhead_ratio"],
                         **side(r)} for r in scraped_runs],
            "baseline": [{"ratio": r["detail"]["overhead_ratio"],
                          **side(r)} for r in plain_runs]},
    }


def incident_violations(record: Dict,
                        max_overhead_ratio: Optional[float] = None,
                        min_chain_fraction: float = 0.5) -> List[str]:
    """The drill's pass/fail contract (CLI exits 1 on any)."""
    d = record["detail"]
    out: List[str] = []
    if d["control_errors"]:
        out.append(f"{len(d['control_errors'])} control-plane errors "
                   f"from the rig itself (first: "
                   f"{d['control_errors'][0]})")
    b = d["baseline"]
    if b["storm"]["http_5xx"] or b["storm"]["transport_errors"]:
        out.append(f"baseline storm saw {b['storm']['http_5xx']} 5xx / "
                   f"{b['storm']['transport_errors']} transport errors "
                   f"on a healthy fleet")
    if b["storm"]["ok"] == 0:
        out.append("baseline storm finished zero requests — the drill "
                   "measured nothing")
    if b["bundles_captured"]:
        out.append(f"{b['bundles_captured']} incident bundles captured "
                   f"during the clean baseline (spurious captures)")
    if b["firing_alerts"]:
        out.append(f"alerts firing during the clean baseline: "
                   f"{b['firing_alerts']}")
    stitch = b.get("stitch") or {}
    if not stitch.get("chains_complete"):
        out.append("the online stitcher completed zero chains during "
                   "the baseline — every later gate would pass "
                   "vacuously")
    elif stitch.get("complete_fraction", 0.0) < min_chain_fraction:
        out.append(f"baseline stitched-chain completeness "
                   f"{stitch.get('complete_fraction')} < "
                   f"{min_chain_fraction} — the join is leaking")
    for s in d["scenarios"]:
        if not s["injected_ok"]:
            out.append(f"{s['name']}: fault injection failed")
        if s["detected_in_s"] is None:
            out.append(f"{s['name']}: {s['expected_alert']} never "
                       f"showed on the obsplane's /fleet view within "
                       f"{d['detect_timeout_s']}s (missed detection)")
            continue
        if s["bundles_captured"] == 0:
            out.append(f"{s['name']}: alert fired but no incident "
                       f"bundle was captured")
        elif s["bundles_captured"] > 1:
            out.append(f"{s['name']}: {s['bundles_captured']} bundles "
                       f"captured for one fault (dedup failed)")
        if s["bundle_missing"]:
            out.append(f"{s['name']}: bundle incomplete — "
                       f"{s['bundle_missing']}")
        if not s["attribution_process_ok"]:
            out.append(f"{s['name']}: attribution named "
                       f"{s['attribution'].get('process')!r}, expected "
                       f"{s['expected_process']!r}")
        if not s["attribution_phase_ok"]:
            out.append(f"{s['name']}: attribution named phase "
                       f"{s['attribution'].get('phase')!r}, expected "
                       f"{s['expected_phase']!r}")
        if s["resolved_in_s"] is None:
            out.append(f"{s['name']}: alerts did not resolve within "
                       f"{d['resolve_timeout_s']}s of clearing the "
                       f"fault")
        elif not s.get("post_settle_quiet", True):
            out.append(f"{s['name']}: alerts re-fired and stayed "
                       f"firing through the settle window")
        if not s["cleared_ok"]:
            out.append(f"{s['name']}: fault clear failed")
    f = d["final"]
    if f["firing_alerts"]:
        out.append(f"alerts still firing at drill end: "
                   f"{f['firing_alerts']}")
    expected_bundles = len(d["scenarios"]) \
        + d["baseline"]["bundles_captured"]
    if f["bundles_total"] > expected_bundles:
        out.append(f"{f['bundles_total']} bundles on the obsplane at "
                   f"drill end, expected {expected_bundles} (one per "
                   f"scenario)")
    guard = d.get("overhead_guard")
    if guard is not None and max_overhead_ratio is not None:
        ratio = guard.get("overhead_ratio")
        base = guard.get("baseline_ratio")
        if guard["scraped"]["errors"] or guard["baseline"]["errors"]:
            out.append("overhead guard A/B saw errors — the ratio is "
                       "suspect")
        elif ratio is None:
            out.append("overhead guard ratio unmeasured")
        elif ratio > max_overhead_ratio and \
                (base is None or ratio > base * 1.10) and \
                guard["scraped"]["router_req_per_s"] < \
                0.9 * guard["baseline"]["router_req_per_s"]:
            # three escapes, any one passes (the multirouter guard
            # convention): inside the band, within 10% of the
            # same-host unscraped RATIO, or within 10% of its
            # router-side THROUGHPUT (the ratio's denominator — the
            # direct side — swings with host noise the router and the
            # scraper never see)
            out.append(
                f"overhead ratio {ratio:.2f}x with the obsplane "
                f"scraping exceeds the {max_overhead_ratio:g}x band, "
                f"the same-host unscraped baseline {base:.2f}x + "
                f"10%, and router-side throughput "
                f"{guard['scraped']['router_req_per_s']} req/s is "
                f"more than 10% under the baseline's "
                f"{guard['baseline']['router_req_per_s']}")
    return out
