"""Asyncio load client: fires RequestPlans, measures TTFT / ITL / e2e.

One aiohttp session, unbounded connector (the arrival process is the
concurrency control, not the client pool). Streaming requests parse SSE
chunk arrival times into TTFT and inter-token latencies; non-streaming
(embeddings) record e2e only.

Abort injection: ``execute(plan, abort_after_s=...)`` drops the
connection mid-stream — the soak uses this to prove the stack survives
client disconnects (the engine must abort the orphan generation; later
requests must be unaffected).
"""

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import List, Optional

import aiohttp

from production_stack_tpu.loadgen.workload import RequestPlan


@dataclass
class RequestRecord:
    """Per-request measurement. ``request_id`` is assigned by the
    runner, strictly increasing in launch order — the monotonicity /
    exactly-one-terminal-record invariants hang off it."""
    request_id: int
    session_id: int
    turn_index: int
    kind: str
    launch_time: float = 0.0          # wall clock (epoch)
    finish_time: float = 0.0
    ttft_s: float = 0.0
    e2e_s: float = 0.0
    itl_s: List[float] = field(default_factory=list)   # inter-chunk gaps
    prompt_tokens: int = 0
    output_tokens: int = 0
    status: int = 0                   # HTTP status (0 = transport error)
    error: Optional[str] = None
    aborted: bool = False             # injected disconnect, not a failure
    cancelled: bool = False           # harness-side drain cancel, ditto
    body: str = ""

    @property
    def ok(self) -> bool:
        return self.error is None and not self.aborted \
            and not self.cancelled


def _estimate_tokens(body: dict) -> int:
    msgs = body.get("messages") or []
    n = sum(len(str(m.get("content", "")).split()) for m in msgs)
    if "input" in body:
        n += len(str(body["input"]).split())
    return n


class LoadClient:
    def __init__(self, base_url: str, api_key: Optional[str] = None,
                 request_timeout_s: float = 600.0):
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self.request_timeout_s = request_timeout_s
        self._session: Optional[aiohttp.ClientSession] = None

    async def start(self) -> None:
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0))

    async def close(self) -> None:
        if self._session:
            await self._session.close()
            self._session = None

    async def execute(self, plan: RequestPlan, request_id: int,
                      abort_after_s: Optional[float] = None
                      ) -> RequestRecord:
        rec = RequestRecord(request_id=request_id,
                            session_id=plan.session_id,
                            turn_index=plan.turn_index, kind=plan.kind,
                            launch_time=time.time())
        headers = {"Content-Type": "application/json", **plan.headers}
        # stable request identity: a function of the PLANNED position
        # (session, turn), not the launch-order request_id — so the
        # same logical request carries the same id no matter which
        # worker fires it or when. The fake engine keys per-request
        # service-time/error seeding off this header, which is what
        # makes multi-worker replays reproducible run-to-run.
        headers.setdefault(
            "x-request-id", f"lg-{plan.session_id}.{plan.turn_index}")
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        t0 = time.monotonic()
        try:
            coro = self._run(plan, rec, headers, t0)
            if abort_after_s is not None:
                try:
                    await asyncio.wait_for(coro, timeout=abort_after_s)
                except asyncio.TimeoutError:
                    # the injected disconnect: connection torn down by
                    # wait_for's cancellation, exactly like a vanished
                    # client
                    rec.aborted = True
            else:
                await coro
        except asyncio.CancelledError:
            raise
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError, json.JSONDecodeError) as e:
            # JSONDecodeError: a 200 with a malformed body (truncated
            # proxy response) must be recorded, not crash the run
            rec.error = f"{type(e).__name__}: {e}"
        end = time.monotonic()
        rec.finish_time = time.time()
        rec.e2e_s = end - t0
        if rec.ttft_s == 0.0 and rec.ok:
            rec.ttft_s = rec.e2e_s       # non-streaming: first byte = last
        return rec

    async def _run(self, plan: RequestPlan, rec: RequestRecord,
                   headers: dict, t0: float) -> None:
        timeout = aiohttp.ClientTimeout(total=self.request_timeout_s)
        async with self._session.post(
                f"{self.base_url}{plan.path}", json=plan.body,
                headers=headers, timeout=timeout) as resp:
            rec.status = resp.status
            if resp.status != 200:
                rec.error = (f"HTTP {resp.status}: "
                             f"{(await resp.text())[:200]}")
                return
            if not plan.stream:
                data = await resp.json()
                usage = data.get("usage") or {}
                rec.prompt_tokens = usage.get("prompt_tokens",
                                              _estimate_tokens(plan.body))
                rec.output_tokens = usage.get("completion_tokens", 0)
                return
            chunks: List[str] = []
            usage = None
            last_at: Optional[float] = None
            async for raw_line in resp.content:
                line = raw_line.decode("utf-8", "replace").strip()
                if not line.startswith("data:"):
                    continue
                data = line[5:].strip()
                if data == "[DONE]":
                    break
                try:
                    chunk = json.loads(data)
                except json.JSONDecodeError:
                    continue
                if chunk.get("usage"):
                    usage = chunk["usage"]
                for choice in chunk.get("choices", []):
                    delta = choice.get("delta") or {}
                    if delta.get("content"):
                        now = time.monotonic()
                        if last_at is None:
                            rec.ttft_s = now - t0    # first real token
                        else:
                            rec.itl_s.append(now - last_at)
                        last_at = now
                        chunks.append(delta["content"])
            rec.body = "".join(chunks)
            if usage:
                rec.prompt_tokens = usage.get("prompt_tokens", 0)
                rec.output_tokens = usage.get("completion_tokens",
                                              len(chunks))
            else:
                rec.prompt_tokens = _estimate_tokens(plan.body)
                rec.output_tokens = len(chunks)
