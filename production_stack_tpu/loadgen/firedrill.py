"""Fire-drill mode: prove the in-process SLO engine detects real faults.

The SLO engine (production_stack_tpu/slo.py) is only worth shipping if
it (a) stays silent on a healthy stack and (b) fires the RIGHT alert,
fast, when a real fault is injected — and resolves once the fault
clears. This rig closes that loop with the r8/r9 injection machinery:

1. **Baseline phase** (false-positive gate): a clean closed-loop storm
   against router + N healthy engines; *zero* alerts may fire, nothing
   may be pending, and the storm itself must see zero 5xx.
2. **Scenarios**, each: inject a fault -> keep the storm running ->
   the expected alert must reach ``firing`` within the detection bound
   -> clear the fault -> every alert must resolve within the
   resolution bound. Alerts firing for an SLO the scenario does not
   plausibly affect are *false fires*.

   - ``error_rate``   — partial 500s on every engine (the fake's
     ``error_rate`` override: gradual availability breach, no breaker
     trip) -> ``chat_availability_page``
   - ``engine_down``  — SIGKILL one engine, no goodbye (failover is
     disabled for the drill so the fault is client-visible)
     -> ``chat_availability_page``
   - ``slow_ttft``    — TTFT inflation past the chat TTFT threshold
     -> ``chat_ttft_page`` (rag traffic keeps its own e2e SLO green:
     the per-class separation assertion)
   - ``overload``     — bounded-queue engines + the same storm ->
     relayed/endpoint-cap sheds -> ``shed_rate_page`` (and
     availability must NOT fire: sheds are backpressure, not outage)
   - ``queue_delay``  — /load queue-delay override -> the signal-fed
     ``engine_queue_delay_page``

The drill runs the REAL router with ``--slo-window-scale`` shrinking
the canonical 5m/1h + 30m/6h windows to seconds, and neutralizes the
resilience machinery that exists to HIDE faults from clients
(``--failover-attempts 1``, breaker thresholds out of reach) — the
drill measures detection, not masking. ``--overhead-guard`` runs the
r7 router A/B paired — SLO accounting on (the default) vs ``--no-slo``
on the same host — failing only when the SLO-on ratio breaks the 2.5x
band AND exceeds the same-host baseline by >10% (the absolute ratio is
host-relative; the accounting's marginal cost is not).

Committed record: ``FIREDRILL_r14.json`` via
``benchmarks/run_firedrill.sh``; exit 1 on any missed detection, false
fire, non-resolution, baseline 5xx, or control-plane error.
"""

import asyncio
import json
import os
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_engine,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.slo import WINDOWS, default_slos
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"

# the drill measures the SLO engine, so the layers built to MASK
# faults from clients are turned down: no failover, breaker thresholds
# out of reach (rate trip is `>=`, so 1.01 can never trip), fast
# scrape/eval so the signal SLOs see injected /load overrides quickly
ROUTER_FIREDRILL_ARGS = ["--failover-attempts", "1",
                         "--breaker-threshold", "1000000",
                         "--breaker-failure-rate", "1.01",
                         "--engine-stats-interval", "0.5",
                         "--request-timeout", "20",
                         "--slo-eval-interval", "0.25"]

SCENARIO_NAMES = ("error_rate", "engine_down", "slow_ttft", "overload",
                  "queue_delay")
# scenarios that drive the fake engine's /fault control endpoint; a
# real-engine drill is limited to the process-level one
_FAKE_ONLY = ("error_rate", "slow_ttft", "overload", "queue_delay")


def drill_slo_config(window_scale: float, *, min_events: int = 4,
                     ttft_threshold_s: float = 0.25,
                     rag_e2e_threshold_s: float = 10.0,
                     queue_delay_bound_ms: float = 5000.0) -> dict:
    """The default SLO set with drill-sized latency thresholds (the
    objectives and alert shape stay canonical — only windows scale)."""
    slos = []
    for slo in default_slos():
        row = slo.to_json()
        if slo.name == "chat_ttft":
            row["threshold_s"] = ttft_threshold_s
        elif slo.name == "rag_e2e":
            row["threshold_s"] = rag_e2e_threshold_s
        elif slo.name == "engine_queue_delay":
            row["bound"] = queue_delay_bound_ms
        slos.append(row)
    return {"window_scale": window_scale, "min_events": min_events,
            "slos": slos}


class _StormCounters:
    __slots__ = ("launched", "ok", "http_5xx", "http_4xx", "shed",
                 "transport_errors", "samples")

    def __init__(self):
        self.launched = 0
        self.ok = 0
        self.http_5xx = 0
        self.http_4xx = 0
        self.shed = 0
        self.transport_errors = 0
        self.samples: List[str] = []

    def to_json(self) -> dict:
        return {"launched": self.launched, "ok": self.ok,
                "http_5xx": self.http_5xx, "http_4xx": self.http_4xx,
                "shed": self.shed,
                "transport_errors": self.transport_errors,
                "samples": self.samples}


class _Storm:
    """Continuous closed-loop storm with phase-tagged outcome counters.

    80% of requests are plain chat; 20% carry ``x-slo-class: rag`` so
    the per-class SLO split has two live classes to separate. Sheds
    (429/503 + Retry-After) are counted apart from 5xx — the overload
    scenario's whole point is that sheds burn shed_rate, not
    availability."""

    def __init__(self, url: str, model: str, *, users: int,
                 num_tokens: int, request_timeout_s: float = 20.0):
        self.url = url
        self.model = model
        self.users = users
        self.num_tokens = num_tokens
        self.timeout = aiohttp.ClientTimeout(total=request_timeout_s)
        self.phase = "baseline"
        self.counters: Dict[str, _StormCounters] = {}
        self._tasks: List[asyncio.Task] = []
        self._stopping = False

    def _c(self) -> _StormCounters:
        c = self.counters.get(self.phase)
        if c is None:
            c = self.counters[self.phase] = _StormCounters()
        return c

    async def _one(self, session: aiohttp.ClientSession,
                   i: int, n: int) -> None:
        rag = (n % 5) == 0
        headers = {"Content-Type": "application/json"}
        if rag:
            headers["x-slo-class"] = "rag"
        body = json.dumps({
            "model": self.model,
            "messages": [{"role": "user",
                          "content": f"drill u{i} r{n}"
                                     + (" ctx " * 40 if rag else "")}],
            "max_tokens": self.num_tokens, "stream": False}).encode()
        c = self._c()
        c.launched += 1
        try:
            async with session.post(f"{self.url}{CHAT_PATH}", data=body,
                                    headers=headers,
                                    timeout=self.timeout) as resp:
                await resp.read()
                if resp.status < 400:
                    c.ok += 1
                elif resp.status in (429, 503) and \
                        "Retry-After" in resp.headers:
                    c.shed += 1
                elif resp.status >= 500:
                    c.http_5xx += 1
                    if len(c.samples) < 5:
                        c.samples.append(f"HTTP {resp.status}")
                else:
                    c.http_4xx += 1
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            c.transport_errors += 1
            if len(c.samples) < 5:
                c.samples.append(f"{type(e).__name__}: {e}")

    async def _worker(self, i: int) -> None:
        n = i          # stagger the rag fraction across workers
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as session:
            while not self._stopping:
                await self._one(session, i, n)
                n += self.users
                await asyncio.sleep(0.02)

    def start(self) -> None:
        self._tasks = [asyncio.create_task(self._worker(i))
                       for i in range(self.users)]

    async def stop(self) -> None:
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)

    def totals(self) -> dict:
        return {phase: c.to_json()
                for phase, c in self.counters.items()}


class _Control:
    """The rig's own control plane (fault POSTs, /alerts polls) with
    its error count — 'zero raw 5xx from the rig itself' is a gate."""

    def __init__(self, session: aiohttp.ClientSession):
        self.session = session
        self.errors: List[str] = []

    async def post_fault(self, engine_url: str, body: dict) -> bool:
        try:
            async with self.session.post(
                    f"{engine_url}/fault", json=body,
                    timeout=aiohttp.ClientTimeout(total=3)) as r:
                if r.status == 200:
                    return True
                self.errors.append(
                    f"POST {engine_url}/fault -> HTTP {r.status}")
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            self.errors.append(
                f"POST {engine_url}/fault -> {type(e).__name__}: {e}")
        return False

    async def alerts(self, router_url: str) -> Optional[dict]:
        try:
            async with self.session.get(
                    f"{router_url}/alerts",
                    timeout=aiohttp.ClientTimeout(total=3)) as r:
                if r.status == 200:
                    return await r.json()
                self.errors.append(f"GET /alerts -> HTTP {r.status}")
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            self.errors.append(f"GET /alerts -> {type(e).__name__}: {e}")
        return None


async def _wait_alerts(control: _Control, router_url: str, predicate,
                       timeout_s: float,
                       poll_s: float = 0.3) -> Optional[float]:
    """Poll /alerts until ``predicate(payload)``; seconds it took, or
    None on timeout."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        payload = await control.alerts(router_url)
        if payload is not None and predicate(payload):
            return round(time.monotonic() - t0, 2)
        await asyncio.sleep(poll_s)
    return None


def _fired_totals(payload: dict) -> Dict[str, int]:
    return {a["name"]: a["fired_total"] for a in payload["alerts"]}


def _slo_of(alert_name: str, payload: dict) -> str:
    for a in payload["alerts"]:
        if a["name"] == alert_name:
            return a["slo"]
    return alert_name


async def run_firedrill(*, engines: int = 2,
                        engine: str = "fake",
                        users: int = 8,
                        baseline_s: float = 10.0,
                        window_scale: float = 0.01,
                        scenarios: Optional[List[str]] = None,
                        detect_timeout_s: Optional[float] = None,
                        resolve_timeout_s: Optional[float] = None,
                        num_tokens: int = 4,
                        fake_tokens_per_s: float = 400.0,
                        error_rate: float = 0.5,
                        slow_ttft_arg_s: float = 0.4,
                        ttft_threshold_s: float = 0.25,
                        overload_capacity: int = 1,
                        queue_delay_ms: float = 60000.0,
                        min_events: int = 4,
                        routing: str = "roundrobin",
                        platform: str = "cpu",
                        log_dir: str = "loadgen-logs",
                        startup_timeout_s: float = 420.0,
                        overhead_guard: bool = False,
                        overhead_users: int = 48,
                        overhead_duration_s: float = 10.0) -> Dict:
    """Launch router + N engines with scaled SLO windows, storm, run
    the fault scenarios; return the FIREDRILL record."""
    if scenarios is None:
        scenarios = list(SCENARIO_NAMES)
    if engine != "fake":
        dropped = [s for s in scenarios if s in _FAKE_ONLY]
        if dropped:
            logger.warning("real-engine drill: dropping fake-only "
                           "scenarios %s", dropped)
        scenarios = [s for s in scenarios if s not in _FAKE_ONLY]
    unknown = [s for s in scenarios if s not in SCENARIO_NAMES]
    if unknown:
        raise ValueError(f"unknown scenarios {unknown}; "
                         f"options: {list(SCENARIO_NAMES)}")

    # detection must cover filling the page pair's LONG window past the
    # 14.4x burn (~0.144 bad fraction) plus the scaled for_s hold;
    # resolution covers flushing the SHORT window plus resolve_s
    long_w = WINDOWS["1h"] * window_scale
    short_w = WINDOWS["5m"] * window_scale
    # the slow (ticket) pair's short window is the longest residue a
    # cleared fault leaves behind: resolution and inter-scenario
    # settling are sized to IT, not to the page pair's 5m window
    ticket_short_w = WINDOWS["30m"] * window_scale
    # worst case is a latency fault: the inflation itself collapses
    # the storm's throughput, so bad events fill the long window at a
    # fraction of the clean rate — budget most of the window plus slack
    if detect_timeout_s is None:
        detect_timeout_s = max(15.0, 0.85 * long_w + 10.0)
    if resolve_timeout_s is None:
        resolve_timeout_s = max(10.0, ticket_short_w + 10.0)
    settle_s = ticket_short_w + 1.0

    os.makedirs(log_dir, exist_ok=True)
    slo_cfg = drill_slo_config(window_scale, min_events=min_events,
                               ttft_threshold_s=ttft_threshold_s)
    slo_cfg_path = os.path.join(log_dir, "firedrill_slo_config.json")
    with open(slo_cfg_path, "w") as f:
        json.dump(slo_cfg, f, indent=2)

    procs: List[Proc] = []
    engine_procs: List[Proc] = []
    fake_args = ["--tokens-per-s", str(fake_tokens_per_s),
                 "--num-tokens", str(num_tokens)] \
        if engine == "fake" else None
    record_scenarios: List[dict] = []
    storm = None
    try:
        for _ in range(engines):
            engine_procs.append(launch_engine(
                engine, free_port(), log_dir=log_dir, platform=platform,
                extra_args=fake_args))
        procs.extend(engine_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in engine_procs])
        model = "fake-model" if engine == "fake" else engine
        router = launch_router(
            [e.url for e in engine_procs], model, free_port(),
            routing=routing, log_dir=log_dir,
            extra_args=ROUTER_FIREDRILL_ARGS
            + ["--slo-config", slo_cfg_path])
        procs.append(router)
        await wait_healthy(router.url, 60.0, require_endpoints=engines)

        logger.info("firedrill: %d users vs router + %d %s engines, "
                    "window_scale %g (5m->%.1fs, 1h->%.1fs), "
                    "scenarios %s", users, engines, engine,
                    window_scale, short_w, long_w, scenarios)
        async with aiohttp.ClientSession() as control_session:
            control = _Control(control_session)
            storm = _Storm(router.url, model, users=users,
                           num_tokens=num_tokens)
            storm.start()
            t0 = time.monotonic()

            # ---------------------------------------------- baseline
            await asyncio.sleep(baseline_s)
            baseline_payload = await control.alerts(router.url)
            baseline_fired = (_fired_totals(baseline_payload)
                              if baseline_payload else {})
            baseline_states = {
                a["name"]: a["state"]
                for a in (baseline_payload or {}).get("alerts", [])}
            fired_so_far = dict(baseline_fired)

            # ---------------------------------------------- scenarios
            async def all_engines_fault(body: dict) -> bool:
                oks = await asyncio.gather(*[
                    control.post_fault(e.url, body)
                    for e in engine_procs])
                return all(oks)

            killed: Dict[str, int] = {}     # name -> engine index

            async def inject(name: str) -> bool:
                if name == "error_rate":
                    return await all_engines_fault(
                        {"error_rate": error_rate})
                if name == "slow_ttft":
                    return await all_engines_fault(
                        {"mode": "slow_ttft", "arg": slow_ttft_arg_s,
                         "count": -1})
                if name == "overload":
                    return await all_engines_fault(
                        {"mode": "overload", "arg": overload_capacity})
                if name == "queue_delay":
                    return await all_engines_fault(
                        {"queue_delay_ms": queue_delay_ms})
                if name == "engine_down":
                    victim = engine_procs[0]
                    victim.popen.kill()
                    victim.popen.wait()
                    killed[name] = 0
                    logger.info("firedrill: killed %s", victim.url)
                    return True
                raise AssertionError(name)

            async def clear(name: str) -> bool:
                if name == "engine_down":
                    idx = killed.pop(name)
                    port = int(engine_procs[idx].url.rsplit(":", 1)[1])
                    engine_procs[idx] = launch_engine(
                        engine, port, log_dir=log_dir,
                        platform=platform, extra_args=fake_args)
                    try:
                        # a REAL engine re-pays its XLA warmup here:
                        # the restart gets the same budget as launch
                        await wait_healthy(engine_procs[idx].url,
                                           startup_timeout_s)
                    except TimeoutError:
                        control.errors.append(
                            f"{engine_procs[idx].url} not healthy "
                            f"after restart")
                        return False
                    return True
                if name == "queue_delay":
                    return await all_engines_fault(
                        {"queue_delay_ms": None})
                # mode-clearing POST also resets error_rate
                return await all_engines_fault({"mode": None})

            expected_slo = {
                "error_rate": "chat_availability",
                "engine_down": "chat_availability",
                "slow_ttft": "chat_ttft",
                "overload": "shed_rate",
                "queue_delay": "engine_queue_delay",
            }
            # SLOs a scenario's fault plausibly burns: alerts firing
            # outside this set are false fires. The rag fraction of
            # the storm means availability faults burn BOTH
            # availability SLOs; latency inflation burns only chat's
            # TTFT (rag's 10s e2e bar stays green — the per-class
            # separation the drill asserts).
            affected_slos = {
                "error_rate": {"chat_availability", "rag_availability"},
                "engine_down": {"chat_availability",
                                "rag_availability"},
                "slow_ttft": {"chat_ttft"},
                "overload": {"shed_rate"},
                "queue_delay": {"engine_queue_delay"},
            }

            for name in scenarios:
                expected_alert = f"{expected_slo[name]}_page"
                storm.phase = name
                # outcomes are attributed to the phase a request
                # LAUNCHED in; let requests launched under the previous
                # phase finish before the fault exists, or a tail-end
                # baseline request served through the fault reads as a
                # 5xx on a healthy stack
                await asyncio.sleep(0.5)
                injected_ok = await inject(name)
                injected_at = time.monotonic()

                detected_in = await _wait_alerts(
                    control, router.url,
                    lambda p: expected_alert in p["firing"],
                    detect_timeout_s)
                payload = await control.alerts(router.url) or {}
                firing_at_detect = list(payload.get("firing", []))

                cleared_ok = await clear(name)
                resolved_in = await _wait_alerts(
                    control, router.url,
                    lambda p: not p["firing"],
                    resolve_timeout_s) if detected_in is not None \
                    else None

                # drain the scenario's residue from the slow pair's
                # short window, then require quiet again — a ticket
                # alert whose pending period completes DURING this
                # settle still belongs to THIS scenario's fault, so
                # the fired-totals snapshot for attribution is taken
                # only after the post-settle quiet gate
                storm.phase = "settle"
                await asyncio.sleep(settle_s)
                post_settle_quiet = await _wait_alerts(
                    control, router.url,
                    lambda p: not p["firing"],
                    resolve_timeout_s)

                payload = await control.alerts(router.url) or {}
                totals = _fired_totals(payload) if payload else {}
                fired_delta = {
                    a: totals.get(a, 0) - fired_so_far.get(a, 0)
                    for a in totals
                    if totals.get(a, 0) > fired_so_far.get(a, 0)}
                fired_so_far = totals or fired_so_far
                false_fires = sorted(
                    a for a in fired_delta
                    if _slo_of(a, payload) not in affected_slos[name])

                record_scenarios.append({
                    "name": name,
                    "expected_alert": expected_alert,
                    "injected_ok": injected_ok,
                    "cleared_ok": cleared_ok,
                    "t_inject_s": round(injected_at - t0, 2),
                    "detected_in_s": detected_in,
                    "firing_at_detect": firing_at_detect,
                    "resolved_in_s": resolved_in,
                    "post_settle_quiet": post_settle_quiet is not None,
                    "fired_during": fired_delta,
                    "false_fires": false_fires,
                })
                logger.info(
                    "firedrill %s: detected=%s resolved=%s fired=%s",
                    name, detected_in, resolved_in, fired_delta)

            storm.phase = "final"
            await asyncio.sleep(1.0)
            final_payload = await control.alerts(router.url) or {}
            await storm.stop()
            storm_totals = storm.totals()
            control_errors = list(control.errors)
            elapsed = time.monotonic() - t0
    finally:
        if storm is not None and not storm._stopping:
            await storm.stop()
        _stop(list(engine_procs) + [p for p in procs
                                    if p not in engine_procs])

    overhead = None
    if overhead_guard:
        from production_stack_tpu.loadgen.overhead import run_overhead
        logger.info("firedrill: re-running the r7 overhead A/B — "
                    "SLO accounting on (default) vs --no-slo on the "
                    "same host...")

        async def _side(extra):
            guard = await run_overhead(
                engine="fake", users=overhead_users,
                duration_s=overhead_duration_s, num_tokens=num_tokens,
                platform=platform, log_dir=log_dir,
                startup_timeout_s=startup_timeout_s,
                router_extra_args=extra)
            return {
                "router_req_per_s":
                    guard["detail"]["router"]["req_per_s"],
                "direct_req_per_s":
                    guard["detail"]["direct"]["req_per_s"],
                "overhead_ratio": guard["detail"]["overhead_ratio"],
                "errors": (guard["detail"]["router"]["errors"]
                           + guard["detail"]["direct"]["errors"]),
            }

        # paired same-host A/B: the absolute ratio swings with the
        # host (core count, contention — r7 measured 2.34x, r13 2.47x
        # on their hosts), so the guard also pins the --no-slo
        # baseline from THIS host and bounds the accounting's marginal
        # cost even where the absolute band is out of reach
        slo_on = await _side(None)
        no_slo = await _side(["--no-slo"])
        overhead = {
            **slo_on,
            "no_slo_baseline": no_slo,
            "errors": slo_on["errors"] + no_slo["errors"],
        }

    detected = [s for s in record_scenarios
                if s["detected_in_s"] is not None]
    resolved = [s for s in record_scenarios
                if s["resolved_in_s"] is not None]
    baseline = storm_totals.get("baseline", _StormCounters().to_json())
    return {
        "metric": "SLO fire-drill: injected faults detected by the "
                  "in-process burn-rate alerts and resolved after "
                  "clearing (baseline fires nothing)",
        "value": round(100.0 * len(resolved)
                       / max(1, len(record_scenarios)), 1),
        "unit": "% scenarios detected+resolved",
        "platform": platform,
        "detail": {
            "engine": engine, "engines": engines, "users": users,
            "routing": routing,
            "duration_s": round(elapsed, 1),
            "window_scale": window_scale,
            "windows_s": {lbl: round(w * window_scale, 2)
                          for lbl, w in WINDOWS.items()},
            "min_events": min_events,
            "baseline_s": baseline_s,
            "detect_timeout_s": round(detect_timeout_s, 1),
            "resolve_timeout_s": round(resolve_timeout_s, 1),
            "settle_s": round(settle_s, 1),
            "slo_config": slo_cfg,
            "baseline": {
                "storm": baseline,
                "alerts_fired": {k: v for k, v in baseline_fired.items()
                                 if v},
                "non_inactive": {k: v for k, v in
                                 baseline_states.items()
                                 if v not in ("inactive",)},
            },
            "scenarios": record_scenarios,
            "detected": len(detected),
            "resolved": len(resolved),
            "final_firing": list(final_payload.get("firing", [])),
            "storm": storm_totals,
            "control_errors": control_errors,
            "overhead_guard": overhead,
        },
    }


def firedrill_violations(record: Dict,
                         max_overhead_ratio: Optional[float] = None
                         ) -> List[str]:
    """The drill's pass/fail contract (CLI exits 1 on any)."""
    d = record["detail"]
    out = []
    if d["control_errors"]:
        out.append(f"{len(d['control_errors'])} control-plane errors "
                   f"from the rig itself (first: "
                   f"{d['control_errors'][0]})")
    b = d["baseline"]
    if b["storm"]["http_5xx"] or b["storm"]["transport_errors"]:
        out.append(f"baseline storm saw {b['storm']['http_5xx']} 5xx / "
                   f"{b['storm']['transport_errors']} transport errors "
                   f"on a healthy stack")
    if b["storm"]["ok"] == 0:
        out.append("baseline storm finished zero requests — the drill "
                   "measured nothing")
    if b["alerts_fired"]:
        out.append(f"alerts fired during the clean baseline "
                   f"(false positives): {b['alerts_fired']}")
    if any(s in ("pending", "firing")
           for s in b["non_inactive"].values()):
        out.append(f"alerts pending/firing at the end of the clean "
                   f"baseline: {b['non_inactive']}")
    for s in d["scenarios"]:
        if not s["injected_ok"]:
            out.append(f"{s['name']}: fault injection failed")
        if s["detected_in_s"] is None:
            out.append(f"{s['name']}: {s['expected_alert']} did not "
                       f"fire within {d['detect_timeout_s']}s "
                       f"(missed detection)")
        elif s["resolved_in_s"] is None:
            out.append(f"{s['name']}: alerts did not resolve within "
                       f"{d['resolve_timeout_s']}s of clearing the "
                       f"fault")
        elif not s.get("post_settle_quiet", True):
            out.append(f"{s['name']}: alerts re-fired and stayed "
                       f"firing through the settle window")
        if not s["cleared_ok"]:
            out.append(f"{s['name']}: fault clear failed")
        if s["false_fires"]:
            out.append(f"{s['name']}: false fires on unrelated SLOs: "
                       f"{s['false_fires']}")
    if d["final_firing"]:
        out.append(f"alerts still firing at drill end: "
                   f"{d['final_firing']}")
    guard = d.get("overhead_guard")
    if guard is not None:
        if guard["errors"]:
            out.append(f"overhead guard saw {guard['errors']} errors — "
                       f"the A/B is suspect")
        ratio = guard["overhead_ratio"]
        baseline = (guard.get("no_slo_baseline") or {}).get(
            "overhead_ratio")
        # the band is the contract where the host can reach it; where
        # even --no-slo measures above the band (slower host than the
        # r7/r13 runs), the guard still bounds SLO accounting's
        # marginal cost to <=10% over the same-host baseline
        if max_overhead_ratio and ratio:
            bound = max_overhead_ratio
            if baseline:
                bound = max(bound, baseline * 1.10)
            if ratio > bound:
                out.append(
                    f"overhead ratio {ratio:.2f}x with SLO accounting "
                    f"enabled exceeds the {max_overhead_ratio:g}x band "
                    f"and the same-host --no-slo baseline "
                    f"({baseline if baseline else '?'}x) by more "
                    f"than 10%")
    return out
