"""Aggregation and reporting.

Two committed shapes:

- BENCH-schema JSON (the repo's existing perf record format, bench.py):
  ``{"metric", "value", "unit", "platform", "detail": {...}}`` — one
  headline number plus full methodology in ``detail``.
- ``SCALEOUT_*.json`` — the replicas → aggregate tokens/s curve with
  per-point summaries and scaling efficiency vs N=1 (BASELINE config 2).
"""

import json
import platform as _platform
import time
from typing import Dict, List, Optional, Sequence

from production_stack_tpu.loadgen.client import RequestRecord


def percentile(values: Sequence[float], p: float) -> float:
    """Nearest-rank percentile on an unsorted sequence; 0.0 if empty."""
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
    return s[idx]


def aggregate(records: List[RequestRecord],
              window_start: Optional[float] = None,
              window_end: Optional[float] = None) -> Dict:
    """Summary metrics over records launched inside the window
    (semantics match benchmarks/multi_round_qa/summary.py: offered QPS
    counts launches; throughput counts finished tokens over the wall
    window)."""
    if window_start is None:
        window_start = min((r.launch_time for r in records), default=0.0)
    if window_end is None:
        window_end = max((r.finish_time for r in records),
                         default=window_start)
    in_window = [r for r in records
                 if window_start <= r.launch_time <= window_end]
    ok = [r for r in in_window if r.ok and r.finish_time <= window_end]
    errors = [r for r in in_window if r.error is not None]
    aborted = [r for r in in_window if r.aborted]
    cancelled = [r for r in in_window if r.cancelled]
    duration = max(window_end - window_start, 1e-9)
    ttfts = [r.ttft_s for r in ok]
    e2es = [r.e2e_s for r in ok]
    itls = [g for r in ok for g in r.itl_s]
    kinds: Dict[str, int] = {}
    for r in in_window:
        kinds[r.kind] = kinds.get(r.kind, 0) + 1
    # first few distinct error strings: a run that produced only zeros
    # must explain itself in its own report
    error_samples: List[str] = []
    seen = set()
    for r in errors:
        key = (r.error or "")[:120]
        if key not in seen:
            seen.add(key)
            error_samples.append(key)
        if len(error_samples) >= 5:
            break
    return {
        "duration_s": round(duration, 3),
        "launched": len(in_window),
        "finished": len(ok),
        "errors": len(errors),
        "http_5xx": len([r for r in errors if r.status >= 500]),
        "aborted_injected": len(aborted),
        "cancelled_by_harness": len(cancelled),
        "offered_qps": round(len(in_window) / duration, 4),
        "processed_qps": round(len(ok) / duration, 4),
        "input_tokens_per_s": round(
            sum(r.prompt_tokens for r in ok) / duration, 2),
        "output_tokens_per_s": round(
            sum(r.output_tokens for r in ok) / duration, 2),
        "total_output_tokens": sum(r.output_tokens for r in ok),
        "ttft_s": {"mean": round(sum(ttfts) / len(ttfts), 4) if ttfts
                   else 0.0,
                   "p50": round(percentile(ttfts, 50), 4),
                   "p90": round(percentile(ttfts, 90), 4),
                   "p99": round(percentile(ttfts, 99), 4)},
        "itl_s": {"mean": round(sum(itls) / len(itls), 4) if itls
                  else 0.0,
                  "p99": round(percentile(itls, 99), 4)},
        "e2e_s": {"p50": round(percentile(e2es, 50), 4),
                  "p99": round(percentile(e2es, 99), 4)},
        "requests_by_kind": kinds,
        "error_samples": error_samples,
    }


def bench_schema(metric: str, agg: Dict, *, platform: str = "cpu",
                 detail: Optional[Dict] = None) -> Dict:
    """Wrap an aggregate into the BENCH_*.json record shape so driver
    tooling that scrapes bench.py output can scrape loadgen output
    unchanged."""
    d = dict(agg)
    d.update(detail or {})
    return {
        "metric": metric,
        "value": agg["output_tokens_per_s"],
        "unit": "out_tok/s",
        "platform": platform,
        "detail": d,
    }


def scaleout_record(*, engine: str, routing: str, workload: str,
                    points: List[Dict], platform: str = "cpu",
                    notes: str = "") -> Dict:
    """The SCALEOUT_*.json shape: one point per replica count, each
    carrying its full aggregate; efficiency is tokens/s relative to
    perfect linear scaling from the N=1 point."""
    base = next((p for p in points if p["replicas"] == 1), None)
    for p in points:
        if base and base["output_tokens_per_s"] > 0:
            ideal = base["output_tokens_per_s"] * p["replicas"]
            p["scaling_efficiency"] = round(
                p["output_tokens_per_s"] / ideal, 4)
        else:
            p["scaling_efficiency"] = None
    return {
        "metric": "aggregate output tokens/s vs replicas "
                  "(DP scale-out through the router)",
        "engine": engine,
        "routing": routing,
        "workload": workload,
        "platform": platform,
        "host": _platform.node(),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "points": points,
        "notes": notes,
    }


def write_json(path: str, obj: Dict) -> str:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2)
        f.write("\n")
    return path
