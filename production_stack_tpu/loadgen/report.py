"""Aggregation and reporting.

Two committed shapes:

- BENCH-schema JSON (the repo's existing perf record format, bench.py):
  ``{"metric", "value", "unit", "platform", "detail": {...}}`` — one
  headline number plus full methodology in ``detail``.
- ``SCALEOUT_*.json`` — the replicas → aggregate tokens/s curve with
  per-point summaries and scaling efficiency vs N=1 (BASELINE config 2).
"""

import json
import platform as _platform
import time
from typing import Dict, Iterable, List, Optional, Sequence

from production_stack_tpu.loadgen.client import RequestRecord


def percentile(values: Sequence[float], p: float) -> float:
    """Nearest-rank percentile on an unsorted sequence; 0.0 if empty."""
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
    return s[idx]


class LatencyRecordSet:
    """Mergeable raw-sample latency set: merge-then-quantile.

    The one legal way to combine latency measurements from multiple
    phases or workers is to merge the RAW samples and take quantiles of
    the union — averaging per-worker percentiles is statistically
    meaningless (the mean of two p99s is not the p99 of anything).
    This class is the enforcement point: workers ship their samples
    (``to_dict``/``from_dict`` round-trip through worker JSONL),
    coordinators ``merge`` and only then read ``quantiles``.

    Samples accumulate via ``add``/``add_samples`` (streaming: a
    coordinator can fold worker record files in one pass without
    holding RequestRecords), and quantiles are computed on demand with
    the same nearest-rank ``percentile`` every committed record uses.
    """

    def __init__(self) -> None:
        self.ttft_s: List[float] = []
        self.itl_s: List[float] = []
        self.e2e_s: List[float] = []
        self.count = 0                   # ok records folded in

    @classmethod
    def from_records(cls, records: Iterable[RequestRecord]
                     ) -> "LatencyRecordSet":
        s = cls()
        for r in records:
            s.add(r)
        return s

    def add(self, rec: RequestRecord) -> None:
        """Fold one OK record's raw samples in (errors/aborts carry no
        latency truth and are counted elsewhere)."""
        if not rec.ok:
            return
        self.count += 1
        self.ttft_s.append(rec.ttft_s)
        self.e2e_s.append(rec.e2e_s)
        self.itl_s.extend(rec.itl_s)

    def add_samples(self, *, ttft_s: Sequence[float] = (),
                    itl_s: Sequence[float] = (),
                    e2e_s: Sequence[float] = (), count: int = 0) -> None:
        self.ttft_s.extend(ttft_s)
        self.itl_s.extend(itl_s)
        self.e2e_s.extend(e2e_s)
        self.count += count

    def merge(self, other: "LatencyRecordSet") -> "LatencyRecordSet":
        """Fold another worker/phase's raw samples in (in place)."""
        self.add_samples(ttft_s=other.ttft_s, itl_s=other.itl_s,
                         e2e_s=other.e2e_s, count=other.count)
        return self

    def quantiles(self) -> Dict:
        """The percentile sub-dicts every summary/record shape carries —
        computed from the merged raw samples, never from per-shard
        percentiles."""
        ttfts, itls, e2es = self.ttft_s, self.itl_s, self.e2e_s
        return {
            "ttft_s": {"mean": round(sum(ttfts) / len(ttfts), 4)
                       if ttfts else 0.0,
                       "p50": round(percentile(ttfts, 50), 4),
                       "p90": round(percentile(ttfts, 90), 4),
                       "p99": round(percentile(ttfts, 99), 4)},
            "itl_s": {"mean": round(sum(itls) / len(itls), 4)
                      if itls else 0.0,
                      "p99": round(percentile(itls, 99), 4)},
            "e2e_s": {"p50": round(percentile(e2es, 50), 4),
                      "p99": round(percentile(e2es, 99), 4)},
        }

    def to_dict(self) -> Dict:
        """Raw-sample transport shape (worker -> coordinator). Ships
        samples, not summaries, so the receiver can merge-then-quantile."""
        return {"count": self.count,
                "ttft_s": [round(v, 6) for v in self.ttft_s],
                "itl_s": [round(v, 6) for v in self.itl_s],
                "e2e_s": [round(v, 6) for v in self.e2e_s]}

    @classmethod
    def from_dict(cls, d: Dict) -> "LatencyRecordSet":
        s = cls()
        s.add_samples(ttft_s=d.get("ttft_s", ()),
                      itl_s=d.get("itl_s", ()),
                      e2e_s=d.get("e2e_s", ()),
                      count=int(d.get("count", 0)))
        return s


def aggregate(records: List[RequestRecord],
              window_start: Optional[float] = None,
              window_end: Optional[float] = None) -> Dict:
    """Summary metrics over records launched inside the window
    (semantics match benchmarks/multi_round_qa/summary.py: offered QPS
    counts launches; throughput counts finished tokens over the wall
    window)."""
    if window_start is None:
        window_start = min((r.launch_time for r in records), default=0.0)
    if window_end is None:
        window_end = max((r.finish_time for r in records),
                         default=window_start)
    in_window = [r for r in records
                 if window_start <= r.launch_time <= window_end]
    ok = [r for r in in_window if r.ok and r.finish_time <= window_end]
    errors = [r for r in in_window if r.error is not None]
    aborted = [r for r in in_window if r.aborted]
    cancelled = [r for r in in_window if r.cancelled]
    duration = max(window_end - window_start, 1e-9)
    latencies = LatencyRecordSet.from_records(ok)
    kinds: Dict[str, int] = {}
    for r in in_window:
        kinds[r.kind] = kinds.get(r.kind, 0) + 1
    # first few distinct error strings: a run that produced only zeros
    # must explain itself in its own report
    error_samples: List[str] = []
    seen = set()
    for r in errors:
        key = (r.error or "")[:120]
        if key not in seen:
            seen.add(key)
            error_samples.append(key)
        if len(error_samples) >= 5:
            break
    return {
        "duration_s": round(duration, 3),
        "launched": len(in_window),
        "finished": len(ok),
        "errors": len(errors),
        "http_5xx": len([r for r in errors if r.status >= 500]),
        "aborted_injected": len(aborted),
        "cancelled_by_harness": len(cancelled),
        "offered_qps": round(len(in_window) / duration, 4),
        "processed_qps": round(len(ok) / duration, 4),
        "input_tokens_per_s": round(
            sum(r.prompt_tokens for r in ok) / duration, 2),
        "output_tokens_per_s": round(
            sum(r.output_tokens for r in ok) / duration, 2),
        "total_output_tokens": sum(r.output_tokens for r in ok),
        **latencies.quantiles(),
        "requests_by_kind": kinds,
        "error_samples": error_samples,
    }


def bench_schema(metric: str, agg: Dict, *, platform: str = "cpu",
                 detail: Optional[Dict] = None) -> Dict:
    """Wrap an aggregate into the BENCH_*.json record shape so driver
    tooling that scrapes bench.py output can scrape loadgen output
    unchanged."""
    d = dict(agg)
    d.update(detail or {})
    return {
        "metric": metric,
        "value": agg["output_tokens_per_s"],
        "unit": "out_tok/s",
        "platform": platform,
        "detail": d,
    }


def scaleout_record(*, engine: str, routing: str, workload: str,
                    points: List[Dict], platform: str = "cpu",
                    notes: str = "") -> Dict:
    """The SCALEOUT_*.json shape: one point per replica count, each
    carrying its full aggregate; efficiency is tokens/s relative to
    perfect linear scaling from the N=1 point."""
    base = next((p for p in points if p["replicas"] == 1), None)
    for p in points:
        if base and base["output_tokens_per_s"] > 0:
            ideal = base["output_tokens_per_s"] * p["replicas"]
            p["scaling_efficiency"] = round(
                p["output_tokens_per_s"] / ideal, 4)
        else:
            p["scaling_efficiency"] = None
    return {
        "metric": "aggregate output tokens/s vs replicas "
                  "(DP scale-out through the router)",
        "engine": engine,
        "routing": routing,
        "workload": workload,
        "platform": platform,
        "host": _platform.node(),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "points": points,
        "notes": notes,
    }


def write_json(path: str, obj: Dict) -> str:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2)
        f.write("\n")
    return path
