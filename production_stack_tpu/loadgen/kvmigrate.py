"""kvmigrate mode: the kvplane closed loop — migration storm + codecs.

Two experiments, one record (``KVMIGRATE_*.json``), one pass/fail
contract (``kvmigrate_violations`` -> CLI exit 1):

**Fragmentation storm (tentpole pillar 1).** Two fake engines behind
the real router (roundrobin — every replica keeps taking traffic), one
injected into the fragmented admission-failure regime via ``POST
/fault {"kv_pool": ...}`` (free capacity exists fleet-wide, but replica
A's pool cannot seat a request). A request storm runs twice:

- **migration ON**: the real kvplane planner process polls the census,
  sees A's ``alloc_failures_fragmented`` rising, and executes the
  migrate_out -> warm -> rehome hand-off. Gate: A's fragmented-failure
  RATE in the second half of the storm collapses to ~0 (the planner
  needs one failure delta to trigger, so the first half is allowed to
  hurt), while the fleet's aggregate block count stays constant —
  migration moves memory pressure, it must not mint capacity.
- **migration OFF** (anti-vacuity): the identical storm with no
  planner must KEEP failing — if the OFF phase passes the ON gate, the
  rig is measuring nothing and the record is rejected.

Failures are measured at the ENGINE (census counter deltas), not the
client: the router may retry a refused admission elsewhere, which is
good for users and useless for measuring pool health.

**Codec capacity (tentpole pillar 2).** The r11 kvshare storm re-run
twice with fake engines publishing deterministic pseudo-KV through the
REAL tier codecs (``--kv-codec raw`` vs ``int4``, kvcache/codec.py).
Gates: the int4 phase's logical-bytes / cache-server-physical-bytes
ratio >= 2.0 (>= 2x tier capacity at equal logical bytes), the raw
phase's ratio stays ~1 (sanity: the accounting is honest), hit TTFT
within tolerance of raw, and the kvshare hit-rate floor still holds.

Engines: fake only — the storm drives the injected census model
(engine-free tier-1), the codec phase drives REAL codec encode/decode
against a REAL cache server.
"""

import asyncio
import json
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen import kvshare
from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_engine,
                                                       launch_kvplane,
                                                       launch_router,
                                                       wait_healthy)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"

# injected census: A is fragmented (free capacity exists — 4 blocks —
# but below the 16-block request demand), B holds the fleet's headroom
FRAGMENTED_POOL = {"num_blocks": 256, "free": 4, "active": 252,
                   "cached": 0, "blocks_per_request": 16,
                   "free_contiguity": 0.08}
HEALTHY_POOL = {"num_blocks": 256, "free": 224, "active": 32,
                "cached": 0, "blocks_per_request": 16,
                "free_contiguity": 0.9}


async def _post_json(http: aiohttp.ClientSession, url: str,
                     body: dict, timeout_s: float = 10.0) -> dict:
    async with http.post(url, json=body,
                         timeout=aiohttp.ClientTimeout(
                             total=timeout_s)) as resp:
        return await resp.json()


async def _census(http: aiohttp.ClientSession, url: str) -> Dict:
    async with http.get(f"{url}/load",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
        return (await r.json()).get("kv_pool") or {}


async def _storm(router_url: str, *, duration_s: float, workers: int,
                 model: str = "fake-model") -> Dict:
    """Closed-loop chat storm through the router; counts client-side
    outcomes (engine-side truth comes from the census deltas)."""
    stop_at = time.monotonic() + duration_s
    counts = {"requests": 0, "ok": 0, "rejected_503": 0, "errors": 0}

    async def worker(i: int) -> None:
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as http:
            r = 0
            while time.monotonic() < stop_at:
                r += 1
                body = {"model": model,
                        "messages": [{"role": "user",
                                      "content": f"storm-{i}-{r}"}],
                        "max_tokens": 4}
                counts["requests"] += 1
                try:
                    async with http.post(
                            f"{router_url}{CHAT_PATH}", json=body,
                            timeout=aiohttp.ClientTimeout(
                                total=10)) as resp:
                        await resp.read()
                        if resp.status == 200:
                            counts["ok"] += 1
                        elif resp.status == 503:
                            counts["rejected_503"] += 1
                        else:
                            counts["errors"] += 1
                except (aiohttp.ClientError, ConnectionError, OSError,
                        asyncio.TimeoutError):
                    counts["errors"] += 1
                await asyncio.sleep(0.02)

    await asyncio.gather(*[worker(i) for i in range(workers)])
    return counts


async def _run_storm_phase(*, migration: bool, duration_s: float,
                           workers: int, poll_interval_s: float,
                           log_dir: str, routing: str = "roundrobin",
                           startup_timeout_s: float = 60.0) -> Dict:
    """One storm phase: fragmented A + healthy B behind the router,
    with (ON) or without (OFF) the kvplane planner process."""
    procs: List[Proc] = []
    tag = "on" if migration else "off"
    try:
        extra = ["--num-tokens", "4", "--tokens-per-s", "0"]
        eng_a = launch_engine("fake", free_port(),
                              log_dir=f"{log_dir}/{tag}",
                              extra_args=extra)
        eng_b = launch_engine("fake", free_port(),
                              log_dir=f"{log_dir}/{tag}",
                              extra_args=extra)
        procs += [eng_a, eng_b]
        await asyncio.gather(wait_healthy(eng_a.url, startup_timeout_s),
                             wait_healthy(eng_b.url, startup_timeout_s))
        router = launch_router([eng_a.url, eng_b.url], "fake-model",
                               free_port(), routing=routing,
                               log_dir=f"{log_dir}/{tag}",
                               extra_args=["--engine-stats-interval",
                                           "1"])
        procs.append(router)
        await wait_healthy(router.url, 60.0, require_endpoints=2)

        async with aiohttp.ClientSession() as http:
            await _post_json(http, f"{eng_a.url}/fault",
                             {"kv_pool": dict(FRAGMENTED_POOL)})
            await _post_json(http, f"{eng_b.url}/fault",
                             {"kv_pool": dict(HEALTHY_POOL)})

            planner_status = None
            if migration:
                planner = launch_kvplane(
                    [eng_a.url, eng_b.url], free_port(),
                    log_dir=f"{log_dir}/{tag}", router_url=router.url,
                    extra_args=["--poll-interval",
                                str(poll_interval_s),
                                "--move-cooldown", "1.0"])
                procs.append(planner)
                await wait_healthy(planner.url, 30.0)

            census_before = {"a": await _census(http, eng_a.url),
                             "b": await _census(http, eng_b.url)}
            half = duration_s / 2.0
            first = await _storm(router.url, duration_s=half,
                                 workers=workers)
            census_mid = {"a": await _census(http, eng_a.url),
                          "b": await _census(http, eng_b.url)}
            second = await _storm(router.url, duration_s=half,
                                  workers=workers)
            census_after = {"a": await _census(http, eng_a.url),
                            "b": await _census(http, eng_b.url)}
            if migration:
                async with http.get(
                        f"{planner.url}/status",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    planner_status = await r.json()
    finally:
        _stop(procs)

    def frag(census: Dict) -> int:
        return sum(c.get("alloc_failures_fragmented", 0)
                   for c in census.values())

    def allocs(census: Dict) -> int:
        return sum(c.get("allocs", 0) for c in census.values())

    halves = []
    for before, after, storm in ((census_before, census_mid, first),
                                 (census_mid, census_after, second)):
        d_frag = frag(after) - frag(before)
        d_allocs = allocs(after) - allocs(before)
        halves.append({
            "alloc_attempts": d_allocs,
            "fragmented_failures": d_frag,
            "failure_rate": round(d_frag / d_allocs, 4)
            if d_allocs else 0.0,
            "client": storm,
        })
    return {
        "migration": migration,
        "halves": halves,
        "census_before": census_before,
        "census_after": census_after,
        "aggregate_blocks_before": sum(
            c.get("num_blocks", 0) for c in census_before.values()),
        "aggregate_blocks_after": sum(
            c.get("num_blocks", 0) for c in census_after.values()),
        "planner": {k: planner_status.get(k) for k in
                    ("moves", "moved_blocks", "warmed_chunks",
                     "decisions", "move_errors", "recent_moves")}
        if planner_status else None,
    }


async def run_kvmigrate(*, storm_duration_s: float = 8.0,
                        storm_workers: int = 4,
                        poll_interval_s: float = 0.3,
                        codec: str = "int4",
                        sessions: int = 4,
                        rounds: int = 6,
                        seed: int = 0,
                        platform: str = "cpu",
                        log_dir: str = "loadgen-logs/kvmigrate",
                        startup_timeout_s: float = 60.0) -> Dict:
    """Run storm ON, storm OFF, and the raw-vs-codec kvshare re-run;
    return the KVMIGRATE record."""
    logger.info("kvmigrate: fragmentation storm with migration ON "
                "(%.0fs, %d workers)...", storm_duration_s,
                storm_workers)
    storm_on = await _run_storm_phase(
        migration=True, duration_s=storm_duration_s,
        workers=storm_workers, poll_interval_s=poll_interval_s,
        log_dir=log_dir, startup_timeout_s=startup_timeout_s)
    logger.info("kvmigrate: anti-vacuity storm with migration OFF...")
    storm_off = await _run_storm_phase(
        migration=False, duration_s=storm_duration_s,
        workers=storm_workers, poll_interval_s=poll_interval_s,
        log_dir=log_dir, startup_timeout_s=startup_timeout_s)

    kv_chunk_chars = 64
    kv_bytes_per_char = 256  # fake_engine --kv-bytes-per-char default
    share_kwargs = dict(engines=2, engine="fake", sessions=sessions,
                        rounds=rounds, system_chars=384,
                        round_chars=160, num_tokens=8,
                        prefill_ms_per_char=0.5,
                        kv_chunk_chars=kv_chunk_chars,
                        routing="session", seed=seed,
                        platform=platform,
                        startup_timeout_s=startup_timeout_s)
    logger.info("kvmigrate: codec phase — raw tier baseline...")
    phase_raw = await kvshare._run_phase(
        cached=True, kv_codec="raw",
        log_dir=f"{log_dir}/codec-raw", **share_kwargs)
    logger.info("kvmigrate: codec phase — %s tier...", codec)
    phase_codec = await kvshare._run_phase(
        cached=True, kv_codec=codec,
        log_dir=f"{log_dir}/codec-{codec}", **share_kwargs)

    # capacity ratio = logical KV bytes resident / physical cache
    # bytes. Logical comes from the cache server's CHUNK COUNT times
    # the per-chunk logical size (each resident chunk stands in for
    # kv_chunk_chars * kv_bytes_per_char of bf16-equivalent KV) —
    # counting resident chunks, not publish traffic, so a digest both
    # replicas raced to publish is never double-counted.
    chunk_logical_bytes = kv_chunk_chars * kv_bytes_per_char

    def capacity_ratio(phase: Dict) -> Optional[float]:
        stats = phase.get("cache_server") or {}
        physical = stats.get("bytes")
        count = stats.get("count")
        if not physical or not count:
            return None
        return round(count * chunk_logical_bytes / physical, 3)

    on_half2 = storm_on["halves"][1]
    off_half2 = storm_off["halves"][1]
    record = {
        "metric": "kvplane migration storm: fragmented-admission "
                  "failure rate (second half, migration ON vs OFF) + "
                  "compressed-tier capacity ratio vs raw at equal "
                  "logical bytes",
        "value": round(100.0 * on_half2["failure_rate"], 2),
        "unit": "% fragmented-failure rate (migration ON, 2nd half)",
        "platform": platform,
        "detail": {
            "storm": {
                "duration_s": storm_duration_s,
                "workers": storm_workers,
                "poll_interval_s": poll_interval_s,
                "pools": {"fragmented": FRAGMENTED_POOL,
                          "healthy": HEALTHY_POOL},
                "on": storm_on,
                "off": storm_off,
            },
            "codec": {
                "name": codec,
                "sessions": sessions, "rounds": rounds, "seed": seed,
                "chunk_logical_bytes": chunk_logical_bytes,
                "raw": phase_raw,
                "compressed": phase_codec,
                "capacity_ratio": {
                    "raw": capacity_ratio(phase_raw),
                    codec: capacity_ratio(phase_codec)},
                # the gate compares MEDIANS: the per-round TTFT tail
                # is scheduling/transfer noise on a single host, and a
                # couple of outlier rounds should not fail a codec
                # whose typical hit is byte-for-byte as fast
                "ttft_followup_p50_ms": {
                    "raw": (phase_raw.get("ttft_followup")
                            or {}).get("p50"),
                    codec: (phase_codec.get("ttft_followup")
                            or {}).get("p50")},
                "ttft_followup_mean_ms": {
                    "raw": (phase_raw.get("ttft_followup")
                            or {}).get("mean"),
                    codec: (phase_codec.get("ttft_followup")
                            or {}).get("mean")},
            },
        },
    }
    logger.info(
        "kvmigrate: ON 2nd-half failure rate %.1f%% (OFF %.1f%%), "
        "capacity ratio raw %s vs %s %s",
        100 * on_half2["failure_rate"],
        100 * off_half2["failure_rate"],
        record["detail"]["codec"]["capacity_ratio"]["raw"],
        codec, record["detail"]["codec"]["capacity_ratio"][codec])
    return record


def kvmigrate_violations(record: Dict,
                         max_on_failure_rate: float = 0.02,
                         min_off_failure_rate: float = 0.2,
                         min_capacity_ratio: float = 2.0,
                         ttft_tolerance: float = 0.25,
                         min_hit_rate: float = 0.6) -> List[str]:
    """The kvmigrate pass/fail contract (CLI exits 1 on any
    violation)."""
    out: List[str] = []
    d = record["detail"]
    storm = d["storm"]
    on, off = storm["on"], storm["off"]

    on2 = on["halves"][1]
    if not on2["alloc_attempts"]:
        out.append("migration-ON second half saw no allocation "
                   "attempts — the storm never exercised the pool")
    elif on2["failure_rate"] > max_on_failure_rate:
        out.append(
            f"migration ON did not erase the fragmented regime: "
            f"second-half failure rate {on2['failure_rate']:.1%} > "
            f"{max_on_failure_rate:.0%} "
            f"({on2['fragmented_failures']}/{on2['alloc_attempts']})")
    planner = on.get("planner") or {}
    if not planner.get("moves"):
        out.append("planner executed no migrations in the ON phase — "
                   "any recovery did not come from kvplane")
    if planner.get("move_errors"):
        out.append(f"{planner['move_errors']} planner move errors in "
                   f"the ON phase")

    off2 = off["halves"][1]
    if off2["failure_rate"] < min_off_failure_rate:
        out.append(
            f"anti-vacuity breach: with migration OFF the second-half "
            f"failure rate was {off2['failure_rate']:.1%} < "
            f"{min_off_failure_rate:.0%} — the storm does not actually "
            f"depend on migration")

    for phase in (on, off):
        if phase["aggregate_blocks_before"] != \
                phase["aggregate_blocks_after"]:
            out.append(
                f"aggregate HBM changed during the "
                f"{'ON' if phase['migration'] else 'OFF'} storm: "
                f"{phase['aggregate_blocks_before']} -> "
                f"{phase['aggregate_blocks_after']} blocks — "
                f"migration must move capacity, not mint it")
        for half in phase["halves"]:
            if half["client"]["errors"]:
                out.append(f"{half['client']['errors']} non-503 client "
                           f"errors in a storm half")

    codec = d["codec"]
    name = codec["name"]
    for phase_name in ("raw", "compressed"):
        if codec[phase_name]["errors"]:
            out.append(f"{codec[phase_name]['errors']} errors in the "
                       f"codec {phase_name} phase")
    ratios = codec["capacity_ratio"]
    if ratios.get(name) is None:
        out.append("compressed-phase capacity ratio unmeasured (cache "
                   "server stats or bytes_saved missing)")
    elif ratios[name] < min_capacity_ratio:
        out.append(f"codec {name} capacity ratio "
                   f"{ratios[name]:.2f}x < {min_capacity_ratio:.1f}x")
    if ratios.get("raw") is not None and \
            not (0.85 <= ratios["raw"] <= 1.10):
        out.append(f"raw capacity ratio {ratios['raw']:.2f}x outside "
                   f"[0.85, 1.10] — the logical/physical accounting "
                   f"is off, the codec gate is not trustworthy")
    ttft = codec["ttft_followup_p50_ms"]
    if ttft.get("raw") is None or ttft.get(name) is None:
        out.append("codec TTFT comparison missing a side")
    elif ttft[name] > ttft["raw"] * (1.0 + ttft_tolerance):
        out.append(f"compressed-tier hit TTFT p50 {ttft[name]:.1f}ms "
                   f"exceeds raw {ttft['raw']:.1f}ms by more than "
                   f"{ttft_tolerance:.0%}")
    if codec["compressed"]["hit_rate"] <= min_hit_rate:
        out.append(f"codec-phase hit rate "
                   f"{codec['compressed']['hit_rate']:.1%} <= "
                   f"{min_hit_rate:.0%} — quantized chunks are not "
                   f"being consumed")
    return out
