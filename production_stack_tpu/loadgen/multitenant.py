"""Multitenant storm: named pools, LoRA churn, noisy-neighbor containment.

The heterogeneous-fleet closed loop (ISSUE 19). One router fronts TWO
named pools (``--pools``): pool-a serves ``model-a`` (plus dynamically
loaded LoRA adapters), pool-b serves ``model-b``. Each pool is owned by
its own ``LocalProcessActuator`` publishing membership through a shared
``PoolConfigWriter`` (one dynamic-config document, N writers), and its
own ``Autoscaler`` policy loop — both loops share ONE
``ActuationBudget`` so simultaneous decisions serialize instead of
double-spending the host.

Phases:

1. **baseline** — mixed model-a/model-b traffic; per-model goodput is
   the reference for the interference gate.
2. **churn** — same mix while pool-a goes through the wringer: LoRA
   adapters are loaded on every pool-a engine, traffic moves onto the
   adapter id once the router's ``/v1/models`` aggregates it
   fleet-wide, then the adapter is evicted; one engine gets an
   ``adapter_load_error`` fault injected and the rig asserts the load
   answers a structured 503 + ``Retry-After`` while the router's
   healthy-endpoint count is untouched (shed ≠ sick at the adapter
   stage — the r9 contract); finally one pool-a engine is SIGKILLed
   mid-storm. Pool-b must not notice any of it.
3. **noisy** — tenants ``acme``/``beta``/``gamma`` share one QoS tier
   on model-b; acme bursts far past the per-tenant bucket
   (``--qos-tenant-rate``) while its peers stay under it.
4. **surge** — heavy legitimate load on BOTH models forces each pool's
   policy loop to scale up through the shared budget.

The acceptance contract (``multitenant_violations``; CLI exits 1 on
any):

- **routing is 100% model-correct** — every ok response's
  ``x-engine-id`` belongs to the pool that serves the requested model
  (joined against the config writer's cumulative membership history),
  and zero 404s: the fake engines run ``--strict-models``, so a
  misrouted request is observable, not silently absorbed;
- **zero cross-pool interference** — pool-b goodput during pool-a's
  churn+kill phase holds >= ``interference_floor`` of baseline with
  zero 5xx/transport errors;
- **noisy-neighbor containment** — the bursting tenant is shed >=
  ``min_noisy_shed`` of its attempts while each same-tier peer keeps
  ok-fraction >= ``peer_floor``;
- **per-pool scale events** — the shared decision log contains applied
  scale-ups for BOTH pool labels.

``--no-tenant-buckets`` is the anti-vacuity lever: the router runs
without per-tenant buckets, acme's burst saturates pool-b's bounded
engines, and the peer-goodput gate MUST fail (exit 1) — proving the
gate measures the isolation mechanism, not ambient capacity.

The committed record is ``TENANT_*.json`` (BENCH schema; headline =
pool-b churn-phase goodput as % of baseline). Reproduction:
``benchmarks/run_multitenant.sh``.
"""

import asyncio
import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

import aiohttp

from production_stack_tpu.autoscaler.actuator import (LocalProcessActuator,
                                                      PoolConfigWriter)
from production_stack_tpu.autoscaler.collector import SignalCollector
from production_stack_tpu.autoscaler.controller import (ActuationBudget,
                                                        Autoscaler)
from production_stack_tpu.autoscaler.policy import (AutoscalerPolicy,
                                                    PolicyConfig)
from production_stack_tpu.loadgen.orchestrator import (_spawn, _stop,
                                                       free_port,
                                                       wait_healthy)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"

POOL_A = "pool-a"
POOL_B = "pool-b"
MODEL_A = "model-a"
MODEL_B = "model-b"


class _Rec:
    __slots__ = ("t", "phase", "model", "tenant", "tier", "kind",
                 "engine", "latency_s")

    def __init__(self, t, phase, model, tenant, tier, kind, engine,
                 latency_s):
        self.t = t                      # completion, monotonic
        self.phase = phase
        self.model = model              # model requested AT SEND TIME
        self.tenant = tenant
        self.tier = tier
        self.kind = kind                # ok | shed | http_5xx |
                                        # http_4xx | transport
        self.engine = engine            # x-engine-id (ok only)
        self.latency_s = latency_s


class _Worker:
    """One closed-loop client. ``model`` is a zero-arg callable so the
    churn script can retarget live workers onto a freshly loaded
    adapter id (and back) without restarting the storm."""

    __slots__ = ("session", "model", "tenant", "tier", "think_s")

    def __init__(self, session: str, model: Callable[[], str],
                 tenant: Optional[str] = None, tier: str = "",
                 think_s: float = 0.05):
        self.session = session
        self.model = model
        self.tenant = tenant
        self.tier = tier
        self.think_s = think_s


def _fixed(model: str) -> Callable[[], str]:
    return lambda: model


async def _storm(url: str, phase: str, *, deadline: float,
                 workers: List[_Worker],
                 num_tokens: int = 4,
                 request_timeout_s: float = 20.0,
                 sink: Optional[List[_Rec]] = None) -> List[_Rec]:
    """Closed-loop storm, one task per worker. Fresh connection per
    request (``force_close``) so per-request routing is exercised;
    sheds honor the Retry-After backoff like a well-behaved client."""
    recs: List[_Rec] = sink if sink is not None else []
    timeout = aiohttp.ClientTimeout(total=request_timeout_s)

    async def run(w: _Worker) -> None:
        headers = {"Content-Type": "application/json",
                   "x-user-id": w.session}
        if w.tier:
            headers["x-priority-class"] = w.tier
        if w.tenant:
            headers["x-tenant-id"] = w.tenant
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0,
                                               force_close=True)) as s:
            while time.monotonic() < deadline:
                model = w.model()
                body = json.dumps({
                    "model": model,
                    "messages": [{"role": "user",
                                  "content": f"multitenant {w.session}"}],
                    "max_tokens": num_tokens, "stream": False}).encode()
                t0 = time.monotonic()
                kind, engine = "transport", ""
                try:
                    async with s.post(f"{url}{CHAT_PATH}", data=body,
                                      headers=headers,
                                      timeout=timeout) as resp:
                        if resp.status == 200:
                            await resp.read()
                            kind = "ok"
                            engine = resp.headers.get("x-engine-id", "")
                        elif resp.status in (429, 503) and \
                                "Retry-After" in resp.headers:
                            await resp.read()
                            kind = "shed"
                        elif resp.status >= 500:
                            await resp.read()
                            kind = "http_5xx"
                        else:
                            await resp.read()
                            kind = "http_4xx"
                except (aiohttp.ClientError, ConnectionError, OSError,
                        asyncio.TimeoutError):
                    kind = "transport"
                now = time.monotonic()
                recs.append(_Rec(now, phase, model, w.tenant, w.tier,
                                 kind, engine, now - t0))
                if kind == "shed":
                    await asyncio.sleep(0.1)   # honor the backoff
                else:
                    await asyncio.sleep(w.think_s)

    await asyncio.gather(*(run(w) for w in workers))
    return recs


def _kinds(recs: List[_Rec]) -> Dict[str, int]:
    out = {"ok": 0, "shed": 0, "http_5xx": 0, "http_4xx": 0,
           "transport": 0}
    for r in recs:
        out[r.kind] += 1
    return out


def _model_kinds(recs: List[_Rec], model: str) -> Dict[str, int]:
    return _kinds([r for r in recs if r.model == model])


def _tenant_kinds(recs: List[_Rec], tenant: str) -> Dict[str, int]:
    return _kinds([r for r in recs if r.tenant == tenant])


# ---------------------------------------------------------------- helpers

async def _admin_lora(session: aiohttp.ClientSession, engine_url: str,
                      verb: str, name: str) -> Tuple[int, Optional[str]]:
    """POST /admin/lora/{load|evict}; returns (status, Retry-After)."""
    async with session.post(
            f"{engine_url}/admin/lora/{verb}", json={"name": name},
            timeout=aiohttp.ClientTimeout(total=10)) as r:
        await r.read()
        return r.status, r.headers.get("Retry-After")


async def _set_fault(session: aiohttp.ClientSession, engine_url: str,
                     body: dict) -> None:
    async with session.post(
            f"{engine_url}/fault", json=body,
            timeout=aiohttp.ClientTimeout(total=10)) as r:
        await r.read()


async def _router_health(session: aiohttp.ClientSession,
                         router_url: str) -> dict:
    async with session.get(
            f"{router_url}/health",
            timeout=aiohttp.ClientTimeout(total=5)) as r:
        return await r.json()


async def _wait_model_listed(session: aiohttp.ClientSession,
                             router_url: str, model: str, *,
                             present: bool = True,
                             timeout_s: float = 15.0) -> float:
    """Poll the router's aggregated ``/v1/models`` until ``model``
    appears (or disappears); returns the wait in seconds. This is the
    fleet-wide adapter catalog the rig's adapter traffic keys on — a
    request sent before the catalog lists the adapter would 404."""
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    while time.monotonic() < deadline:
        try:
            async with session.get(
                    f"{router_url}/v1/models",
                    timeout=aiohttp.ClientTimeout(total=5)) as r:
                body = await r.json()
                ids = {row.get("id") for row in body.get("data", [])}
                if (model in ids) == present:
                    return time.monotonic() - t0
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            pass
        await asyncio.sleep(0.3)
    raise TimeoutError(
        f"router /v1/models did not {'list' if present else 'drop'} "
        f"{model!r} within {timeout_s:.0f}s")


def _audit_routing(recs: List[_Rec], writer: PoolConfigWriter,
                   model_to_pool: Dict[str, str],
                   adapter_models: List[str]) -> Dict:
    """The model-correctness audit: join every ok response's
    x-engine-id (the Host the router dialed) against the cumulative
    membership history of the pool that serves the requested model.
    Adapters belong to pool-a (they were only ever loaded there)."""
    hosts: Dict[str, set] = {}
    for pool, urls in writer.history.items():
        hosts[pool] = {u.split("://", 1)[-1].rstrip("/") for u in urls}
    lookup = dict(model_to_pool)
    for m in adapter_models:
        lookup[m] = POOL_A
    wrong: List[dict] = []
    checked = 0
    for r in recs:
        if r.kind != "ok" or not r.engine:
            continue
        pool = lookup.get(r.model)
        checked += 1
        if pool is None or r.engine not in hosts.get(pool, set()):
            wrong.append({"model": r.model, "engine": r.engine,
                          "pool": pool, "phase": r.phase})
    return {"ok_checked": checked,
            "misroutes": len(wrong),
            "misroute_samples": wrong[:10],
            "http_404s": sum(1 for r in recs if r.kind == "http_4xx"),
            "pool_hosts": {p: sorted(h) for p, h in hosts.items()}}


# ---------------------------------------------------------------- the rig

def _launch_pool_router(port: int, *, pools_json: str, config_path: str,
                        log_dir: str, max_inflight: int,
                        tenant_rate: float, extra_args: List[str]):
    cmd = [sys.executable, "-m", "production_stack_tpu.router.app",
           "--host", "127.0.0.1", "--port", str(port),
           "--service-discovery", "static",
           "--pools", pools_json,
           "--routing-logic", "roundrobin",
           "--engine-stats-interval", "1",
           "--dynamic-config-json", config_path,
           "--dynamic-config-interval", "0.3",
           "--failover-attempts", "3",
           "--max-inflight", str(max_inflight),
           "--qos-tiers", "tier0=1.0,tier1=0.9"]
    if tenant_rate > 0:
        cmd += ["--qos-tenant-rate", str(tenant_rate)]
    cmd += extra_args
    return _spawn(f"router-{port}", cmd, f"http://127.0.0.1:{port}",
                  log_dir)


async def run_multitenant(*, baseline_s: float = 6.0,
                          churn_s: float = 14.0,
                          noisy_s: float = 8.0,
                          surge_s: float = 8.0,
                          adapter_cycles: int = 2,
                          initial_a: int = 2, initial_b: int = 1,
                          max_a: int = 3, max_b: int = 2,
                          fake_capacity: int = 4,
                          num_tokens: int = 4,
                          tenant_rate: float = 5.0,
                          tenant_buckets: bool = True,
                          max_inflight: int = 40,
                          noisy_workers: int = 8,
                          tick_interval_s: float = 0.5,
                          surge_rounds: int = 3,
                          platform: str = "cpu",
                          log_dir: str = "loadgen-logs",
                          startup_timeout_s: float = 120.0) -> Dict:
    """Launch two actuator-owned pools behind one pooled router, run
    the four phases, return the TENANT record."""
    os.makedirs(log_dir, exist_ok=True)
    config_path = os.path.join(log_dir, "multitenant-config.json")
    decision_log = os.path.join(log_dir, "multitenant-decisions.jsonl")
    for stale in (config_path, decision_log):
        if os.path.exists(stale):
            os.unlink(stale)

    writer = PoolConfigWriter(config_path)
    service_s = 0.02

    def engine_args(model: str) -> List[str]:
        # strict models make misroutes OBSERVABLE (404), the overload
        # fault bounds admission + advertises capacity for the
        # utilization signal, exactly like the autoscale rig's fakes
        return ["--model", model, "--strict-models",
                "--ttft", f"{service_s:.3f}",
                "--num-tokens", str(num_tokens),
                "--tokens-per-s", "400",
                "--fault", "overload", "--fault-arg", str(fake_capacity)]

    actuator_a = LocalProcessActuator(
        engine="fake", dynamic_config_path=config_path,
        routing_logic="roundrobin", log_dir=log_dir, platform=platform,
        engine_extra_args=engine_args(MODEL_A),
        startup_timeout_s=startup_timeout_s,
        pool=POOL_A, pool_models=[MODEL_A], config_writer=writer)
    actuator_b = LocalProcessActuator(
        engine="fake", dynamic_config_path=config_path,
        routing_logic="roundrobin", log_dir=log_dir, platform=platform,
        engine_extra_args=engine_args(MODEL_B),
        startup_timeout_s=startup_timeout_s,
        pool=POOL_B, pool_models=[MODEL_B], config_writer=writer)

    router = None
    scalers: List[Autoscaler] = []
    budget = ActuationBudget(max_concurrent=1)
    recs: List[_Rec] = []
    adapter_models: List[str] = []
    adapter_ops: List[dict] = []
    fault_probe: Dict = {}
    kill_info: Dict = {}
    http = aiohttp.ClientSession()
    try:
        urls_a = await actuator_a.start(initial_a)
        urls_b = await actuator_b.start(initial_b)
        pools_json = json.dumps(
            {n: dict(p) for n, p in writer.pools.items()})
        router = _launch_pool_router(
            free_port(), pools_json=pools_json, config_path=config_path,
            log_dir=log_dir, max_inflight=max_inflight,
            tenant_rate=tenant_rate if tenant_buckets else 0.0,
            extra_args=[])
        actuator_a.router_url = router.url
        actuator_b.router_url = router.url
        await wait_healthy(router.url, 60.0,
                           require_endpoints=initial_a + initial_b)

        def make_scaler(actuator, pool, initial, maximum) -> Autoscaler:
            policy = AutoscalerPolicy(PolicyConfig(
                min_replicas=initial, max_replicas=maximum,
                target_queue_delay_ms=800.0, down_queue_delay_ms=1.0,
                target_utilization=0.85, down_utilization=0.01,
                up_cooldown_s=2.0, down_cooldown_s=600.0,
                up_breach_ticks=2,
                # the rig never wants a scale-down mid-storm
                down_breach_ticks=10_000,
                # a SIGKILLed replica must not wedge the pool's loop:
                # resume on live signals after ~2s of staleness
                settling_grace_ticks=4))
            collector = SignalCollector(actuator.endpoint_urls,
                                        router_url=router.url,
                                        poll_interval_s=tick_interval_s)
            return Autoscaler(policy, actuator, collector,
                              interval_s=tick_interval_s,
                              decision_log_path=decision_log,
                              pool=pool, budget=budget)

        scalers = [make_scaler(actuator_a, POOL_A, initial_a, max_a),
                   make_scaler(actuator_b, POOL_B, initial_b, max_b)]
        for s in scalers:
            await s.start()
        await asyncio.sleep(tick_interval_s)

        # ---- phase 1: baseline ---------------------------------------
        base_workers = (
            [_Worker(f"a{i}", _fixed(MODEL_A), think_s=0.08)
             for i in range(3)] +
            [_Worker(f"b{i}", _fixed(MODEL_B), think_s=0.08)
             for i in range(3)])
        logger.info("multitenant phase: baseline (%.0fs)", baseline_s)
        baseline = await _storm(router.url, "baseline",
                                deadline=time.monotonic() + baseline_s,
                                workers=base_workers,
                                num_tokens=num_tokens)
        recs.extend(baseline)

        # ---- phase 2: churn (adapters + fault + kill on pool-a) ------
        logger.info("multitenant phase: churn (%.0fs, %d adapter "
                    "cycles, fault + SIGKILL on %s)", churn_s,
                    adapter_cycles, POOL_A)
        current = {"model": MODEL_A}
        churn_recs: List[_Rec] = []
        churn_workers = (
            [_Worker(f"ca{i}", _fixed(MODEL_A), think_s=0.08)
             for i in range(3)] +
            [_Worker(f"cb{i}", _fixed(MODEL_B), think_s=0.08)
             for i in range(3)] +
            [_Worker(f"ad{i}", lambda: current["model"], think_s=0.08)
             for i in range(2)])
        t_churn = time.monotonic()
        storm_task = asyncio.create_task(_storm(
            router.url, "churn", deadline=t_churn + churn_s,
            workers=churn_workers, num_tokens=num_tokens,
            sink=churn_recs))

        live_a = list(urls_a)
        for cycle in range(adapter_cycles):
            name = f"lora-r21-{cycle}"
            statuses = await asyncio.gather(
                *(_admin_lora(http, u, "load", name) for u in live_a))
            listed_in = await _wait_model_listed(http, router.url, name)
            adapter_models.append(name)
            current["model"] = name          # retarget live workers
            await asyncio.sleep(1.2)         # adapter traffic window
            current["model"] = MODEL_A
            await asyncio.sleep(0.6)         # drain in-flight adapter reqs
            evicts = await asyncio.gather(
                *(_admin_lora(http, u, "evict", name) for u in live_a))
            adapter_ops.append({
                "adapter": name,
                "load_statuses": [s for s, _ in statuses],
                "evict_statuses": [s for s, _ in evicts],
                "listed_fleetwide_after_s": round(listed_in, 2)})

        # adapter-load failure is a SHED, never sickness: inject the
        # fault, assert the structured refusal, assert the router's
        # healthy count never moves
        before = await _router_health(http, router.url)
        await _set_fault(http, live_a[0],
                         {"mode": "adapter_load_error", "count": 1})
        status, retry_after = await _admin_lora(http, live_a[0], "load",
                                                "lora-r21-doomed")
        await _set_fault(http, live_a[0],        # restore capacity ad
                         {"mode": "overload", "arg": fake_capacity})
        await asyncio.sleep(1.0)
        after = await _router_health(http, router.url)
        fault_probe = {
            "status": status, "retry_after": retry_after,
            "healthy_endpoints_before": before.get("healthy_endpoints"),
            "healthy_endpoints_after": after.get("healthy_endpoints")}

        # SIGKILL one pool-a engine mid-storm: pool-b must not notice
        victim = live_a[-1]
        handle = actuator_a._handles.get(victim)
        t_kill = time.monotonic() - t_churn
        if handle is not None:
            handle.popen.kill()
        kill_info = {"victim": victim, "at_s": round(t_kill, 1)}
        logger.info("  SIGKILLed %s at t+%.1fs", victim, t_kill)

        churn = await storm_task
        # ---- phase 3: noisy tenant -----------------------------------
        logger.info("multitenant phase: noisy tenant (%.0fs, acme x%d "
                    "vs beta/gamma, buckets %s)", noisy_s,
                    noisy_workers, "on" if tenant_buckets else "OFF")
        noisy_spec = (
            [_Worker(f"acme{i}", _fixed(MODEL_B), tenant="acme",
                     tier="tier1", think_s=0.005)
             for i in range(noisy_workers)] +
            [_Worker("beta0", _fixed(MODEL_B), tenant="beta",
                     tier="tier1", think_s=0.3),
             _Worker("gamma0", _fixed(MODEL_B), tenant="gamma",
                     tier="tier1", think_s=0.3)] +
            [_Worker(f"na{i}", _fixed(MODEL_A), think_s=0.1)
             for i in range(2)])
        noisy = await _storm(router.url, "noisy",
                             deadline=time.monotonic() + noisy_s,
                             workers=noisy_spec,
                             num_tokens=num_tokens)
        recs.extend(churn_recs)
        recs.extend(noisy)

        # ---- phase 4: surge (both pools must scale) ------------------
        surge_spec = (
            [_Worker(f"sa{i}", _fixed(MODEL_A), think_s=0.005)
             for i in range(10)] +
            [_Worker(f"sb{i}", _fixed(MODEL_B), think_s=0.005)
             for i in range(10)])
        surge: List[_Rec] = []
        for rnd in range(surge_rounds):
            logger.info("multitenant phase: surge round %d (%.0fs)",
                        rnd + 1, surge_s)
            await _storm(router.url, "surge",
                         deadline=time.monotonic() + surge_s,
                         workers=surge_spec, num_tokens=num_tokens,
                         sink=surge)
            ups = {s.pool for s in scalers
                   if s.summary()["scale_ups"] > 0}
            if ups >= {POOL_A, POOL_B}:
                break
        recs.extend(surge)

        health = await _router_health(http, router.url)
    finally:
        for s in scalers:
            if s.healthy():
                await s.close()
        if router is not None:
            _stop([router])
        await actuator_a.close()
        await actuator_b.close()
        await http.close()

    # ---- reduce ------------------------------------------------------
    base_b = _model_kinds(baseline, MODEL_B)
    churn_b = _model_kinds(churn, MODEL_B)
    base_b_qps = base_b["ok"] / baseline_s
    churn_b_qps = churn_b["ok"] / churn_s
    held = (100.0 * churn_b_qps / base_b_qps) if base_b_qps else 0.0

    acme = _tenant_kinds(noisy, "acme")
    acme_total = sum(acme.values())
    peers = {t: _tenant_kinds(noisy, t) for t in ("beta", "gamma")}

    decisions: List[dict] = []
    if os.path.exists(decision_log):
        with open(decision_log) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        decisions.append(json.loads(line))
                    except ValueError:
                        pass
    applied_ups = [d for d in decisions
                   if d.get("direction") == "up" and d.get("applied")]
    deferred = [d for d in decisions
                if d.get("deferred") == "actuation_budget"]

    routing = _audit_routing(recs, writer,
                             {MODEL_A: POOL_A, MODEL_B: POOL_B},
                             adapter_models)

    return {
        "metric": "pool-b goodput held during pool-a adapter churn + "
                  "engine kill (multi-pool isolation)",
        "value": round(held, 1),
        "unit": "percent_of_baseline",
        "platform": platform,
        "detail": {
            "tenant_buckets": tenant_buckets,
            "tenant_rate": tenant_rate if tenant_buckets else 0.0,
            "pools": {POOL_A: {"model": MODEL_A, "initial": initial_a,
                               "max": max_a},
                      POOL_B: {"model": MODEL_B, "initial": initial_b,
                               "max": max_b}},
            "phase_durations_s": {"baseline": baseline_s,
                                  "churn": churn_s, "noisy": noisy_s,
                                  "surge": surge_s},
            "baseline": {"model_a": _model_kinds(baseline, MODEL_A),
                         "model_b": base_b,
                         "model_b_goodput_qps": round(base_b_qps, 2)},
            "churn": {
                "model_a": _model_kinds(churn, MODEL_A),
                "model_b": churn_b,
                "model_b_goodput_qps": round(churn_b_qps, 2),
                "adapter": {m: _model_kinds(churn, m)
                            for m in adapter_models},
                "adapter_ops": adapter_ops,
                "adapter_load_fault": fault_probe,
                "engine_kill": kill_info},
            "noisy": {
                "acme": acme,
                "acme_attempts": acme_total,
                "acme_shed_fraction": round(
                    acme["shed"] / acme_total, 3) if acme_total else 0.0,
                "peers": peers,
                "router_tenant_sheds": (health.get("qos") or {}).get(
                    "tenant_sheds"),
            },
            "surge": _kinds(surge),
            "routing": routing,
            "autoscaling": {
                "pools_scaled_up": sorted(
                    {d.get("pool") for d in applied_ups
                     if d.get("pool")}),
                "applied_scale_ups": len(applied_ups),
                "budget_deferrals": len(deferred),
                "budget": budget.snapshot(),
                "per_pool": {s.pool: s.summary() for s in scalers},
            },
            "router_pools_snapshot": health.get("pools"),
            "pool_membership_history": {
                p: sorted(urls) for p, urls in writer.history.items()},
        },
    }


def multitenant_violations(record: Dict, *,
                           interference_floor: float = 0.95,
                           min_noisy_shed: float = 0.5,
                           peer_floor: float = 0.95) -> List[str]:
    """The rig's pass/fail contract (CLI exits 1 on any)."""
    d = record["detail"]
    out: List[str] = []

    # gate 1: routing is 100% model-correct
    routing = d["routing"]
    if routing["ok_checked"] == 0:
        out.append("no ok responses to audit — the storm never ran")
    if routing["misroutes"]:
        out.append(f"{routing['misroutes']} responses served by an "
                   f"engine OUTSIDE the pool that owns the requested "
                   f"model (of {routing['ok_checked']} audited): "
                   f"{routing['misroute_samples'][:3]}")
    if routing["http_404s"]:
        out.append(f"{routing['http_404s']} requests answered 404 "
                   f"(strict engines make misroutes observable; a "
                   f"correctly pooled router never produces one)")

    # gate 2: zero cross-pool interference during pool-a's churn+kill
    if record["value"] < 100.0 * interference_floor:
        out.append(
            f"pool-b goodput fell to {record['value']}% of baseline "
            f"during pool-a churn+kill (need >= "
            f"{100 * interference_floor:.0f}%): cross-pool "
            f"interference")
    churn_b = d["churn"]["model_b"]
    bad_b = churn_b["http_5xx"] + churn_b["transport"]
    if bad_b:
        out.append(f"{bad_b} pool-b client-visible errors during "
                   f"pool-a's churn phase — the blast radius leaked "
                   f"across pools")

    # the adapter-failure semantics ride gate 2's phase: shed, not sick
    fault = d["churn"]["adapter_load_fault"]
    if fault.get("status") != 503 or not fault.get("retry_after"):
        out.append(f"injected adapter-load failure answered "
                   f"{fault.get('status')} (Retry-After: "
                   f"{fault.get('retry_after')!r}) — must be a "
                   f"structured 503 + Retry-After shed")
    if fault.get("healthy_endpoints_after") is not None and \
            fault.get("healthy_endpoints_after") != \
            fault.get("healthy_endpoints_before"):
        out.append(
            f"router healthy-endpoint count moved "
            f"{fault.get('healthy_endpoints_before')} -> "
            f"{fault.get('healthy_endpoints_after')} across the "
            f"adapter-load failure: a failed weight fetch must NEVER "
            f"be a breaker signal (shed != sick)")

    # gate 3: noisy-neighbor containment
    noisy = d["noisy"]
    if noisy["acme_attempts"] == 0:
        out.append("the noisy tenant never sent traffic")
    elif noisy["acme_shed_fraction"] < min_noisy_shed:
        out.append(
            f"noisy tenant acme was shed only "
            f"{noisy['acme_shed_fraction']:.0%} of attempts (need >= "
            f"{min_noisy_shed:.0%}): the per-tenant bucket is not "
            f"binding")
    for tenant, kinds in noisy["peers"].items():
        total = sum(kinds.values())
        ok_frac = kinds["ok"] / total if total else 0.0
        if ok_frac < peer_floor:
            out.append(
                f"tier peer {tenant} kept only {ok_frac:.0%} goodput "
                f"during acme's burst (need >= {peer_floor:.0%}): the "
                f"noisy neighbor was not contained")

    # gate 4: per-pool scale events in the shared decision log
    scaled = set(d["autoscaling"]["pools_scaled_up"])
    for pool in d["pools"]:
        if pool not in scaled:
            out.append(f"no applied scale-up with pool label "
                       f"{pool!r} in the decision log: the per-pool "
                       f"policy loop never actuated")
    return out
