"""trace mode: end-to-end span-chain validation + phase attribution.

The closed loop for the tracing substrate (ISSUE 8). The rig launches a
real router in front of N engines (fake by default; optionally the full
disaggregated split — cache server + producer pool + consumer pool +
``--prefill-backends``), drives a mixed chat/rag storm, captures each
request's ``x-trace-id`` and client-observed latency, then fetches
``/debug/traces`` from the router and every engine and JOINS the three
views per trace id. It exits 1 unless the traces it claims to provide
actually exist and actually account for the time:

- **chain completeness**: >= ``min_chain_fraction`` (default 95%) of
  the requests found in the router's ring must have a complete span
  chain — a router trace whose winning ``relay`` span names an engine,
  AND that engine's ring holding the same trace id; with the split
  topology on, rag-class requests (past ``--min-prompt-chars``) must
  additionally show the ``prefill`` event span and the producer pool's
  rings must hold router-issued trace ids;
- **attribution honesty**: the router-side unattributed time (trace
  duration minus the phase-span sum) must be < ``max_unattributed``
  (default 10%) of the trace duration at the p50 — if the phases don't
  cover the request, the breakdown is decoration, not attribution;
- **zero errors**: a storm that 5xx'd or dropped transport is not a
  measurement.

The committed record (TRACE_r13.json) carries the first honest
phase-level decomposition of where a request's time goes through the
split topology — the attribution the r12 chat-ITL claim previously
could not provide — plus (``--overhead-guard``) a tracing-on re-run of
the r7 router-overhead A/B pinned inside its band.

Reproduction one-liners: docs/benchmarks.md "Request tracing";
benchmarks/run_trace.sh.
"""

import asyncio
import dataclasses
import json
import random
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.orchestrator import (Proc, _stop,
                                                       free_port,
                                                       launch_cache_server,
                                                       launch_engine,
                                                       launch_router,
                                                       wait_cache_ready,
                                                       wait_healthy)
from production_stack_tpu.loadgen.report import percentile
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

CHAT_PATH = "/v1/chat/completions"


@dataclasses.dataclass
class _ClientRecord:
    trace_id: Optional[str]
    cls: str                       # chat | rag
    status: int
    e2e_s: float
    ttft_s: Optional[float]


def _words(rng: random.Random, n_chars: int) -> str:
    out, size = [], 0
    while size < n_chars:
        w = "w%04x" % rng.randrange(1 << 16)
        out.append(w)
        size += len(w) + 1
    return " ".join(out)[:n_chars]


async def _storm(router_url: str, model: str, *, duration_s: float,
                 chat_users: int, rag_users: int,
                 chat_prompt_chars: int, chat_tokens: int,
                 rag_prompt_chars: int, rag_tokens: int, seed: int,
                 request_timeout_s: float = 120.0
                 ) -> List[_ClientRecord]:
    """Closed-loop mixed storm; every request's x-trace-id + client
    latency is recorded — the client-side half of the join."""
    records: List[_ClientRecord] = []
    timeout = aiohttp.ClientTimeout(total=request_timeout_s)
    end_at = time.monotonic() + duration_s

    async def one_request(http, cls: str, rng: random.Random,
                          uid: str) -> None:
        if cls == "chat":
            prompt = f"chat {uid} " + _words(rng, chat_prompt_chars)
            max_tokens = chat_tokens
        else:
            prompt = f"rag {uid} " + _words(rng, rag_prompt_chars)
            max_tokens = rag_tokens
        body = json.dumps({
            "model": model, "stream": True, "max_tokens": max_tokens,
            "messages": [{"role": "user", "content": prompt}]}).encode()
        t0 = time.monotonic()
        first_at = None
        try:
            async with http.post(
                    f"{router_url}{CHAT_PATH}", data=body,
                    headers={"Content-Type": "application/json"},
                    timeout=timeout) as resp:
                trace_id = resp.headers.get("x-trace-id")
                async for raw_line in resp.content:
                    if first_at is None and raw_line.strip():
                        first_at = time.monotonic()
                records.append(_ClientRecord(
                    trace_id=trace_id, cls=cls, status=resp.status,
                    e2e_s=time.monotonic() - t0,
                    ttft_s=None if first_at is None else first_at - t0))
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            records.append(_ClientRecord(
                trace_id=None, cls=cls, status=-1,
                e2e_s=time.monotonic() - t0, ttft_s=None))
            logger.warning("storm request failed: %s: %s",
                           type(e).__name__, e)

    async def user(cls: str, i: int) -> None:
        rng = random.Random(seed * 104729 + (0 if cls == "chat"
                                             else 1 << 20) + i)
        k = 0
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0)) as http:
            while time.monotonic() < end_at:
                await one_request(http, cls, rng, f"{i}-{k}")
                k += 1

    await asyncio.gather(
        *[user("chat", i) for i in range(chat_users)],
        *[user("rag", i) for i in range(rag_users)])
    return records


async def _fetch_traces(url: str, limit: int = 100000) -> Dict[str, dict]:
    """{trace_id: trace} from one process's /debug/traces ring.
    Carries the engine Bearer when ENGINE_API_KEY is exported —
    /debug/traces is auth-enforced on secured engines (per-request
    data, unlike the probe endpoints)."""
    from production_stack_tpu.router.service_discovery import (
        engine_auth_headers)
    try:
        async with aiohttp.ClientSession() as http:
            async with http.get(
                    f"{url}/debug/traces", params={"limit": str(limit)},
                    headers=engine_auth_headers(),
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                data = await r.json()
    except (aiohttp.ClientError, ConnectionError, OSError,
            asyncio.TimeoutError, ValueError):
        return {}
    return {t["trace_id"]: t for t in data.get("traces", [])}


def _span_names(trace: dict) -> set:
    return {s["name"] for s in trace.get("spans", [])}


def _relay_server(trace: dict) -> Optional[str]:
    """The engine the winning attempt relayed from (the last relay /
    backend_ttfb span's server attr)."""
    for span in reversed(trace.get("spans", [])):
        if span["name"] in ("relay", "backend_ttfb"):
            return (span.get("attrs") or {}).get("server")
    return None


def _phase_breakdown(traces: List[dict]) -> Dict[str, dict]:
    """Per-phase p50/p99 ms + share of total attributed time."""
    per_phase: Dict[str, List[float]] = {}
    for t in traces:
        sums: Dict[str, float] = {}
        for s in t.get("spans", []):
            if s["kind"] == "phase":
                sums[s["name"]] = sums.get(s["name"], 0.0) \
                    + s["duration_ms"]
        for name, ms in sums.items():
            per_phase.setdefault(name, []).append(ms)
        per_phase.setdefault("unattributed", []).append(
            t.get("unattributed_ms", 0.0))
    total = sum(sum(v) for v in per_phase.values()) or 1.0
    return {
        name: {
            "p50_ms": round(percentile(vals, 50), 2),
            "p99_ms": round(percentile(vals, 99), 2),
            "share_pct": round(100.0 * sum(vals) / total, 1),
            "requests": len(vals),
        }
        for name, vals in sorted(per_phase.items())
    }


def _join(client_records: List[_ClientRecord], router_traces: Dict,
          engine_traces: Dict[str, Dict], prefill_urls: List[str],
          min_prompt_chars_hit_cls: Optional[str]) -> Dict:
    """The three-way join: client records x router ring x engine rings.
    ``sampled`` = client requests whose trace id the router ring still
    holds (ring churn drops the oldest; the gate applies to what IS
    held — a held trace must be complete)."""
    sampled = complete = with_engine_side = 0
    unattributed_pct: List[float] = []
    joined_cls: Dict[str, List[dict]] = {}
    for rec in client_records:
        if rec.trace_id is None or rec.trace_id not in router_traces:
            continue
        rt = router_traces[rec.trace_id]
        sampled += 1
        dur = rt.get("duration_ms") or 0.0
        if dur > 0:
            unattributed_pct.append(
                100.0 * rt.get("unattributed_ms", 0.0) / dur)
        server = _relay_server(rt)
        engine_side = server is not None and \
            rec.trace_id in engine_traces.get(server, {})
        chain_ok = engine_side
        if chain_ok and min_prompt_chars_hit_cls is not None \
                and rec.cls == min_prompt_chars_hit_cls:
            # split topology: the long-prompt class must ALSO show the
            # prefill stage in its router trace (router->prefill->decode)
            chain_ok = "prefill" in _span_names(rt)
        if engine_side:
            with_engine_side += 1
        if chain_ok:
            complete += 1
        joined_cls.setdefault(rec.cls, []).append(rt)
    # only ROUTER-ISSUED ids count as prefill-stage evidence: a
    # producer minting fresh contexts (a broken traceparent forward)
    # must read as zero, not as a full ring
    prefill_trace_ids = set()
    for url in prefill_urls:
        prefill_trace_ids |= (set(engine_traces.get(url, {}))
                              & set(router_traces))
    return {
        "client_requests": len(client_records),
        "sampled": sampled,
        "with_engine_side": with_engine_side,
        "complete_chains": complete,
        "chain_fraction": round(complete / sampled, 4) if sampled else 0.0,
        "unattributed_p50_pct": round(
            percentile(unattributed_pct, 50), 2) if unattributed_pct
        else None,
        "unattributed_p99_pct": round(
            percentile(unattributed_pct, 99), 2) if unattributed_pct
        else None,
        "prefill_ring_traces": len(prefill_trace_ids),
        "phase_breakdown": {cls: _phase_breakdown(ts)
                            for cls, ts in sorted(joined_cls.items())},
    }


async def run_trace(*, engines: int = 2, engine: str = "fake",
                    disagg: bool = False,
                    prefill_engines: int = 2, decode_engines: int = 2,
                    chat_users: int = 6, rag_users: int = 3,
                    duration_s: float = 20.0,
                    chat_prompt_chars: int = 96, chat_tokens: int = 24,
                    rag_prompt_chars: int = 2400, rag_tokens: int = 4,
                    tokens_per_s: float = 40.0,
                    prefill_ms_per_char: float = 0.4,
                    interference: float = 1.5,
                    kv_chunk_chars: int = 64,
                    headstart_s: float = 3.0,
                    min_prompt_chars: int = 512,
                    routing: str = "least_loaded", seed: int = 0,
                    ring_entries: int = 16384,
                    platform: str = "cpu",
                    log_dir: str = "loadgen-logs",
                    startup_timeout_s: float = 420.0,
                    overhead_guard: bool = False,
                    overhead_users: int = 48,
                    overhead_duration_s: float = 10.0) -> Dict:
    """Launch the topology, storm it, join the spans, return the
    BENCH-schema record (headline value = complete-chain %)."""
    procs: List[Proc] = []
    prefill_procs: List[Proc] = []
    model = "fake-model" if engine == "fake" else engine
    try:
        cache_url = None
        if disagg:
            cache = launch_cache_server(free_port(), log_dir=log_dir)
            procs.append(cache)
            await wait_cache_ready(cache.url)
            cache_url = cache.url

        def fake_args(role: Optional[str]) -> List[str]:
            args = ["--num-tokens", str(max(chat_tokens, rag_tokens)),
                    "--tokens-per-s", str(tokens_per_s),
                    "--prefill-ms-per-char", str(prefill_ms_per_char),
                    "--prefill-decode-interference", str(interference),
                    "--trace-ring-entries", str(ring_entries)]
            if role is not None:
                args += ["--kv-role", role,
                         "--kv-remote-url", cache_url,
                         "--kv-chunk-chars", str(kv_chunk_chars)]
            return args

        def real_args(role: Optional[str]) -> List[str]:
            args = ["--trace-ring-entries", str(ring_entries)]
            if role is not None:
                args += ["--kv-transfer-config",
                         json.dumps({"kv_role": role, "chunk_size": 16,
                                     "remote_url": cache_url})]
            return args

        mk = fake_args if engine == "fake" else real_args
        if disagg:
            prefill_procs = [launch_engine(engine, free_port(),
                                           log_dir=log_dir,
                                           platform=platform,
                                           extra_args=mk("kv_producer"))
                             for _ in range(prefill_engines)]
            decode_procs = [launch_engine(engine, free_port(),
                                          log_dir=log_dir,
                                          platform=platform,
                                          extra_args=mk("kv_consumer"))
                            for _ in range(decode_engines)]
        else:
            prefill_procs = []
            decode_procs = [launch_engine(engine, free_port(),
                                          log_dir=log_dir,
                                          platform=platform,
                                          extra_args=mk(None))
                            for _ in range(engines)]
        procs.extend(prefill_procs)
        procs.extend(decode_procs)
        await asyncio.gather(*[wait_healthy(e.url, startup_timeout_s)
                               for e in prefill_procs + decode_procs])

        router_extra = ["--engine-stats-interval", "2",
                        "--trace-ring-entries", str(ring_entries)]
        if disagg:
            router_extra += [
                "--prefill-backends",
                ",".join(e.url for e in prefill_procs),
                "--prefill-models",
                ",".join([model] * len(prefill_procs)),
                "--prefill-headstart", str(headstart_s),
                "--disagg-min-prompt-chars", str(min_prompt_chars),
            ]
        router = launch_router([e.url for e in decode_procs], model,
                               free_port(), routing=routing,
                               log_dir=log_dir, extra_args=router_extra)
        procs.append(router)
        await wait_healthy(router.url, 60.0,
                           require_endpoints=len(decode_procs))

        t0 = time.monotonic()
        client_records = await _storm(
            router.url, model, duration_s=duration_s,
            chat_users=chat_users, rag_users=rag_users,
            chat_prompt_chars=chat_prompt_chars,
            chat_tokens=chat_tokens,
            rag_prompt_chars=rag_prompt_chars, rag_tokens=rag_tokens,
            seed=seed)
        elapsed = time.monotonic() - t0

        router_traces = await _fetch_traces(router.url)
        engine_traces = {}
        for p in prefill_procs + decode_procs:
            engine_traces[p.url] = await _fetch_traces(p.url)
    finally:
        _stop(procs)

    rag_gated = disagg and rag_prompt_chars >= min_prompt_chars > \
        chat_prompt_chars
    join = _join(client_records, router_traces, engine_traces,
                 [p.url for p in prefill_procs],
                 "rag" if rag_gated else None)
    errors = sum(1 for r in client_records if r.status != 200)

    def side_pct(vals, p):
        return round(percentile(vals, p) * 1e3, 2) if vals else None

    client_lat = {
        cls: {
            "e2e_ms": {"p50": side_pct(
                [r.e2e_s for r in client_records
                 if r.cls == cls and r.status == 200], 50),
                "p99": side_pct(
                [r.e2e_s for r in client_records
                 if r.cls == cls and r.status == 200], 99)},
            "ttft_ms": {"p50": side_pct(
                [r.ttft_s for r in client_records
                 if r.cls == cls and r.ttft_s is not None], 50)},
        }
        for cls in ("chat", "rag") if rag_users or cls == "chat"
    }

    detail = {
        "engine": engine,
        "disagg": disagg,
        "topology": (f"{len(prefill_procs)}P+{len(decode_procs)}D"
                     if disagg else f"{len(decode_procs)} aggregated"),
        "duration_s": round(elapsed, 1),
        "chat_users": chat_users, "rag_users": rag_users,
        "min_prompt_chars": min_prompt_chars if disagg else None,
        "errors": errors,
        "client_latency": client_lat,
        "join": join,
    }

    if overhead_guard:
        # the r7 guard, tracing on: same A/B, same band — tracing must
        # be free enough to leave on in production
        from production_stack_tpu.loadgen.overhead import run_overhead
        logger.info("trace: running the tracing-on overhead guard "
                    "(%d users, %.0fs per side)...", overhead_users,
                    overhead_duration_s)
        guard = await run_overhead(
            engine="fake", users=overhead_users,
            duration_s=overhead_duration_s, platform=platform,
            log_dir=log_dir, startup_timeout_s=startup_timeout_s)
        detail["overhead_guard"] = {
            "direct_req_per_s": guard["detail"]["direct"]["req_per_s"],
            "router_req_per_s": guard["detail"]["router"]["req_per_s"],
            "overhead_ratio": guard["detail"]["overhead_ratio"],
            "errors": (guard["detail"]["direct"]["errors"]
                       + guard["detail"]["router"]["errors"]),
        }

    return {
        "metric": "end-to-end trace completeness + phase attribution "
                  "(router/engine span chains joined by trace id)",
        "value": round(100.0 * join["chain_fraction"], 2),
        "unit": "% complete span chains",
        "platform": platform,
        "detail": detail,
    }


def trace_violations(record: Dict, min_chain_fraction: float = 0.95,
                     max_unattributed_pct: float = 10.0,
                     max_overhead_ratio: Optional[float] = None
                     ) -> List[str]:
    """The pass/fail contract ``loadgen trace`` enforces (exit 1)."""
    out: List[str] = []
    d = record["detail"]
    join = d["join"]
    if d["errors"]:
        out.append(f"{d['errors']} client-visible errors — the storm "
                   f"is not a measurement")
    if join["sampled"] == 0:
        out.append("router trace ring held none of the storm's trace "
                   "ids (ring too small, or x-trace-id missing)")
    elif join["chain_fraction"] < min_chain_fraction:
        out.append(
            f"only {100 * join['chain_fraction']:.1f}% of sampled "
            f"requests have a complete span chain "
            f"(need >= {100 * min_chain_fraction:.0f}%): "
            f"{join['complete_chains']}/{join['sampled']} "
            f"({join['with_engine_side']} had the engine side)")
    una = join.get("unattributed_p50_pct")
    if una is None:
        out.append("no unattributed-time samples (no joined traces)")
    elif una >= max_unattributed_pct:
        out.append(f"unattributed time p50 {una:.1f}% >= "
                   f"{max_unattributed_pct:.0f}% — the phases do not "
                   f"cover the request")
    if d["disagg"] and join["prefill_ring_traces"] == 0:
        out.append("split topology but the prefill pool's trace rings "
                   "hold no router-issued trace ids (prefill stage "
                   "invisible)")
    guard = d.get("overhead_guard")
    if max_overhead_ratio is not None:
        if guard is None:
            out.append("--max-overhead-ratio set but the guard did "
                       "not run")
        elif guard["errors"]:
            out.append(f"overhead guard saw {guard['errors']} errors")
        elif guard["overhead_ratio"] and \
                guard["overhead_ratio"] > max_overhead_ratio:
            out.append(f"tracing-on overhead ratio "
                       f"{guard['overhead_ratio']:.2f}x exceeds the "
                       f"{max_overhead_ratio:g}x band")
    return out
