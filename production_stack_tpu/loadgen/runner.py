"""Workload driver: plays a WorkloadSpec against a base URL.

Closed loop — ``arrival.users`` worker tasks each hold one live session
at a time, issuing its next turn when the previous answer lands.

Open loop — requests launch at Poisson arrival offsets regardless of
completions; each arrival fires the next turn of a ready session (or
admits a new one), so sustained overload shows up as latency and queue
growth, not a self-throttled client.

Soak invariants (checked continuously, reported at the end):
  I1 zero HTTP 5xx
  I2 zero transport/protocol errors (injected aborts excluded)
  I3 request ids assigned strictly monotonically, exactly one terminal
     record per launched id (no lost or duplicated measurements)
  I4 p99 TTFT within the configured bound
  I5 after an injected client disconnect, later requests still succeed
     (the abort was clean; no slot/stream leaked into a wedge)

Checkpoint lines — one JSON object per interval on stdout (and
optionally appended to a file): a long soak that dies at hour 4 still
leaves hour-by-hour evidence.
"""

import asyncio
import dataclasses
import itertools
import json
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from production_stack_tpu.loadgen.arrival import arrival_stream
from production_stack_tpu.loadgen.client import LoadClient, RequestRecord
from production_stack_tpu.loadgen.report import aggregate, percentile
from production_stack_tpu.loadgen.spec import (KINDS, SessionSpec,
                                               TrafficMix, WorkloadSpec)
from production_stack_tpu.loadgen.workload import (SessionPlan, SessionState,
                                                   plan_sessions)
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

DRAIN_GRACE_S = 30.0


class InvariantTracker:
    def __init__(self, p99_ttft_bound_s: Optional[float] = None):
        self.p99_ttft_bound_s = p99_ttft_bound_s
        self.violations: List[str] = []
        self._last_id = -1
        self._launched: set = set()
        self._terminal: set = set()
        self._first_abort_finish: Optional[float] = None
        self._ok_after_abort = 0
        self._launched_after_abort = 0

    def on_launch(self, request_id: int) -> None:
        if request_id <= self._last_id:
            self.violations.append(
                f"I3 non-monotonic request id {request_id} after "
                f"{self._last_id}")
        if request_id in self._launched:
            self.violations.append(f"I3 duplicate launch id {request_id}")
        self._launched.add(request_id)
        self._last_id = max(self._last_id, request_id)
        if self._first_abort_finish is not None:
            self._launched_after_abort += 1

    def on_complete(self, rec: RequestRecord) -> None:
        if rec.request_id in self._terminal:
            self.violations.append(
                f"I3 duplicate terminal record for id {rec.request_id}")
        self._terminal.add(rec.request_id)
        if rec.status >= 500:
            self.violations.append(
                f"I1 HTTP {rec.status} on request {rec.request_id} "
                f"({rec.kind}): {rec.error}")
        elif rec.error is not None:
            self.violations.append(
                f"I2 error on request {rec.request_id} ({rec.kind}): "
                f"{rec.error}")
        if rec.aborted and self._first_abort_finish is None:
            self._first_abort_finish = rec.finish_time
        if rec.ok and self._first_abort_finish is not None and \
                rec.launch_time > self._first_abort_finish:
            self._ok_after_abort += 1

    def finalize(self, records: List[RequestRecord]) -> List[str]:
        missing = self._launched - self._terminal
        if missing:
            self.violations.append(
                f"I3 {len(missing)} launched requests have no terminal "
                f"record (ids {sorted(missing)[:5]}...)")
        if self.p99_ttft_bound_s is not None:
            ttfts = [r.ttft_s for r in records if r.ok]
            p99 = percentile(ttfts, 99)
            if p99 > self.p99_ttft_bound_s:
                self.violations.append(
                    f"I4 p99 TTFT {p99:.3f}s exceeds bound "
                    f"{self.p99_ttft_bound_s:.3f}s")
        if self._first_abort_finish is not None and \
                self._launched_after_abort > 0 and self._ok_after_abort == 0:
            self.violations.append(
                "I5 no successful request after the first injected "
                "disconnect — abort may have wedged the stack")
        return self.violations


def warmup_spec(spec: WorkloadSpec,
                kind: Optional[str] = None) -> WorkloadSpec:
    """Single-turn warmup traffic derived from ``spec``: same model,
    adapter, and traffic mix (so the right executables compile — a
    chat-only warmup would leave the first guided/shaped/embeddings
    request to pay its compile inside the measured window) but sized
    far below any engine geometry the orchestrator launches
    (max-model-len 1024, ~8 model tokens per filler word under
    debug-tiny's character tokenizer) — a warmup the engine 400s would
    silently push the compiles back into the measured window.
    ``kind`` pins the mix to a single request kind."""
    if kind:
        # zero every kind explicitly: TrafficMix defaults chat to 1.0
        mix = TrafficMix(**{**{k: 0.0 for k in KINDS}, kind: 1.0})
    else:
        mix = TrafficMix(**dataclasses.asdict(spec.mix))
    return WorkloadSpec(
        name="warmup", model=spec.model, seed=spec.seed + 7919,
        lora_model=spec.lora_model, mix=mix,
        guided_choices=spec.guided_choices,
        session=SessionSpec(
            rounds_min=1, rounds_max=1, system_prompt_tokens=8,
            question_tokens_mean=8.0, question_tokens_sigma=0.0,
            question_tokens_max=16, answer_tokens_mean=8.0,
            answer_tokens_sigma=0.0, answer_tokens_max=8))


@dataclass
class RunResult:
    records: List[RequestRecord]
    summary: Dict
    violations: List[str]
    checkpoints: List[Dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


class _Run:
    """Shared machinery between the two loop modes."""

    def __init__(self, spec: WorkloadSpec, client: LoadClient,
                 tracker: InvariantTracker, abort_fraction: float,
                 first_session_id: int = 0):
        self.spec = spec
        self.client = client
        self.tracker = tracker
        self.records: List[RequestRecord] = []
        self._ids = itertools.count()
        # independent RNG stream for abort injection so injecting aborts
        # does not perturb the planned workload
        self._abort_rng = random.Random((spec.seed << 8) ^ 0x5eed)
        self.abort_fraction = abort_fraction
        # sharding hook (loadgen/distributed): a worker owning session
        # range [first, first+k) plans the SAME sessions the whole
        # schedule would have planned at those ids — plan_sessions is
        # resumable, so shards concatenate to the unsharded schedule
        self._first_session = first_session_id
        self._next_session = first_session_id

    def new_session(self) -> SessionState:
        plan = plan_sessions(self.spec, 1, first_id=self._next_session)[0]
        self._next_session += 1
        return SessionState(plan, self.spec)

    @property
    def sessions_started(self) -> int:
        return self._next_session - self._first_session

    async def fire(self, state: SessionState) -> RequestRecord:
        plan = state.next_request()
        rid = next(self._ids)
        self.tracker.on_launch(rid)
        abort_after = None
        if self.abort_fraction > 0 and plan.stream and \
                self._abort_rng.random() < self.abort_fraction:
            abort_after = 0.2 + self._abort_rng.random() * 0.8
        try:
            rec = await self.client.execute(plan, rid,
                                            abort_after_s=abort_after)
        except asyncio.CancelledError:
            # harness-side cancellation (open-loop drain, shutdown):
            # the launched id still needs its terminal record, or
            # finalize() would report the harness's own cancels as a
            # false I3 violation against the stack
            rec = RequestRecord(
                request_id=rid, session_id=plan.session_id,
                turn_index=plan.turn_index, kind=plan.kind,
                launch_time=time.time(), finish_time=time.time(),
                cancelled=True)
            self.records.append(rec)
            self.tracker.on_complete(rec)
            raise
        state.record_answer(rec.body)
        rec.body = ""        # only the history append above needs it; a
        # 4.4 h soak must not retain every response string until exit
        self.records.append(rec)
        self.tracker.on_complete(rec)
        return rec


async def _closed_loop(run: _Run, deadline: Optional[float],
                       max_sessions: Optional[int]) -> None:
    spec = run.spec

    async def worker() -> None:
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                return
            if max_sessions is not None and \
                    run.sessions_started >= max_sessions:
                return
            state = run.new_session()
            while not state.done:
                if deadline is not None and time.monotonic() >= deadline:
                    return
                rec = await run.fire(state)
                if rec.error is not None:
                    # instantly-failing requests (a 4xx storm, a dead
                    # backend) must not spin the closed loop into a
                    # tight error-generating hot loop
                    await asyncio.sleep(0.2)
                if spec.arrival.think_time_s:
                    await asyncio.sleep(spec.arrival.think_time_s)

    workers = [asyncio.create_task(worker())
               for _ in range(spec.arrival.users)]
    try:
        await asyncio.gather(*workers)
    finally:
        for w in workers:
            w.cancel()


async def _open_loop(run: _Run, deadline: Optional[float],
                     max_sessions: Optional[int],
                     arrival_seed: Optional[int] = None) -> None:
    spec = run.spec
    # arrival randomness is decoupled from spec.seed on request: N
    # distributed workers plan sessions off the SAME spec.seed (shared
    # schedule, disjoint id ranges) but need INDEPENDENT Poisson
    # streams — identical streams would synchronize arrivals into
    # N-request bursts instead of superposing to one Poisson process
    seed = arrival_seed if arrival_seed is not None \
        else (spec.seed << 8) ^ 0xa441
    rng = random.Random(seed)
    ready: List[SessionState] = []
    in_flight: set = set()
    t0 = time.monotonic()
    endless = deadline is not None     # duration-bounded: ramp's last
    # stage repeats so the soak outlives the declared sweep

    def fire_one() -> None:
        if ready:
            state = ready.pop(0)
        elif max_sessions is not None and \
                run.sessions_started >= max_sessions:
            return
        else:
            state = run.new_session()

        async def task() -> None:
            await run.fire(state)
            if not state.done:
                ready.append(state)

        t = asyncio.create_task(task())
        in_flight.add(t)
        t.add_done_callback(in_flight.discard)

    for offset, _qps in arrival_stream(rng, spec.arrival.stages(),
                                       repeat_last=endless):
        now = time.monotonic()
        if deadline is not None and t0 + offset >= deadline:
            break
        if t0 + offset > now:
            await asyncio.sleep(t0 + offset - now)
        if deadline is not None and time.monotonic() >= deadline:
            break
        fire_one()
        if max_sessions is not None and not ready and \
                run.sessions_started >= max_sessions and not in_flight:
            break
    # drain: stop launching, let in-flight requests land
    drain_until = time.monotonic() + DRAIN_GRACE_S
    while in_flight and time.monotonic() < drain_until:
        await asyncio.sleep(0.1)
    for t in list(in_flight):
        t.cancel()
    if in_flight:
        await asyncio.gather(*in_flight, return_exceptions=True)


async def _checkpoint_loop(run: _Run, interval_s: float, started: float,
                           out: List[Dict],
                           path: Optional[str]) -> None:
    seq = 0
    while True:
        await asyncio.sleep(interval_s)
        seq += 1
        recs = run.records
        ok = [r for r in recs if r.ok]
        elapsed = time.monotonic() - started
        line = {
            "checkpoint": seq,
            "t_s": round(elapsed, 1),
            "launched": run.tracker._last_id + 1,
            "finished": len(ok),
            "errors": len([r for r in recs if r.error is not None]),
            "aborted": len([r for r in recs if r.aborted]),
            "output_tokens_per_s": round(
                sum(r.output_tokens for r in ok) / max(elapsed, 1e-9), 2),
            "p99_ttft_s": round(
                percentile([r.ttft_s for r in ok], 99), 4),
            "violations": len(run.tracker.violations),
        }
        out.append(line)
        text = json.dumps(line)
        print(f"CHECKPOINT {text}", flush=True)
        if path:
            with open(path, "a") as f:
                f.write(text + "\n")


async def run_workload(spec: WorkloadSpec, base_url: str, *,
                       api_key: Optional[str] = None,
                       duration_s: Optional[float] = None,
                       max_sessions: Optional[int] = None,
                       abort_fraction: float = 0.0,
                       p99_ttft_bound_s: Optional[float] = None,
                       checkpoint_interval_s: float = 30.0,
                       checkpoint_path: Optional[str] = None,
                       warmup_requests: int = 0,
                       first_session_id: int = 0,
                       arrival_seed: Optional[int] = None) -> RunResult:
    """Drive ``spec`` against ``base_url``; returns records + summary +
    invariant verdicts. ``duration_s``/``max_sessions`` override the
    spec's own bounds when given. ``first_session_id`` starts the
    session schedule mid-stream (distributed worker shard
    [first, first+max_sessions)); ``max_sessions`` counts sessions
    started by THIS run, not absolute ids."""
    spec.validate()
    duration_s = duration_s if duration_s is not None else spec.duration_s
    max_sessions = max_sessions if max_sessions is not None \
        else spec.max_sessions
    if duration_s is None and max_sessions is None:
        max_sessions = spec.arrival.users * 2    # finite default
    client = LoadClient(base_url, api_key=api_key,
                        request_timeout_s=spec.request_timeout_s)
    await client.start()
    tracker = InvariantTracker(p99_ttft_bound_s=p99_ttft_bound_s)
    run = _Run(spec, client, tracker, abort_fraction,
               first_session_id=first_session_id)
    checkpoints: List[Dict] = []
    try:
        if warmup_requests > 0:
            # untracked single-turn pokes (distinct users so session
            # routing spreads them over every replica) to absorb
            # first-request compiles before the measured window
            # one warm _Run per active request kind, round-robined so
            # EVERY kind fires at least once regardless of count —
            # proportional sampling could leave a kind (and its
            # executable's compile) for the measured window
            kinds = [k for k, _ in spec.mix.weights()]
            warm_runs = [_Run(warmup_spec(spec, kind=k), client,
                              InvariantTracker(), 0.0) for k in kinds]
            await asyncio.gather(*[
                warm_runs[i % len(warm_runs)].fire(
                    warm_runs[i % len(warm_runs)].new_session())
                for i in range(max(warmup_requests, len(warm_runs)))])
            warm_records = [r for w in warm_runs for r in w.records]
            warm_errors = [r for r in warm_records if r.error is not None]
            if warm_errors:
                # a failed warmup silently pushes the compiles back
                # into the measured window — say so
                logger.warning(
                    "%d/%d warmup requests failed (first: %s) — "
                    "compiles may land in the measured window",
                    len(warm_errors), len(warm_records),
                    warm_errors[0].error)
        started = time.monotonic()
        deadline = started + duration_s if duration_s is not None else None
        ck_task = asyncio.create_task(_checkpoint_loop(
            run, checkpoint_interval_s, started, checkpoints,
            checkpoint_path))
        try:
            if spec.arrival.mode == "closed":
                await _closed_loop(run, deadline, max_sessions)
            else:
                await _open_loop(run, deadline, max_sessions,
                                 arrival_seed=arrival_seed)
        finally:
            ck_task.cancel()
            try:
                await ck_task
            except asyncio.CancelledError:
                pass
    finally:
        await client.close()
    violations = tracker.finalize(run.records)
    return RunResult(records=run.records,
                     summary=aggregate(run.records),
                     violations=violations,
                     checkpoints=checkpoints)
