"""Scale-out orchestrator: N engines + router, measured end to end.

Launches N engine processes (real ``production_stack_tpu.engine.server``
serving ``debug-tiny`` on CPU, or the test fake engine) plus the real
router with a chosen routing policy, runs the SAME seeded workload at
each replica count, and emits the aggregate-tokens/s-vs-replicas curve
(``SCALEOUT_*.json``) — the stack's core DP scale-out claim (BASELINE
config 2), previously never measured.

Everything is public surface: subprocesses + HTTP. The orchestrator
never imports engine or router internals.
"""

import asyncio
import dataclasses
import os
import socket
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.loadgen.report import scaleout_record, write_json
from production_stack_tpu.loadgen.runner import run_workload, warmup_spec
from production_stack_tpu.loadgen.spec import WorkloadSpec
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# engine CLI geometry for CPU scale-out runs: small enough that warmup
# compiles in tens of seconds, big enough to hold a full "scaleout" /
# "mixed" preset session (their round-3 histories reach ~800 model
# tokens under debug-tiny's character-level tokenizer)
ENGINE_ARGS = ["--max-model-len", "1024", "--max-num-seqs", "8",
               "--prefill-chunk", "64", "--decode-window", "8",
               "--kv-len-buckets", "256,512,1024"]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class Proc:
    name: str
    popen: subprocess.Popen
    url: str
    log_path: str


def _spawn(name: str, cmd: List[str], url: str, log_dir: str,
           env: Optional[Dict[str, str]] = None) -> Proc:
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f"{name}.log")
    log = open(log_path, "ab")
    popen = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             cwd=REPO_ROOT,
                             env={**os.environ, **(env or {})})
    log.close()
    return Proc(name=name, popen=popen, url=url, log_path=log_path)


def launch_engine(kind: str, port: int, *, log_dir: str,
                  platform: str = "cpu",
                  extra_args: Optional[List[str]] = None) -> Proc:
    """kind "fake" -> tests/fake_engine.py mock; anything else is a
    model name served by the real engine server."""
    url = f"http://127.0.0.1:{port}"
    if kind == "fake":
        # defaults pace the mock like a tiny real engine; extra_args
        # can override (the overhead A/B pins a zero-think engine so
        # the measurement is the router, not the pacing)
        cmd = [sys.executable, "-m", "tests.fake_engine",
               "--port", str(port), "--host", "127.0.0.1",
               "--model", "fake-model", "--num-tokens", "16",
               "--tokens-per-s", "200", *(extra_args or [])]
        return _spawn(f"engine-fake-{port}", cmd, url, log_dir)
    cmd = [sys.executable, "-m", "production_stack_tpu.engine.server",
           "--model", kind, "--host", "127.0.0.1", "--port", str(port),
           *ENGINE_ARGS, *(extra_args or [])]
    env = {"JAX_PLATFORMS": platform} if platform else {}
    return _spawn(f"engine-{kind}-{port}", cmd, url, log_dir, env=env)


def launch_cache_server(port: int, *, log_dir: str,
                        capacity_gb: float = 1.0) -> Proc:
    """Shared TPKV cache server (python backend — the rigs measure the
    serving stack, not the C++ store). Proc.url is the tpukv:// URL
    engines take as their remote tier."""
    cmd = [sys.executable, "-m", "production_stack_tpu.kvcache.server",
           "--host", "127.0.0.1", "--port", str(port),
           "--capacity-gb", str(capacity_gb), "--backend", "python"]
    return _spawn(f"cache-server-{port}", cmd,
                  f"tpukv://127.0.0.1:{port}", log_dir)


async def wait_cache_ready(url: str, timeout_s: float = 30.0) -> None:
    """Poll a TPKV server with PING until it answers."""
    from production_stack_tpu.kvcache.store import RemoteStore
    client = RemoteStore(url, connect_timeout=0.5, io_timeout=2.0,
                         breaker_threshold=1 << 30)
    deadline = time.monotonic() + timeout_s
    try:
        while time.monotonic() < deadline:
            if await asyncio.to_thread(client.ping):
                return
            await asyncio.sleep(0.3)
    finally:
        client.close()
    raise TimeoutError(f"cache server {url} not answering PING "
                       f"after {timeout_s:.0f}s")


def launch_router(backend_urls: List[str], model: str, port: int, *,
                  routing: str = "session", log_dir: str,
                  snapshot_ttl: Optional[float] = None,
                  extra_args: Optional[List[str]] = None) -> Proc:
    cmd = [sys.executable, "-m", "production_stack_tpu.router.app",
           "--host", "127.0.0.1", "--port", str(port),
           "--service-discovery", "static",
           "--static-backends", ",".join(backend_urls),
           "--static-models", ",".join([model] * len(backend_urls)),
           "--routing-logic", routing,
           "--engine-stats-interval", "5"]
    if snapshot_ttl is not None:
        cmd += ["--request-stats-snapshot-ttl", str(snapshot_ttl)]
    cmd += extra_args or []
    return _spawn(f"router-{port}", cmd, f"http://127.0.0.1:{port}",
                  log_dir)


def launch_obsplane(router_urls: List[str], engine_urls: List[str],
                    port: int, *, log_dir: str,
                    incident_dir: str,
                    extra_args: Optional[List[str]] = None) -> Proc:
    """The fleet observability aggregator (obsplane/app.py): scrapes
    every router and engine, stitches traces online, and captures
    alert-triggered incident bundles into ``incident_dir``."""
    cmd = [sys.executable, "-m", "production_stack_tpu.obsplane",
           "--host", "127.0.0.1", "--port", str(port),
           "--routers", ",".join(router_urls),
           "--engines", ",".join(engine_urls),
           "--incident-dir", incident_dir,
           *(extra_args or [])]
    return _spawn(f"obsplane-{port}", cmd, f"http://127.0.0.1:{port}",
                  log_dir)


def launch_kvplane(replica_urls: List[str], port: int, *,
                   log_dir: str, router_url: Optional[str] = None,
                   extra_args: Optional[List[str]] = None) -> Proc:
    """The fleet KV memory planner (kvplane/app.py): polls every
    replica's /load kv_pool census and erases fragmented-admission
    failures by migrating KV replica-to-replica."""
    cmd = [sys.executable, "-m", "production_stack_tpu.kvplane",
           "--host", "127.0.0.1", "--port", str(port),
           "--replicas", ",".join(replica_urls)]
    if router_url:
        cmd += ["--router", router_url]
    cmd += extra_args or []
    return _spawn(f"kvplane-{port}", cmd, f"http://127.0.0.1:{port}",
                  log_dir)


async def wait_healthy(url: str, timeout_s: float,
                       require_endpoints: int = 0) -> None:
    """Poll /health until 200 (and, for the router, until it can route
    to ``require_endpoints`` backends)."""
    deadline = time.monotonic() + timeout_s
    last_err = "never polled"
    async with aiohttp.ClientSession() as session:
        while time.monotonic() < deadline:
            try:
                async with session.get(
                        f"{url}/health",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    if r.status == 200:
                        if require_endpoints == 0:
                            return
                        body = await r.json()
                        if body.get("endpoints", 0) >= require_endpoints:
                            return
                        last_err = f"endpoints={body.get('endpoints')}"
                    else:
                        last_err = f"HTTP {r.status}"
            except (aiohttp.ClientError, ConnectionError, OSError,
                    asyncio.TimeoutError) as e:
                last_err = f"{type(e).__name__}"
            await asyncio.sleep(0.5)
    raise TimeoutError(f"{url}/health not ready after {timeout_s:.0f}s "
                       f"(last: {last_err})")


def _stop(procs: List[Proc]) -> None:
    for p in procs:
        if p.popen.poll() is None:
            p.popen.terminate()
    deadline = time.monotonic() + 10
    for p in procs:
        try:
            p.popen.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.popen.kill()
            p.popen.wait(timeout=5)


class LocalStack:
    """N engines + 1 router on localhost; async context manager."""

    def __init__(self, replicas: int, engine: str = "debug-tiny", *,
                 routing: str = "session", log_dir: str = "loadgen-logs",
                 platform: str = "cpu", startup_timeout_s: float = 420.0,
                 engine_args: Optional[List[str]] = None):
        self.replicas = replicas
        self.engine = engine
        self.routing = routing
        self.log_dir = log_dir
        self.platform = platform
        self.startup_timeout_s = startup_timeout_s
        self.engine_args = engine_args
        self.procs: List[Proc] = []
        self.engine_urls: List[str] = []
        self.url: Optional[str] = None

    async def __aenter__(self) -> "LocalStack":
        try:
            engines = [launch_engine(self.engine, free_port(),
                                     log_dir=self.log_dir,
                                     platform=self.platform,
                                     extra_args=self.engine_args)
                       for _ in range(self.replicas)]
            self.procs.extend(engines)
            self.engine_urls = [e.url for e in engines]
            # engines warm up concurrently (each compiles its own
            # executables); health gates on warmup completion
            await asyncio.gather(*[
                wait_healthy(e.url, self.startup_timeout_s)
                for e in engines])
            model = "fake-model" if self.engine == "fake" else self.engine
            router = launch_router([e.url for e in engines], model,
                                   free_port(), routing=self.routing,
                                   log_dir=self.log_dir)
            self.procs.append(router)
            await wait_healthy(router.url, 60.0,
                               require_endpoints=self.replicas)
            self.url = router.url
            return self
        except BaseException:
            for p in self.procs:
                if p.popen.poll() is not None:
                    logger.error("%s exited rc=%s; log: %s", p.name,
                                 p.popen.returncode, p.log_path)
            _stop(self.procs)
            raise

    async def __aexit__(self, *exc) -> None:
        _stop(self.procs)


async def run_scaleout(spec: WorkloadSpec, *,
                       replicas: List[int],
                       engine: str = "debug-tiny",
                       routing: str = "session",
                       duration_s: float = 60.0,
                       users_per_replica: Optional[int] = None,
                       platform: str = "cpu",
                       log_dir: str = "loadgen-logs",
                       startup_timeout_s: float = 420.0,
                       checkpoint_interval_s: Optional[float] = None,
                       output: Optional[str] = None) -> Dict:
    """Measure the same workload at each replica count; write and
    return the SCALEOUT record.

    The offered load scales with N (closed loop: users_per_replica × N
    concurrent users) so each point probes capacity, and the seeded
    session plans are identical across points — N is the only variable.
    """
    if users_per_replica is None:
        users_per_replica = spec.arrival.users
    points: List[Dict] = []
    for n in replicas:
        logger.info("scale-out point: %d replica(s) of %s via %s routing",
                    n, engine, routing)
        stack_log = os.path.join(log_dir, f"n{n}")
        async with LocalStack(n, engine, routing=routing,
                              log_dir=stack_log, platform=platform,
                              startup_timeout_s=startup_timeout_s) as stack:
            point_spec = WorkloadSpec.from_dict(dataclasses.asdict(spec))
            point_spec.arrival.users = users_per_replica * n
            if engine != "fake":
                point_spec.model = engine
            else:
                point_spec.model = "fake-model"
            # warm each engine DIRECTLY before the measured window:
            # consistent-hash session routing gives no guarantee that
            # router-side warmup traffic reaches every replica, and a
            # cold replica pays its first-request XLA compiles inside
            # the point it is supposed to be measured at
            for e_url in stack.engine_urls:
                warm = await run_workload(
                    warmup_spec(point_spec), e_url, max_sessions=2,
                    checkpoint_interval_s=1e9)
                if warm.summary["errors"]:
                    logger.warning(
                        "warmup against %s: %d/%d failed (first: %s) — "
                        "point N=%d may include compile time", e_url,
                        warm.summary["errors"], warm.summary["launched"],
                        (warm.summary["error_samples"] or ["?"])[0], n)
            result = await run_workload(
                point_spec, stack.url, duration_s=duration_s,
                checkpoint_interval_s=checkpoint_interval_s
                or max(15.0, duration_s / 4))
            agg = result.summary
            points.append({
                "replicas": n,
                "users": point_spec.arrival.users,
                "output_tokens_per_s": agg["output_tokens_per_s"],
                "input_tokens_per_s": agg["input_tokens_per_s"],
                "processed_qps": agg["processed_qps"],
                "errors": agg["errors"],
                "invariant_violations": result.violations,
                "ttft_s": agg["ttft_s"],
                "summary": agg,
            })
            logger.info("N=%d: %.2f out tok/s (%d finished, %d errors)",
                        n, agg["output_tokens_per_s"], agg["finished"],
                        agg["errors"])
            if agg["errors"]:
                logger.warning(
                    "N=%d point had %d errors — the curve is suspect. "
                    "First error: %s", n, agg["errors"],
                    (agg["error_samples"] or ["?"])[0])
    record = scaleout_record(engine=engine, routing=routing,
                             workload=spec.name, points=points,
                             platform=platform,
                             notes=f"duration {duration_s:.0f}s/point, "
                                   f"{users_per_replica} users per "
                                   f"replica, seed {spec.seed}")
    if output:
        write_json(output, record)
        logger.info("wrote %s", output)
    return record
