"""PartitionSpecs for the stacked-params Llama pytree (megatron-style).

Column-parallel projections (q/k/v/gate/up) shard the output feature dim
over ``tp``; row-parallel (o/down) shard the input feature dim, so each
layer needs exactly one psum (inserted automatically by XLA from the
sharding propagation) on the attention output and one on the MLP output —
riding ICI within the slice.

Embedding and lm_head shard the vocab dim; norms are replicated.
The KV cache shards over heads (tp) and slots (dp).
"""

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_LAYER_SPECS: Dict[str, P] = {
    # [L, in, out] column-parallel: shard out over tp
    "q": P(None, None, "tp"),
    "k": P(None, None, "tp"),
    "v": P(None, None, "tp"),
    "gate": P(None, None, "tp"),
    "up": P(None, None, "tp"),
    # [L, in, out] row-parallel: shard in over tp
    "o": P(None, "tp", None),
    "down": P(None, "tp", None),
    # column-parallel biases [L, out] follow their projection's out shard
    "q_bias": P(None, "tp"),
    "k_bias": P(None, "tp"),
    "v_bias": P(None, "tp"),
    # norms replicated (incl. Gemma-2's sandwich norms)
    "attn_norm": P(None, None),
    "mlp_norm": P(None, None),
    "post_attn_norm": P(None, None),
    "post_mlp_norm": P(None, None),
}


_MOE_SPECS: Dict[str, P] = {
    # router [L, h, E] replicated: every device routes every token
    "router": P(None, None, None),
    # expert-stacked FFN: experts over ep, hidden features over tp —
    # column-parallel gate/up ([L, E, h, i] shard i), row-parallel down
    # ([L, E, i, h] shard i), same one-psum-per-layer structure as the
    # dense path but within each expert
    "gate": P(None, "ep", None, "tp"),
    "up": P(None, "ep", None, "tp"),
    "down": P(None, "ep", "tp", None),
    # Qwen2-MoE shared expert: an ordinary dense MLP, megatron-sharded
    # over tp; its scalar sigmoid gate is replicated
    "s_gate": P(None, None, "tp"),
    "s_up": P(None, None, "tp"),
    "s_down": P(None, "tp", None),
    "s_gate_w": P(None, None, None),
}


def _qspec(leaf: Any, spec: P, per_row: bool = False) -> Any:
    """Expand a weight's spec for int8-quantized leaves (models/quant.py
    {"w8", "scale"} dicts): w8 keeps the weight's spec; scale drops the
    reduced axis — the in axis (-2) for per-output-channel weights, the
    last axis for the per-row embed table."""
    from production_stack_tpu.models.quant import is_quantized
    if not is_quantized(leaf):
        return spec
    dims = tuple(spec)
    scale_spec = P(*dims[:-1]) if per_row else P(*dims[:-2], dims[-1])
    return {"w8": spec, "scale": scale_spec}


def param_pspecs(params: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec pytree matching models/llama.py's params layout."""
    moe = "router" in params["layers"]
    layer_specs = dict(_LAYER_SPECS, **_MOE_SPECS) if moe else _LAYER_SPECS
    specs: Dict[str, Any] = {
        "embed": _qspec(params["embed"], P("tp", None), per_row=True),
        "layers": {name: _qspec(leaf, layer_specs[name])
                   for name, leaf in params["layers"].items()},
        "final_norm": P(None),
    }
    if "lm_head" in params:
        specs["lm_head"] = _qspec(params["lm_head"], P(None, "tp"))
    return specs


def param_shardings(mesh: Mesh, params: Dict[str, Any]) -> Dict[str, Any]:
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        param_pspecs(params),
                        is_leaf=lambda x: isinstance(x, P))


def data_sharding(mesh: Mesh, sequence_parallel: bool = False):
    """Sharding for [B, T] token batches: batch over dp, optionally
    sequence over sp (ring attention consumes the sp axis)."""
    return NamedSharding(mesh, P("dp", "sp" if sequence_parallel else None))


def cache_pspec() -> P:
    """KV pool [L, N, Hkv, Bs, D]: blocks over dp, kv heads over tp."""
    return P(None, "dp", "tp", None, None)


def cache_scale_pspec() -> P:
    """int8-KV dequant scales [L, N, Hkv, Bs]: same placement as the
    pool minus the head-dim axis (models/kv.py)."""
    return P(None, "dp", "tp", None)


def shard_params(mesh: Mesh, params: Dict[str, Any]) -> Dict[str, Any]:
    """Place an (unsharded) params pytree onto the mesh."""
    return jax.device_put(params, param_shardings(mesh, params))
