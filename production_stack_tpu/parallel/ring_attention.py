"""Ring attention: causal attention with the sequence sharded over ``sp``.

Long-context support: each of the N devices on the sp axis holds one
contiguous block of the sequence (queries and K/V). K/V blocks rotate
around the ring via ``lax.ppermute`` (ICI neighbor hops — bandwidth-
optimal, never all-to-all) while each device accumulates its queries'
attention with a numerically-stable online softmax (flash-style running
max/sum). After N-1 hops every query has seen every key it may attend to.

Causality at block granularity: a device only *uses* a K/V block whose
global positions aren't entirely in its future; within the diagonal block
a per-element mask applies. Compute cost of skipped blocks is masked, not
branched (static shapes; XLA requires it).

Used under ``shard_map`` over the 'sp' axis — see ``ring_causal_attention``
for the jit-level wrapper. The reference stack has no long-context
machinery at all (SURVEY.md §5 "Long-context": nothing in-repo); this is
new TPU-native capability.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, scale):
    """Partial attention of local q against one K/V block.

    q [B,Tq,Hkv,G,D]; k,v [B,Tk,Hkv,D]; positions [Tq]/[Tk] global.
    Returns (unnormalized out [B,Tq,Hkv,G,D], row max m [B,Hkv,G,Tq],
    row sum l [B,Hkv,G,Tq]) for online-softmax merging.
    """
    scores = jnp.einsum("btkgd,bskd->bkgts", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = k_pos[None, :] <= q_pos[:, None]          # [Tq,Tk] causal
    scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
    m = scores.max(axis=-1)                          # [B,Hkv,G,Tq]
    p = jnp.exp(scores - m[..., None])
    # rows with no visible keys: m = -inf -> p would be exp(0)=1; zero them
    valid = (m > _NEG_INF / 2)
    p = jnp.where(valid[..., None], p, 0.0)
    m = jnp.where(valid, m, _NEG_INF)
    l = p.sum(axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", p.astype(v.dtype), v)
    return out.astype(jnp.float32), m, l


def _merge(out, m, l, blk_out, blk_m, blk_l):
    """Online-softmax merge of one block's partial attention."""
    new_m = jnp.maximum(m, blk_m)
    alpha = jnp.exp(m - new_m)
    beta = jnp.exp(blk_m - new_m)
    l = l * alpha + blk_l * beta
    out = out * _to_btkgd(alpha) + blk_out * _to_btkgd(beta)
    return out, new_m, l


def _to_btkgd(x):
    """[B,Hkv,G,Tq] -> [B,Tq,Hkv,G,1] broadcast helper."""
    return jnp.moveaxis(x, -1, 1)[..., None]


def _ring_attention_local(q, k, v, scale, axis_name):
    """Per-device body (inside shard_map). q [B,Tl,H,D]; k,v [B,Tl,Hkv,D]."""
    B, Tl, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    idx = lax.axis_index(axis_name)
    n = lax.psum(1, axis_name)
    q_pos = idx * Tl + jnp.arange(Tl)
    k_pos0 = idx * Tl + jnp.arange(Tl)

    q5 = q.reshape(B, Tl, Hkv, G, D)
    # local (diagonal) block first — no communication needed for it
    out, m, l = _block_attend(q5, k, v, q_pos, k_pos0, scale)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, _):
        k_blk, v_blk, k_pos, out, m, l = carry
        # rotate first, then attend: exactly n-1 hops total, and the
        # final iteration's K/V are consumed, not discarded
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        k_pos = lax.ppermute(k_pos, axis_name, perm)
        blk = _block_attend(q5, k_blk, v_blk, q_pos, k_pos, scale)
        out, m, l = _merge(out, m, l, *blk)
        return (k_blk, v_blk, k_pos, out, m, l), None

    (k_f, v_f, kp_f, out, m, l), _ = lax.scan(
        body, (k, v, k_pos0, out, m, l), None, length=n - 1)
    norm = jnp.where(l > 0, l, 1.0)
    out = out / _to_btkgd(norm)
    return out.reshape(B, Tl, H, D).astype(q.dtype)


def ring_causal_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                          scale: Optional[float] = None):
    """Causal GQA with sequence dim sharded over mesh axis ``axis_name``.

    q [B,T,H,D]; k,v [B,T,Hkv,D] with T globally sharded over sp. Batch
    stays dp-sharded and heads tp-sharded (ring collectives touch only the
    sp axis, so dp/tp shards proceed independently). Output matches
    ops.attention.causal_attention run on a single device.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    dp = "dp" if "dp" in mesh.shape else None
    tp = "tp" if "tp" in mesh.shape else None
    spec = P(dp, axis_name, tp, None)
    fn = jax.shard_map(
        functools.partial(_ring_attention_local, scale=scale,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
