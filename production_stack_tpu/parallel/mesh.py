"""Device-mesh construction for dp/sp/tp parallelism.

The TPU-native replacement for the reference's NCCL-implied distributed
backend (reference: the /dev/shm mount for NCCL at
helm/templates/deployment-vllm-multi.yaml:197-228 and the
--tensor-parallel-size passthrough at :84-87): parallelism here is a
jax.sharding.Mesh over the slice's chips, with XLA inserting ICI
collectives from sharding annotations — no process groups, no shm.

Axes:
  pp — pipeline parallel (layer stages, parallel/pipeline.py)
  dp — data parallel (batch)
  sp — sequence parallel (ring attention over sequence blocks)
  ep — expert parallel (MoE expert weights, ops/moe.py)
  tp — tensor parallel (megatron column/row sharding of matmuls)

tp stays innermost (ICI-nearest: its per-layer psums are the most
latency-sensitive collectives); ep sits just above it so expert
dispatch/combine also rides ICI before dp/sp cross slice boundaries.
pp is outermost: stages exchange one activation per microbatch hop —
the lowest-bandwidth axis, the natural one to place across DCN
(multi-slice) while everything else stays within a slice.

Multi-replica scaling above a slice stays at the stack level (router over
engine replicas), exactly like the reference's L1/L3 split.
"""

import dataclasses
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

AXES = ("pp", "dp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.sp * self.tp * self.ep * self.pp

    @staticmethod
    def for_devices(n: int, tp: Optional[int] = None,
                    sp: Optional[int] = None) -> "MeshConfig":
        """Factor n devices into (dp, sp, tp). Defaults favor a balanced
        mesh that activates every axis when divisibility allows (8 chips
        -> 2x2x2), with tp on the innermost (ICI-nearest) axis."""
        if tp is None:
            tp = 2 if n % 2 == 0 else 1
        if n % tp:
            raise ValueError(f"tp={tp} does not divide {n} devices")
        rest = n // tp
        if sp is None:
            sp = 2 if rest % 2 == 0 and rest >= 2 else 1
        if rest % sp:
            raise ValueError(f"sp={sp} does not divide {rest} devices")
        cfg = MeshConfig(dp=rest // sp, sp=sp, tp=tp)
        assert cfg.size == n
        return cfg


def build_mesh(cfg: Optional[MeshConfig] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    cfg = cfg or MeshConfig.for_devices(len(devices))
    if cfg.size != len(devices):
        raise ValueError(
            f"mesh {cfg} needs {cfg.size} devices, have {len(devices)}")
    import numpy as np
    dev_array = np.asarray(devices).reshape(cfg.pp, cfg.dp, cfg.sp,
                                            cfg.ep, cfg.tp)
    return Mesh(dev_array, AXES)
