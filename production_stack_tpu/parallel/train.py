"""Sharded training step (used by the multichip dry-run and fine-tuning).

The serving stack's flagship compute is inference, but the same model
pytree trains: causal-LM loss with optax, jitted over the (dp, sp, tp)
mesh. Params enter in tp sharding, the batch in dp(/sp) sharding; XLA
derives every collective (psum of grads over dp, activation collectives
over tp/sp) from the annotations.
"""

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu.models import llama
from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.parallel.ring_attention import ring_causal_attention
from production_stack_tpu.parallel.sharding import (data_sharding,
                                                    param_shardings)


class TrainState(NamedTuple):
    params: Dict[str, Any]
    opt_state: Any
    step: jnp.ndarray


def make_optimizer(lr: float = 3e-4) -> optax.GradientTransformation:
    return optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(lr))


def nll_from_logits(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross entropy. logits [B,T,V], tokens [B,T].
    The single definition shared by the plain and pipelined
    (parallel/pipeline.py) losses — their parity tests depend on it."""
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def loss_fn(params, cfg: ModelConfig, tokens: jnp.ndarray,
            attention_fn=None) -> jnp.ndarray:
    """Next-token cross entropy; tokens [B,T] (fp32 logits internally)."""
    logits = llama.forward_train(params, cfg, tokens,
                                 attention_fn=attention_fn)
    return nll_from_logits(logits, tokens)


def train_step(state: TrainState, tokens: jnp.ndarray, cfg: ModelConfig,
               optimizer: optax.GradientTransformation, attention_fn=None
               ) -> Tuple[TrainState, jnp.ndarray]:
    loss, grads = jax.value_and_grad(loss_fn)(state.params, cfg, tokens,
                                              attention_fn)
    updates, opt_state = optimizer.update(grads, state.opt_state,
                                          state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss


def jit_train_step(mesh: Mesh, cfg: ModelConfig, params: Dict[str, Any],
                   optimizer: Optional[optax.GradientTransformation] = None,
                   sequence_parallel: bool = True):
    """Build (sharded_state, step_fn): step_fn(state, tokens) -> state, loss.

    Params/opt-state shard tp-style; tokens shard (dp, sp). When
    sequence_parallel and the mesh's sp axis is >1, attention runs as ring
    attention over sp (O(T/sp) activation memory per device, neighbor-hop
    ICI traffic) instead of XLA all-gathering the sequence.

    NOTE: step_fn donates its state, and device_put may alias the caller's
    buffers into that state — treat the ``params`` argument as consumed.
    """
    optimizer = optimizer or make_optimizer()
    p_shardings = param_shardings(mesh, params)
    params = jax.device_put(params, p_shardings)
    opt_state = jax.jit(
        optimizer.init,
        in_shardings=(p_shardings,))(params)
    state = TrainState(params=params, opt_state=opt_state,
                       step=jnp.zeros((), jnp.int32))
    use_sp = sequence_parallel and mesh.shape.get("sp", 1) > 1
    tok_sharding = data_sharding(mesh, sequence_parallel=use_sp)
    attention_fn = None
    if use_sp:
        if cfg.sliding_window:
            # the ring-attention override bypasses the windowed
            # causal_attention path — training full-causal while
            # serving windowed would silently diverge
            raise NotImplementedError(
                "sequence-parallel training does not implement "
                "sliding-window attention yet; train this config "
                "with sp=1")
        attention_fn = lambda q, k, v: ring_causal_attention(  # noqa: E731
            q, k, v, mesh, axis_name="sp")

    def step_fn(state, tokens):
        return train_step(state, tokens, cfg, optimizer, attention_fn)

    jitted = jax.jit(step_fn,
                     in_shardings=(None, tok_sharding),
                     donate_argnums=(0,))
    return state, jitted
