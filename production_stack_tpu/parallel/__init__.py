from production_stack_tpu.parallel.mesh import MeshConfig, build_mesh
from production_stack_tpu.parallel.sharding import (data_sharding,
                                                    param_shardings)

__all__ = ["MeshConfig", "build_mesh", "param_shardings", "data_sharding"]
