"""GPipe-style pipeline-parallel training over the mesh's 'pp' axis.

The reference forwards --pipeline-parallel-size to vllm serve
(reference: SURVEY.md §2.9 — PP is pure config surface there); here the
TPU-native implementation targets the place PP actually pays off on
TPU: scaling the LAYER dimension across slices/hosts where only one
activation tensor per microbatch hop crosses the (DCN-friendly) 'pp'
axis, while tp/ep collectives stay inside each stage's slice.

Design — the stacked-params layout (models/llama.py) is the seam:
- ``layers`` pytree leaves are [L, ...]; reshaped to [P, L/P, ...] and
  sharded P('pp') on the stage axis, each stage holds its L/P layers.
- ``shard_map`` over 'pp' runs the classic GPipe schedule in SPMD: for
  step t in [0, n_micro + P - 1), every stage ppermutes its previous
  output to the next stage, stage 0 feeds microbatch t from its input
  queue, and each stage scans its local layers. After the pipeline
  drains, the last stage holds every microbatch's final hidden states.
- The schedule is an ordinary ``lax.scan`` of linear ops (ppermute,
  where, dynamic slicing), so ``jax.grad`` differentiates straight
  through it — the backward pass is automatically the reverse
  pipeline, no hand-written backward schedule.
- Embedding, final norm, LM head and the loss are replicated per
  stage; only the last stage's loss is real, and a 'pp' psum of
  ``where(stage == P-1, loss, 0)`` broadcasts it. Their (replicated)
  gradients come out psummed over 'pp' — harmless for parity tests and
  small next to the layer stacks; fold them into per-stage
  placement if embedding cost ever matters.

Bubble fraction is the usual (P-1)/(n_micro + P - 1); pick
n_micro >= ~4P. Composes with the batch dim only (dp=1 inside this
entry point): sp/tp/ep sharding inside a stage would need partial-auto
shard_map — the engine keeps those on the GSPMD path instead.
"""

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu.models import llama
from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.ops.norms import rms_norm
from production_stack_tpu.ops.rope import rope_table
from production_stack_tpu.parallel.train import nll_from_logits


def stage_params(params: Dict[str, Any], n_stages: int) -> Dict[str, Any]:
    """Reshape stacked layers [L, ...] -> [P, L/P, ...] (stage-major:
    stage p owns contiguous layers [p*L/P, (p+1)*L/P))."""
    L = jax.tree.leaves(params["layers"])[0].shape[0]
    if L % n_stages:
        raise ValueError(f"pp={n_stages} does not divide num_layers={L}")
    staged = jax.tree.map(
        lambda w: w.reshape((n_stages, L // n_stages) + w.shape[1:]),
        params["layers"])
    return {**params, "layers": staged}


def stage_shardings(mesh: Mesh, params: Dict[str, Any]) -> Dict[str, Any]:
    """Stage-axis sharding for stage_params output: layers over 'pp',
    everything else replicated."""
    staged = NamedSharding(mesh, P("pp"))
    replicated = NamedSharding(mesh, P())
    return {
        name: jax.tree.map(
            lambda _: staged if name == "layers" else replicated, leaf)
        for name, leaf in params.items()
    }


def pipeline_loss_fn(cfg: ModelConfig, mesh: Mesh, n_micro: int):
    """Build loss(params_staged, tokens) -> scalar, jit-able over mesh.

    tokens [B, T] with B divisible by n_micro; params from
    stage_params()/stage_shardings(). Runs the GPipe schedule above.
    """
    n_stages = mesh.shape["pp"]
    if cfg.alternating_sliding:
        # per-layer window alternation needs layer identity, which the
        # stage-local scan below does not thread — full-causal training
        # of an alternating model would silently diverge from serving
        raise NotImplementedError(
            "pipeline-parallel training does not support alternating "
            "sliding-window models (Gemma-2) yet; train with pp=1")
    rope = rope_table(cfg.max_position_embeddings, cfg.head_dim_,
                      cfg.rope_theta, scaling=cfg.rope_scaling)

    has_head = not cfg.tie_word_embeddings

    def per_stage(layers_local, embed, final_norm, *rest):
        if has_head:
            head, tokens = rest
        else:
            head, (tokens,) = None, rest
        # layers_local: [1, L/P, ...] (shard_map keeps the sharded axis
        # with size 1) -> [L/P, ...]
        layers_local = jax.tree.map(lambda w: w[0], layers_local)
        stage = jax.lax.axis_index("pp")
        B, T = tokens.shape
        mb = B // n_micro
        x_all = llama._embed({"embed": embed}, cfg, tokens)
        H = x_all.shape[-1]
        x_micro = x_all.reshape(n_micro, mb, T, H)
        positions = jnp.broadcast_to(jnp.arange(T), (mb, T))

        def run_local(x):
            def body(carry, lp):
                out, _ = llama._layer_body(cfg, rope, positions, None,
                                           carry, lp, None)
                return out, None
            y, _ = jax.lax.scan(body, x, layers_local)
            return y

        n_steps = n_micro + n_stages - 1
        outputs0 = jnp.zeros((n_micro, mb, T, H), x_all.dtype)

        def step(carry, t):
            prev_out, outputs = carry
            # hop the previous step's output one stage forward
            recv = jax.lax.ppermute(
                prev_out, "pp",
                [(i, i + 1) for i in range(n_stages - 1)])
            feed = jnp.where(
                (t < n_micro),
                jax.lax.dynamic_index_in_dim(
                    x_micro, jnp.minimum(t, n_micro - 1), keepdims=False),
                jnp.zeros((mb, T, H), x_all.dtype))
            x_in = jnp.where(stage == 0, feed, recv)
            y = run_local(x_in)
            # last stage banks microbatch t - (P-1) once it emerges
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            bank = (t >= n_stages - 1) & (stage == n_stages - 1)
            cur = jax.lax.dynamic_index_in_dim(outputs, out_idx,
                                               keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(bank, y, cur), out_idx, axis=0)
            return (y, outputs), None

        (_, outputs), _ = jax.lax.scan(
            step, (jnp.zeros((mb, T, H), x_all.dtype), outputs0),
            jnp.arange(n_steps))

        # loss on the last stage only; psum broadcasts it to all
        x = outputs.reshape(B, T, H)
        x = rms_norm(x, final_norm, cfg.rms_norm_eps,
                     offset=1.0 if cfg.rms_norm_offset else 0.0)
        logits = llama._lm_head(
            {"embed": embed, **({"lm_head": head} if head is not None
                                else {})}, cfg, x)
        local = jnp.where(stage == n_stages - 1,
                          nll_from_logits(logits, tokens), 0.0)
        return jax.lax.psum(local, "pp")

    def loss_fn(params_staged, tokens):
        layer_specs = jax.tree.map(lambda _: P("pp"),
                                   params_staged["layers"])
        extra = (P(), P()) if has_head else (P(),)
        fn = shard_map(
            per_stage, mesh=mesh,
            in_specs=(layer_specs, P(), P()) + extra,
            out_specs=P(),
            check_vma=False)
        args = [params_staged["layers"], params_staged["embed"],
                params_staged["final_norm"]]
        if has_head:
            args.append(params_staged["lm_head"])
        args.append(tokens)
        return fn(*args)

    return loss_fn
