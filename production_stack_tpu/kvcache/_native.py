"""ctypes loader for the native KV store (native/pskv.cpp).

Looks for ``native/build/libpskv.so`` relative to the repo root, building it
with ``make`` on first use when a toolchain is present. Every consumer falls
back to a pure-Python store when the library is unavailable
(store.HostMemoryStore picks the backend), so the stack stays importable on
machines without g++.
"""

import ctypes
import os
import subprocess
import threading
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libpskv.so")

_lock = threading.Lock()
_lib = None
_load_failed = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, i64, i32 = ctypes.c_uint64, ctypes.c_int64, ctypes.c_int
    p, cp = ctypes.c_void_p, ctypes.c_char_p
    lib.pskv_store_new.restype = p
    lib.pskv_store_new.argtypes = [u64]
    lib.pskv_store_free.argtypes = [p]
    lib.pskv_store_put.restype = i32
    lib.pskv_store_put.argtypes = [p, cp, ctypes.c_uint32, cp, u64]
    lib.pskv_store_get_size.restype = i64
    lib.pskv_store_get_size.argtypes = [p, cp, ctypes.c_uint32]
    lib.pskv_store_get.restype = i64
    lib.pskv_store_get.argtypes = [p, cp, ctypes.c_uint32,
                                   ctypes.c_char_p, u64]
    lib.pskv_store_exists.restype = i32
    lib.pskv_store_exists.argtypes = [p, cp, ctypes.c_uint32]
    lib.pskv_store_del.restype = i32
    lib.pskv_store_del.argtypes = [p, cp, ctypes.c_uint32]
    lib.pskv_store_clear.argtypes = [p]
    for name in ("bytes", "count", "hits", "misses", "evictions"):
        fn = getattr(lib, f"pskv_store_{name}")
        fn.restype = u64
        fn.argtypes = [p]
    lib.pskv_server_run.restype = i32
    lib.pskv_server_run.argtypes = [p, ctypes.c_uint16,
                                    ctypes.POINTER(ctypes.c_int),
                                    ctypes.POINTER(ctypes.c_int)]
    lib.pskv_server_run_on.restype = i32
    lib.pskv_server_run_on.argtypes = [p, cp, ctypes.c_uint16,
                                       ctypes.POINTER(ctypes.c_int),
                                       ctypes.POINTER(ctypes.c_int)]
    # psvi_*: flat inner-product vector index (native/vecindex.cpp),
    # consumed by router/semantic_cache.py
    fp = ctypes.POINTER(ctypes.c_float)
    ip = ctypes.POINTER(ctypes.c_int64)
    lib.psvi_new.restype = p
    lib.psvi_new.argtypes = [i32]
    lib.psvi_free.argtypes = [p]
    lib.psvi_dim.restype = i32
    lib.psvi_dim.argtypes = [p]
    lib.psvi_size.restype = u64
    lib.psvi_size.argtypes = [p]
    lib.psvi_add.restype = i32
    lib.psvi_add.argtypes = [p, fp, ctypes.c_int64]
    lib.psvi_remove.restype = i32
    lib.psvi_remove.argtypes = [p, ctypes.c_int64]
    lib.psvi_search.restype = i32
    lib.psvi_search.argtypes = [p, fp, i32, fp, ip]
    lib.psvi_save.restype = i32
    lib.psvi_save.argtypes = [p, cp]
    lib.psvi_load.restype = p
    lib.psvi_load.argtypes = [cp]
    return lib


def _make(*make_args: str) -> bool:
    if os.environ.get("PSKV_NO_BUILD"):
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, *make_args],
                       capture_output=True, timeout=120, check=True)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The loaded library, or None when unavailable (cached)."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB_PATH):
            _make("build/libpskv.so")
        try:
            _lib = _configure(ctypes.CDLL(_LIB_PATH))
        except AttributeError:
            # .so predates a symbol we now bind (build dir is gitignored,
            # so a stale library survives checkouts): force a rebuild and
            # retry once. dlopen caches by path, so the retry must map
            # the rebuilt library from a fresh temp copy (unlinking a
            # mapped .so is safe on Linux).
            if _make("-B", "build/libpskv.so"):
                import shutil
                import tempfile

                fd, tmp = tempfile.mkstemp(suffix=".so", prefix="libpskv-")
                os.close(fd)
                try:
                    shutil.copyfile(_LIB_PATH, tmp)
                    _lib = _configure(ctypes.CDLL(tmp))
                except (OSError, AttributeError):
                    _lib = None
                    _load_failed = True
                finally:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
            else:
                _load_failed = True
        except OSError:
            _load_failed = True
        return _lib


def server_binary() -> Optional[str]:
    """Path to the standalone pskv-server binary, building if needed."""
    path = os.path.join(_NATIVE_DIR, "build", "pskv-server")
    if not os.path.exists(path) and not os.environ.get("PSKV_NO_BUILD"):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "build/pskv-server"],
                           capture_output=True, timeout=120, check=True)
        except (OSError, subprocess.SubprocessError):
            return None
    return path if os.path.exists(path) else None


class NativeLruStore:
    """Thin OO wrapper over the C store (owns the handle)."""

    def __init__(self, capacity_bytes: int, lib: Optional[ctypes.CDLL] = None):
        self._lib = lib or load()
        if self._lib is None:
            raise RuntimeError("libpskv.so unavailable")
        self._h = self._lib.pskv_store_new(capacity_bytes)

    def put(self, key: bytes, val: bytes) -> bool:
        return self._lib.pskv_store_put(self._h, key, len(key), val,
                                        len(val)) == 0

    def get(self, key: bytes) -> Optional[bytes]:
        # size query + copy; retry if the value is concurrently replaced
        # with a larger one between the two calls (rc -2)
        for _ in range(4):
            n = self._lib.pskv_store_get_size(self._h, key, len(key))
            if n < 0:
                return None
            buf = ctypes.create_string_buffer(n)
            rc = self._lib.pskv_store_get(self._h, key, len(key), buf, n)
            if rc >= 0:
                return buf.raw[:rc]
        return None

    def exists(self, key: bytes) -> bool:
        return bool(self._lib.pskv_store_exists(self._h, key, len(key)))

    def delete(self, key: bytes) -> bool:
        return bool(self._lib.pskv_store_del(self._h, key, len(key)))

    def clear(self) -> None:
        self._lib.pskv_store_clear(self._h)

    def stats(self) -> dict:
        return {name: getattr(self._lib, f"pskv_store_{name}")(self._h)
                for name in ("bytes", "count", "hits", "misses",
                             "evictions")}

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.pskv_store_free(h)
            self._h = None
