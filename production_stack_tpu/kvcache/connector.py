"""Engine-side KV connector: moves KV chunks between TPU HBM and the tiers.

The reference engine gets this via vLLM's `--kv-transfer-config
'{"kv_connector":"LMCacheConnector","kv_role":"kv_both"}'` flag (reference:
helm/templates/deployment-vllm-multi.yaml:94-99); roles kv_producer /
kv_consumer split prefill and decode pods for disaggregated prefill
(reference: README.md:56 roadmap). Same contract here, TPU-native flow:

  consumer path: ``prefetch()`` runs on the server thread at request-add
    time — chain-hash the prompt, walk the tiers until the first miss, and
    materialize hits as host numpy arrays. ``on_admit()`` (engine loop, at
    slot assignment) only dispatches per-chunk device_put +
    dynamic_update_slice into the slot — no host I/O on the hot loop — and
    rewinds ``num_prefilled`` so prefill skips the cached prefix.

  producer path: ``on_finish()`` dispatches per-chunk slices out of the
    donated cache *synchronously* (XLA orders them before the next donating
    step, so slot reuse can't clobber the read) and hands the device arrays
    to a writer thread that blocks on D2H and writes through the tiers.

Chunk value layout: k_bytes + v_bytes, each [L, chunk, Hkv, D] in the
engine's kv dtype, C-order. The key namespace (chunks.model_fingerprint)
pins model geometry + dtype, so replicas sharing a remote tier interoperate
only when they'd produce byte-identical KV.
"""

import dataclasses
import queue
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from production_stack_tpu.kvcache.chunks import (ChunkHasher,
                                                 model_fingerprint)
from production_stack_tpu.kvcache.store import KVStore, make_store
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


@dataclasses.dataclass
class KVTransferConfig:
    """Parsed form of the engine's --kv-transfer-config JSON."""
    kv_role: str = "kv_both"            # kv_producer | kv_consumer | kv_both
    chunk_size: int = 256
    local_cpu_gb: float = 0.0           # LMCACHE_MAX_LOCAL_CPU_SIZE equiv
    local_disk_path: Optional[str] = None
    local_disk_gb: float = 16.0
    remote_url: Optional[str] = None    # tpukv://host:port
    # remote-tier failure bounds: a dead/hung cache server must degrade
    # to recompute, never stall admission — per-op socket timeouts plus
    # a breaker that short-circuits every remote call after
    # `remote_breaker_threshold` consecutive failures for
    # `remote_breaker_cooldown_s` (kvcache/store.RemoteStore)
    remote_connect_timeout_s: float = 2.0
    remote_io_timeout_s: float = 5.0
    remote_breaker_threshold: int = 3
    remote_breaker_cooldown_s: float = 10.0
    # hard wall-clock budget for one prefetch's tier walk: past it the
    # walk stops and the request prefills the rest (bounds TTFT under a
    # slow tier; the per-op timeouts bound each individual chunk read).
    # The budget is accounted per remaining chunk (chunk i of n must
    # land by budget*(i+1)/n), so one stalled chunk is cut at roughly
    # its fair share instead of consuming the whole wall and starving
    # every later fetch.
    prefetch_timeout_s: float = 2.0
    # pipelined prefetch: up to `prefetch_workers` chunk reads in
    # flight while earlier chunks are still being consumed (tier
    # latency overlaps tier latency instead of serializing into TTFT).
    # `prefetch_pipeline: false` falls back to one read at a time —
    # the fair-share deadline accounting applies either way.
    prefetch_pipeline: bool = True
    prefetch_workers: int = 4
    # per-tier codec choice, e.g. {"disk": "int8", "remote": "int4"}
    # (kvcache/codec.py: raw | int8 | int4 | fp8). Unmapped tiers stay
    # raw byte-exact. Encoded payloads are checksummed POST-encode, so
    # torn values still read as misses, never as dequantized garbage.
    tier_codecs: Optional[dict] = None

    @classmethod
    def from_dict(cls, d: dict) -> "KVTransferConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        ignored = {k: v for k, v in d.items() if k not in known}
        if ignored:
            logger.warning("kv_transfer_config: ignoring keys %s",
                           sorted(ignored))
        return cls(**{k: v for k, v in d.items() if k in known})

    @property
    def enabled(self) -> bool:
        return (self.local_cpu_gb > 0 or bool(self.local_disk_path)
                or bool(self.remote_url))

    @property
    def is_producer(self) -> bool:
        return self.kv_role in ("kv_producer", "kv_both")

    @property
    def is_consumer(self) -> bool:
        return self.kv_role in ("kv_consumer", "kv_both")


@dataclasses.dataclass
class Prefetch:
    """Host-side KV for a prompt's cached prefix, ready to inject."""
    keys: List[bytes]
    chunks: List[Tuple[np.ndarray, np.ndarray]]   # per-chunk (k, v)
    cached_tokens: int                            # capped, == num_prefilled
    # wall seconds the tier walk took (the kv_prefetch trace span —
    # the request paid this before it could even queue)
    wait_s: float = 0.0


class KVConnector:
    def __init__(self, runner, model_cfg, engine_cfg, cfg: KVTransferConfig,
                 store: Optional[KVStore] = None):
        self.runner = runner
        self.cfg = cfg
        self.chunk_size = cfg.chunk_size
        # namespace by the WIRE dtype, not the pool dtype: an int8 pool
        # extracts/injects full-precision (bf16) chunks, so int8 and
        # bf16 engines of the same model share one tier namespace —
        # the documented mixed-kvCacheDtype producer/consumer handoff
        wire_dtype = ("bfloat16" if runner.cache.quantized
                      else engine_cfg.kv_dtype)
        self.hasher = ChunkHasher(
            cfg.chunk_size,
            namespace=model_fingerprint(model_cfg, wire_dtype))
        self.store = store if store is not None else make_store(
            local_cpu_bytes=int(cfg.local_cpu_gb * (1 << 30)),
            local_disk_path=cfg.local_disk_path,
            local_disk_bytes=int(cfg.local_disk_gb * (1 << 30)),
            remote_url=cfg.remote_url,
            remote_connect_timeout_s=cfg.remote_connect_timeout_s,
            remote_io_timeout_s=cfg.remote_io_timeout_s,
            remote_breaker_threshold=cfg.remote_breaker_threshold,
            remote_breaker_cooldown_s=cfg.remote_breaker_cooldown_s)
        if self.store is None:
            raise ValueError("KV transfer enabled but no tier configured")
        shape = (model_cfg.num_layers, cfg.chunk_size,
                 model_cfg.num_kv_heads, model_cfg.head_dim_)
        self._chunk_shape = shape
        # bf16 numpy dtype comes from ml_dtypes (jax dependency)
        import ml_dtypes
        dtype_map = {"bfloat16": np.dtype(ml_dtypes.bfloat16),
                     "float32": np.dtype(np.float32)}
        # int8 pools extract/inject FULL-PRECISION chunks (the runner
        # dequantizes out and re-quantizes in, runner.extract_chunk /
        # inject_chunk) — tiers always hold portable bf16/f32 bytes
        kv_dtype = ("bfloat16" if runner.cache.quantized
                    else str(runner.cache.k.dtype))
        if kv_dtype not in dtype_map:
            raise ValueError(f"KV tiering does not support kv dtype "
                             f"{kv_dtype!r} (supported: {list(dtype_map)})")
        self._np_dtype = dtype_map[kv_dtype]
        self._chunk_bytes = int(np.prod(shape)) * self._np_dtype.itemsize
        if cfg.tier_codecs and store is None:
            # wrap each configured tier with its codec (kvplane): the
            # wrap happens on the tiers the connector itself built; an
            # injected test store is used as-is
            from production_stack_tpu.kvcache.codec import \
                apply_tier_codecs
            self.store = apply_tier_codecs(
                self.store, dict(cfg.tier_codecs),
                np_dtype=self._np_dtype,
                head_dim=model_cfg.head_dim_,
                chunk_body_bytes=2 * self._chunk_bytes)
        # shared pool for pipelined chunk reads (consumer role only)
        self._fetcher = None
        if cfg.is_consumer:
            from production_stack_tpu.kvcache.pipeline import \
                PipelinedFetcher
            self._fetcher = PipelinedFetcher(
                workers=cfg.prefetch_workers if cfg.prefetch_pipeline
                else 1)
        # writer thread: (keys, [(k_dev, v_dev)]) tuples; bounded so a slow
        # remote tier backpressures into drops, never into the engine loop
        self._save_q: "queue.Queue" = queue.Queue(maxsize=64)
        self._inflight = threading.Event()   # a popped item is being written
        self._stop = threading.Event()
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="kv-writer", daemon=True)
        self._writer.start()
        # engine-thread dedup of keys already queued/saved this process
        self._seen_keys: "dict[bytes, None]" = {}
        self._seen_cap = 65536
        self.queries = 0
        self.query_tokens = 0
        self.hit_tokens = 0
        # hits on chunks this process never published or fetched before:
        # another replica produced them (the cross-replica share the
        # kvshare rig measures). Re-fetches of a chunk this process has
        # already seen count as plain hits only.
        self.foreign_hit_tokens = 0
        self.chunk_hits = 0
        self.chunk_misses = 0       # walk-terminating misses
        self.bytes_loaded = 0       # tier bytes materialized by prefetch
        self.bytes_saved = 0        # tier bytes written through
        self.published_chunks = 0   # producer: chunks written through
        self.progress_published_chunks = 0   # ...of which mid-prefill
        self.rejected_chunks = 0    # size/checksum-invalid values
        self.prefetch_deadline_hits = 0
        # walks cut because ONE chunk blew its fair-share slice (the
        # per-remaining-chunk deadline accounting)
        self.prefetch_chunk_deadline_hits = 0
        # chunk reads issued while an earlier chunk was still being
        # consumed (pipelined overlap evidence)
        self.pipelined_fetches = 0
        self.dropped_saves = 0
        # chunk hits by the tier that served them (cpu / disk / remote)
        self.tier_hits: "dict[str, int]" = {}
        # kvplane migration accounting: chunks published by migrate_out
        # on this (source) replica / chunks pulled warm by the admin
        # warm endpoint on this (destination) replica
        self.migrated_chunks = 0
        self.warmed_chunks = 0
        # phase-latency sink (tracing.PhaseHistograms, ("phase",) keyed)
        # — the owning engine attaches its metrics.engine_phases so
        # kv_prefetch / kv_publish durations land next to the request
        # phases; None (tests constructing a bare connector) records
        # nothing
        self.phase_recorder = None

    # -- consumer path --------------------------------------------------

    def prefetch(self, prompt_tokens: Sequence[int],
                 salt: str = "") -> Optional[Prefetch]:
        """Fetch the longest cached chunk-prefix into host memory.

        Runs off the engine loop (server thread at request-add time). The
        last prompt token is never served from cache — prefill must compute
        at least one position to produce first-token logits — so hits are
        capped at len(prompt)-1. ``salt`` keys KV variants (LoRA adapter
        name) so adapter-colored chunks never serve other models.
        """
        if not self.cfg.is_consumer:
            return None
        import time
        n = len(prompt_tokens)
        self.queries += 1
        self.query_tokens += n
        keys = self.hasher.chunk_keys(prompt_tokens, salt=salt)
        chunks: List[Tuple[np.ndarray, np.ndarray]] = []
        hit_keys: List[bytes] = []
        foreign: List[bool] = []
        # hard budget on the whole walk, accounted per remaining chunk
        # and pipelined across `prefetch_workers` concurrent tier reads
        # (kvcache/pipeline.py): a slow tier costs bounded overlap, not
        # serialized TTFT, and one stalled chunk can no longer consume
        # the budget every later chunk was owed
        t0 = time.monotonic()
        fetched, walk = self._fetcher.fetch_walk(
            keys, self.store.get_with_tier,
            self.cfg.prefetch_timeout_s)
        if walk.deadline_hits or walk.chunk_deadline_hits:
            self.prefetch_deadline_hits += 1
            self.prefetch_chunk_deadline_hits += walk.chunk_deadline_hits
        elif len(fetched) < len(keys):
            self.chunk_misses += 1
        self.pipelined_fetches += walk.pipelined_fetches
        for key, val, tier in fetched:
            kv = self._deserialize(key, val)
            if kv is None:
                break
            self.chunk_hits += 1
            if tier:
                self.tier_hits[tier] = self.tier_hits.get(tier, 0) + 1
            self.bytes_loaded += len(val)
            foreign.append(key not in self._seen_keys)
            chunks.append(kv)
            hit_keys.append(key)
        wait_s = time.monotonic() - t0
        if self.phase_recorder is not None:
            self.phase_recorder.observe("kv_prefetch", wait_s)
        if not chunks:
            return None
        cached = min(len(chunks) * self.chunk_size, n - 1)
        self.hit_tokens += cached
        for i, is_foreign in enumerate(foreign):
            if is_foreign:
                self.foreign_hit_tokens += max(
                    0, min(self.chunk_size, cached - i * self.chunk_size))
        return Prefetch(keys=hit_keys, chunks=chunks, cached_tokens=cached,
                        wait_s=wait_s)

    def inject(self, prefetch: Prefetch, slot: int) -> None:
        """Dispatch cached chunks into the slot (engine loop; device work
        is async, ordered before the next cache-donating step)."""
        for i, (k, v) in enumerate(prefetch.chunks):
            self.runner.inject_chunk(slot, i * self.chunk_size, k, v)
        self.mark_seen(prefetch.keys)

    def mark_seen(self, keys) -> None:
        """Record keys the tier already holds (skip re-publish at
        finish) — also used when the HBM prefix pool wins admission and
        the prefetched chunks are dropped without injection."""
        for key in keys:
            self._mark_seen(key)

    # -- producer path --------------------------------------------------

    def on_prefill_progress(self, seq, salt: str = "") -> None:
        """Publish full PROMPT chunks as soon as they are prefilled.

        Disaggregated prefill overlap: the decode engine can start
        pulling the prefix while the producer is still chunk-prefilling
        a long prompt — without this, KV only became visible at
        ``on_finish``, serializing the two pools. Chunk keys dedup via
        _seen_keys, so the later on_finish pass skips everything
        published here.
        """
        if not self.cfg.is_producer:
            return
        self._publish(seq, seq.prompt_tokens[:seq.num_prefilled],
                      getattr(seq, "slot", -1), salt, progress=True)

    def on_finish(self, seq, salt: str = "") -> None:
        """Queue full-chunk KV of a finished sequence for write-through.

        The final sampled token is excluded: decode writes KV for its
        *input* token, and a finished sequence's last token is never fed
        back — its KV position was never computed, so a chunk covering it
        would poison the shared cache with stale slot contents.
        """
        if not self.cfg.is_producer:
            return
        self._publish(seq, (seq.prompt_tokens + seq.output_tokens)[:-1],
                      getattr(seq, "slot", -1), salt)

    def on_migrate(self, seq, salt: str = "") -> List[bytes]:
        """Publish a LIVE sequence's computed full chunks for kvplane
        migration and return every key of that computed range (already
        published ones included — the destination warms them all).

        Mid-prefill victims publish only their prefilled prompt
        prefix; decoding victims publish like ``on_finish`` (the last
        sampled token's KV position was never computed). Runs on the
        engine loop under the engine lock, same as
        ``on_prefill_progress`` — the write-through itself happens on
        the writer thread, and ``flush()`` afterwards makes it tier-
        visible before the planner re-homes routing."""
        if not self.cfg.is_producer:
            return []
        if seq.num_prefilled < len(seq.prompt_tokens):
            tokens = seq.prompt_tokens[:seq.num_prefilled]
        else:
            tokens = (seq.prompt_tokens + seq.output_tokens)[:-1]
        n_chunks = self.hasher.num_full_chunks(len(tokens))
        if n_chunks == 0:
            return []
        keys = self.hasher.chunk_keys(tokens, salt=salt)[:n_chunks]
        self._publish(seq, tokens, getattr(seq, "slot", -1), salt)
        self.migrated_chunks += len(keys)
        return keys

    def _publish(self, seq, tokens, slot: int, salt: str,
                 progress: bool = False) -> None:
        n_chunks = self.hasher.num_full_chunks(len(tokens))
        if n_chunks == 0 or slot < 0:
            return
        # the key chain is cached on the sequence and extended
        # incrementally — progressive publish runs once per prefill
        # chunk, and restarting the chain each time would be quadratic
        state = getattr(seq, "kv_publish_state", None)
        start_chunk = state[0] if state else 0
        new_keys, state = self.hasher.chain_keys(tokens, salt=salt,
                                                 state=state)
        seq.kv_publish_state = state
        work = []
        for i, key in enumerate(new_keys, start=start_chunk):
            if key in self._seen_keys:
                continue
            k_dev, v_dev = self.runner.extract_chunk(
                slot, i * self.chunk_size, self.chunk_size)
            # the progress flag rides to the writer: a chunk only
            # counts as progress-published once its put SUCCEEDS (a
            # dropped batch or failed save must not satisfy the
            # overlap evidence the disagg rig gates on)
            work.append((key, k_dev, v_dev, progress))
            self._mark_seen(key)
        if not work:
            return
        try:
            self._save_q.put_nowait(work)
        except queue.Full:
            self.dropped_saves += len(work)
            for key, _, _, _ in work:   # allow a retry on a later finish
                self._seen_keys.pop(key, None)

    def _writer_loop(self) -> None:
        while not self._stop.is_set():
            try:
                work = self._save_q.get(timeout=0.2)
            except queue.Empty:
                continue
            self._inflight.set()
            import time as _time
            t0 = _time.monotonic()
            try:
                for key, k_dev, v_dev, progress in work:
                    try:
                        val = self._serialize(k_dev, v_dev)
                        if self.store.put(key, val):
                            self.bytes_saved += len(val)
                            self.published_chunks += 1
                            if progress:
                                # tier-visible while later chunks were
                                # still prefilling (disagg overlap)
                                self.progress_published_chunks += 1
                    except Exception as e:   # never kill the writer
                        logger.warning("KV save failed: %s", e)
            finally:
                self._inflight.clear()
                if self.phase_recorder is not None:
                    # publish latency per write-through batch: D2H sync
                    # + serialization + tier puts, on the writer thread
                    # — the cost a slow tier charges the publish path
                    self.phase_recorder.observe(
                        "kv_publish", _time.monotonic() - t0)

    # -- serialization ---------------------------------------------------

    # trailing full-chunk integrity digest: a torn or bit-flipped value
    # surfacing from any tier (a killed replica mid-publish, a corrupt
    # disk file) must read as a MISS, never inject garbage KV
    _DIGEST_BYTES = 8

    @staticmethod
    def _digest(data) -> bytes:
        import hashlib
        return hashlib.blake2b(
            data, digest_size=KVConnector._DIGEST_BYTES).digest()

    def _serialize(self, k_dev, v_dev) -> bytes:
        k = np.asarray(k_dev)     # blocks until D2H completes
        v = np.asarray(v_dev)
        body = k.tobytes() + v.tobytes()
        return body + self._digest(body)

    def _deserialize(self, key: bytes, val: bytes) -> \
            Optional[Tuple[np.ndarray, np.ndarray]]:
        want = 2 * self._chunk_bytes + self._DIGEST_BYTES
        if len(val) != want:
            logger.warning("KV chunk size mismatch: %d != %d (evicting "
                           "%s)", len(val), want, key.hex()[:16])
            self._reject(key)
            return None
        body, digest = val[:-self._DIGEST_BYTES], val[-self._DIGEST_BYTES:]
        if self._digest(body) != digest:
            logger.warning("KV chunk checksum mismatch (evicting %s)",
                           key.hex()[:16])
            self._reject(key)
            return None
        k = np.frombuffer(val, self._np_dtype, count=int(
            np.prod(self._chunk_shape))).reshape(self._chunk_shape)
        v = np.frombuffer(val, self._np_dtype, offset=self._chunk_bytes,
                          count=int(np.prod(self._chunk_shape))).reshape(
                              self._chunk_shape)
        return k, v

    def _reject(self, key: bytes) -> None:
        """Invalid tier value: count it and delete the poisoned key so
        the next producer pass can republish a good copy."""
        self.rejected_chunks += 1
        try:
            self.store.delete(key)
        except Exception:      # deletion is best-effort cleanup
            pass
        self._seen_keys.pop(key, None)

    # -- misc ------------------------------------------------------------

    def _mark_seen(self, key: bytes) -> None:
        self._seen_keys[key] = None
        while len(self._seen_keys) > self._seen_cap:
            self._seen_keys.pop(next(iter(self._seen_keys)))

    @property
    def hit_rate(self) -> float:
        return self.hit_tokens / self.query_tokens if self.query_tokens \
            else 0.0

    def remote_breaker_open(self) -> bool:
        """True while the remote tier (if any) is being skipped."""
        from production_stack_tpu.kvcache.store import (RemoteStore,
                                                        TieredStore)
        stores = self.store.tiers if isinstance(self.store, TieredStore) \
            else [self.store]
        # a codec-wrapped tier hides the RemoteStore one level down
        stores = [getattr(s, "inner", s) for s in stores]
        return any(s.breaker_open() for s in stores
                   if isinstance(s, RemoteStore))

    def codec_stats(self) -> list:
        """Per-tier codec accounting ({tier, codec, bytes_in/out,
        rejects}) — empty when no tier_codecs are configured."""
        from production_stack_tpu.kvcache.codec import codec_stats_of
        return codec_stats_of(self.store)

    def warm_keys(self, keys: List[bytes]) -> Tuple[int, int]:
        """Pull raw chunk values for ``keys`` through the tier walk so
        hits promote into this replica's fastest tier (the kvplane
        migration destination path: the planner hands over the keys the
        source's migrate_out published). No deserialization — the
        promotion side effect IS the work. Returns (warmed, missed)."""
        warmed = missed = 0
        for key in keys:
            val, _tier = self.store.get_with_tier(key)
            if val is None:
                missed += 1
            else:
                warmed += 1
                self.warmed_chunks += 1
                self._mark_seen(key)
        return warmed, missed

    def tier_stats(self) -> dict:
        """{tier_name: {bytes, count, ...}} for the occupancy gauges."""
        try:
            return self.store.tier_stats()
        except Exception as e:    # a sick tier must not break /load
            logger.warning("KV tier stats failed: %s", e)
            return {}

    def stats_report(self) -> dict:
        """Counters surfaced on /load (and deltas fed to /metrics):
        everything the cache-aware router and the kvshare rig read."""
        return {
            # the engine's disagg role: the router's pool wiring and
            # the disagg rig read it off /load for topology checks
            "role": self.cfg.kv_role,
            "queries": self.queries,
            "query_tokens": self.query_tokens,
            "hit_tokens": self.hit_tokens,
            "foreign_hit_tokens": self.foreign_hit_tokens,
            "hit_rate": round(self.hit_rate, 4),
            "chunk_hits": self.chunk_hits,
            "chunk_misses": self.chunk_misses,
            "bytes_loaded": self.bytes_loaded,
            "bytes_saved": self.bytes_saved,
            "published_chunks": self.published_chunks,
            "progress_published_chunks": self.progress_published_chunks,
            "rejected_chunks": self.rejected_chunks,
            "dropped_saves": self.dropped_saves,
            "prefetch_deadline_hits": self.prefetch_deadline_hits,
            "prefetch_chunk_deadline_hits":
                self.prefetch_chunk_deadline_hits,
            "pipelined_fetches": self.pipelined_fetches,
            "migrated_chunks": self.migrated_chunks,
            "warmed_chunks": self.warmed_chunks,
            "codecs": self.codec_stats(),
            "tier_hits": dict(self.tier_hits),
            "remote_breaker_open": self.remote_breaker_open(),
            # remote occupancy lives on the cache server's own surface;
            # its local entry carries only breaker state (no bytes)
            "tiers": {name: {"bytes": st.get("bytes", 0),
                             "count": st.get("count", 0)}
                      for name, st in self.tier_stats().items()
                      if "bytes" in st},
        }

    def flush(self, timeout: float = 30.0) -> None:
        """Block until queued saves are written (tests/shutdown)."""
        import time
        deadline = time.monotonic() + timeout
        while (not self._save_q.empty() or self._inflight.is_set()) \
                and time.monotonic() < deadline:
            time.sleep(0.01)

    def close(self) -> None:
        self.flush(timeout=5.0)
        self._stop.set()
        self._writer.join(timeout=5.0)
        if self._fetcher is not None:
            self._fetcher.close()
        self.store.close()
