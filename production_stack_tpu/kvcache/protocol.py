"""TPKV binary wire protocol — shared by the Python client/server and the
native C++ server (native/pskv.cpp must stay in sync).

The reference's remote-KV tier speaks LMCache's ``lm://host:port`` protocol
(reference: helm/templates/_helpers.tpl:166-168 formats the URL;
deployment-vllm-multi.yaml:167-170 passes LMCACHE_REMOTE_URL/SERDE). TPKV is
this stack's equivalent: a length-prefixed request/response frame over TCP,
URL scheme ``tpukv://host:port``.

Frame layout (all integers big-endian):
  request:  u32 magic 'TPKV' | u8 op | u16 key_len | u64 val_len
            | key bytes | val bytes
  response: u8 status (0 OK, 1 MISSING, 2 ERROR) | u64 val_len | val bytes
"""

import struct
from typing import Optional, Tuple
from urllib.parse import urlparse

MAGIC = 0x54504B56  # "TPKV"

OP_PUT = 1
OP_GET = 2
OP_EXISTS = 3
OP_DEL = 4
OP_STATS = 5
OP_PING = 6

STATUS_OK = 0
STATUS_MISSING = 1
STATUS_ERROR = 2

MAX_VAL = 1 << 32  # 4 GiB frame cap (matches native server)

_REQ_HDR = struct.Struct(">IBHQ")
_RESP_HDR = struct.Struct(">BQ")

REQ_HDR_SIZE = _REQ_HDR.size    # 15
RESP_HDR_SIZE = _RESP_HDR.size  # 9


def encode_request(op: int, key: bytes = b"", val: bytes = b"") -> bytes:
    if len(val) > MAX_VAL:
        raise ValueError(f"value too large: {len(val)}")
    return _REQ_HDR.pack(MAGIC, op, len(key), len(val)) + key + val


def decode_request_header(hdr: bytes) -> Tuple[int, int, int]:
    """-> (op, key_len, val_len); raises on bad magic/oversize."""
    magic, op, klen, vlen = _REQ_HDR.unpack(hdr)
    if magic != MAGIC:
        raise ValueError(f"bad magic {magic:#x}")
    if vlen > MAX_VAL:
        raise ValueError(f"frame too large: {vlen}")
    return op, klen, vlen


def encode_response(status: int, val: bytes = b"") -> bytes:
    return _RESP_HDR.pack(status, len(val)) + val


def decode_response_header(hdr: bytes) -> Tuple[int, int]:
    """-> (status, val_len)."""
    return _RESP_HDR.unpack(hdr)


def parse_url(url: str) -> Tuple[str, int]:
    """'tpukv://host:port' -> (host, port). Accepts legacy 'lm://' too."""
    parsed = urlparse(url)
    if parsed.scheme not in ("tpukv", "lm"):
        raise ValueError(f"unsupported KV remote scheme: {url!r} "
                         "(expected tpukv://host:port)")
    if not parsed.hostname or not parsed.port:
        raise ValueError(f"remote URL needs host:port, got {url!r}")
    return parsed.hostname, parsed.port


def format_url(host: str, port: int) -> str:
    return f"tpukv://{host}:{port}"
