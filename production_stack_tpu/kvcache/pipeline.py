"""Pipelined tier reads for the consumer prefetch walk (kvplane
pillar 3).

The r11 walk was strictly serial with a single wall-clock wall: chunk
reads issued one at a time, and the FIRST slow chunk could consume the
entire ``prefetch_timeout_s`` budget — every later chunk then broke at
the wall having never been tried, so a slow-not-dead tier serialized
straight into TTFT. Two changes:

1. **Pipelining** — up to ``workers`` chunk reads are in flight at
   once (a bounded submit window ahead of the in-order consumer), so
   tier latency overlaps tier latency: while chunk ``i`` is still on
   the wire, ``i+1 .. i+window`` are already being read. Results are
   consumed strictly in key order (the chain property: chunk ``i+1``
   is useless without ``i``), and the walk still stops at the first
   miss. Remote reads parallelize naturally — ``RemoteStore`` holds
   per-thread sockets.

2. **Per-chunk deadline accounting** (the budget fix) — chunk ``i`` of
   ``n`` must complete by ``t0 + budget * (i+1) / n``: a cumulative
   fair-share deadline instead of one shared wall. A single stalled
   chunk is now abandoned after roughly ``budget / n`` (plus whatever
   slack faster earlier chunks banked), instead of eating the whole
   budget; a uniformly slow tier keeps all of its budget because early
   chunks that finish fast roll their slack forward. The total wall
   stays <= ``budget`` — the per-chunk deadlines are monotone and the
   last one IS the old wall.

A fetch abandoned on deadline keeps running on its pool thread until
the store's own per-op timeout fires (the threads are few and the
store ops are individually bounded); its result is discarded.
"""

import concurrent.futures
import time
from typing import Callable, List, Optional, Tuple

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class WalkStats:
    """What one pipelined walk did (folded into connector counters)."""

    __slots__ = ("deadline_hits", "chunk_deadline_hits",
                 "pipelined_fetches", "wait_s")

    def __init__(self):
        self.deadline_hits = 0          # whole-walk budget exhausted
        self.chunk_deadline_hits = 0    # one chunk blew its fair share
        self.pipelined_fetches = 0      # reads issued ahead of consume
        self.wait_s = 0.0


class PipelinedFetcher:
    """A small shared thread pool + the in-order fair-deadline walk."""

    def __init__(self, workers: int = 4):
        self.workers = max(1, int(workers))
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="kv-prefetch")

    def fetch_walk(self, keys: List[bytes],
                   get_fn: Callable[[bytes], Tuple[Optional[bytes],
                                                   Optional[str]]],
                   budget_s: float,
                   ) -> Tuple[List[Tuple[bytes, bytes, Optional[str]]],
                              WalkStats]:
        """Walk ``keys`` in order; return ``[(key, val, tier)]`` for
        the leading run of hits plus walk stats. Stops at the first
        miss, error, or blown deadline."""
        stats = WalkStats()
        t0 = time.monotonic()
        n = len(keys)
        if n == 0:
            return [], stats
        window = min(self.workers * 2, n)
        futures = {}

        def submit(i: int) -> None:
            futures[i] = self._pool.submit(get_fn, keys[i])

        for i in range(window):
            submit(i)
        stats.pipelined_fetches = window - 1
        results: List[Tuple[bytes, bytes, Optional[str]]] = []
        try:
            for i in range(n):
                chunk_deadline = t0 + budget_s * (i + 1) / n
                timeout = chunk_deadline - time.monotonic()
                if timeout <= 0:
                    stats.deadline_hits += 1
                    break
                try:
                    val, tier = futures[i].result(timeout=timeout)
                except concurrent.futures.TimeoutError:
                    stats.chunk_deadline_hits += 1
                    break
                except Exception as e:  # a sick tier reads as a miss
                    logger.warning("KV prefetch read failed: %s", e)
                    break
                if val is None:
                    break
                results.append((keys[i], val, tier))
                nxt = i + window
                if nxt < n:
                    submit(nxt)
                    stats.pipelined_fetches += 1
        finally:
            for f in futures.values():
                f.cancel()
        stats.wait_s = time.monotonic() - t0
        return results, stats

    def close(self) -> None:
        self._pool.shutdown(wait=False)
