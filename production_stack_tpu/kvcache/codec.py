"""Per-tier KV chunk codecs: raw / int8 / int4 / fp8 (kvplane pillar 2).

The r11 tier stores raw wire-dtype bytes everywhere, so a 64 GB cache
server holds 64 GB of KV no matter how cold the tier is. LMCache's
observation (PAPERS.md) is that the slow tiers tolerate lossy codecs:
decode bandwidth is not the bottleneck there, capacity is. This module
adds a codec boundary per tier — raw bf16 in the HBM-adjacent host
tier, quantized on disk / remote — without touching the connector wire
format: ``CodecStore`` wraps one tier, encodes on ``put`` and decodes
on ``get``, and re-appends the connector's own full-chunk digest after
decode so ``KVConnector._deserialize`` still performs the exact r11
integrity check on what the engine will actually consume.

Torn-value safety is re-established POST-encode: every encoded payload
carries its own trailing blake2b-8 over the encoded bytes (header
included), so a replica killed mid-PUT or a corrupt disk block reads
as a MISS (counted + evicted), never as silently dequantized garbage.

Encoded payload layout::

    b"KQ" | codec_id (1B) | version (1B) | codec body | blake2b-8

Codecs (ratios for the stack's default D=64 head dim):

- ``raw``  — identity (1.0x), still checksummed.
- ``int8`` — symmetric per-row absmax over the head dim, f32 scales
  (~1.9x on bf16).
- ``int4`` — symmetric group quantization, 32 values per f32 scale,
  two values per byte (~3.2x on bf16) — the tier-capacity headline.
- ``fp8``  — e4m3 cast via ml_dtypes (2.0x), gated on the installed
  ml_dtypes exposing ``float8_e4m3fn``; absent -> ValueError at
  config time, never a silent fallback.

All codecs are pure numpy; nothing here imports JAX.
"""

import hashlib
import struct
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

MAGIC = b"KQ"
VERSION = 1
DIGEST_BYTES = 8
HEADER = struct.Struct("<2sBB")

try:  # fp8 availability depends on the installed ml_dtypes
    import ml_dtypes
    _FP8_DTYPE = np.dtype(ml_dtypes.float8_e4m3fn)
except (ImportError, AttributeError):  # pragma: no cover - env detail
    _FP8_DTYPE = None

_INT4_GROUP = 32


def _digest(payload: bytes) -> bytes:
    return hashlib.blake2b(payload, digest_size=DIGEST_BYTES).digest()


class Codec:
    """Encode/decode one chunk body (the connector's ``k+v`` bytes,
    WITHOUT its trailing digest). ``decode`` must reproduce the exact
    original byte length; lossy codecs reproduce approximate values."""

    name = "raw"
    codec_id = 0

    def __init__(self, np_dtype: np.dtype, head_dim: int):
        self.np_dtype = np.dtype(np_dtype)
        self.head_dim = int(head_dim)

    def encode(self, body: bytes) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes, body_len: int) -> bytes:
        raise NotImplementedError

    # ---- shared helpers ------------------------------------------------
    def _rows(self, body: bytes) -> np.ndarray:
        """Body as float32 rows over the head dim (the natural scale
        granularity: one (layer, position, head) vector per row)."""
        arr = np.frombuffer(body, dtype=self.np_dtype)
        if arr.size % self.head_dim:
            raise ValueError(
                f"body of {arr.size} elems not divisible by head_dim "
                f"{self.head_dim}")
        return arr.reshape(-1, self.head_dim).astype(np.float32)

    def _from_f32(self, arr: np.ndarray) -> bytes:
        return np.ascontiguousarray(
            arr.astype(self.np_dtype)).tobytes()


class RawCodec(Codec):
    name = "raw"
    codec_id = 0

    def encode(self, body: bytes) -> bytes:
        return body

    def decode(self, data: bytes, body_len: int) -> bytes:
        if len(data) != body_len:
            raise ValueError(f"raw payload {len(data)}B != body "
                             f"{body_len}B")
        return data


class Int8Codec(Codec):
    """Symmetric absmax int8, one f32 scale per head-dim row."""

    name = "int8"
    codec_id = 1

    def encode(self, body: bytes) -> bytes:
        rows = self._rows(body)
        scale = np.abs(rows).max(axis=1) / 127.0
        scale = np.maximum(scale, 1e-12).astype(np.float32)
        q = np.clip(np.rint(rows / scale[:, None]), -127, 127) \
            .astype(np.int8)
        return scale.tobytes() + q.tobytes()

    def decode(self, data: bytes, body_len: int) -> bytes:
        itemsize = self.np_dtype.itemsize
        n_rows = body_len // (self.head_dim * itemsize)
        scale_bytes = n_rows * 4
        if len(data) != scale_bytes + n_rows * self.head_dim:
            raise ValueError("int8 payload size mismatch")
        scale = np.frombuffer(data[:scale_bytes], dtype=np.float32)
        q = np.frombuffer(data[scale_bytes:], dtype=np.int8) \
            .reshape(n_rows, self.head_dim).astype(np.float32)
        return self._from_f32(q * scale[:, None])


class Int4Codec(Codec):
    """Symmetric group quantization: 32 values per f32 scale, two
    4-bit values packed per byte. ~3.2x over bf16 — the codec the
    >=2x tier-capacity gate runs with."""

    name = "int4"
    codec_id = 2

    def encode(self, body: bytes) -> bytes:
        flat = np.frombuffer(body, dtype=self.np_dtype) \
            .astype(np.float32)
        if flat.size % _INT4_GROUP:
            raise ValueError(
                f"body of {flat.size} elems not divisible by int4 "
                f"group {_INT4_GROUP}")
        groups = flat.reshape(-1, _INT4_GROUP)
        scale = np.abs(groups).max(axis=1) / 7.0
        scale = np.maximum(scale, 1e-12).astype(np.float32)
        q = np.clip(np.rint(groups / scale[:, None]), -7, 7) \
            .astype(np.int8) + 8          # [1, 15]; 0 never produced
        packed = (q[:, 0::2] << 4 | q[:, 1::2]).astype(np.uint8)
        return scale.tobytes() + packed.tobytes()

    def decode(self, data: bytes, body_len: int) -> bytes:
        itemsize = self.np_dtype.itemsize
        n = body_len // itemsize
        n_groups = n // _INT4_GROUP
        scale_bytes = n_groups * 4
        if len(data) != scale_bytes + n // 2:
            raise ValueError("int4 payload size mismatch")
        scale = np.frombuffer(data[:scale_bytes], dtype=np.float32)
        packed = np.frombuffer(data[scale_bytes:], dtype=np.uint8) \
            .reshape(n_groups, _INT4_GROUP // 2)
        q = np.empty((n_groups, _INT4_GROUP), dtype=np.int8)
        q[:, 0::2] = (packed >> 4) & 0x0F
        q[:, 1::2] = packed & 0x0F
        vals = (q.astype(np.float32) - 8.0) * scale[:, None]
        return self._from_f32(vals)


class Fp8Codec(Codec):
    """Straight e4m3 cast (2.0x over bf16). Requires ml_dtypes with
    float8_e4m3fn."""

    name = "fp8"
    codec_id = 3

    def __init__(self, np_dtype: np.dtype, head_dim: int):
        super().__init__(np_dtype, head_dim)
        if _FP8_DTYPE is None:
            raise ValueError(
                "codec 'fp8' needs ml_dtypes.float8_e4m3fn, which "
                "this environment's ml_dtypes does not provide")

    def encode(self, body: bytes) -> bytes:
        arr = np.frombuffer(body, dtype=self.np_dtype) \
            .astype(np.float32)
        return arr.astype(_FP8_DTYPE).tobytes()

    def decode(self, data: bytes, body_len: int) -> bytes:
        n = body_len // self.np_dtype.itemsize
        if len(data) != n:
            raise ValueError("fp8 payload size mismatch")
        arr = np.frombuffer(data, dtype=_FP8_DTYPE).astype(np.float32)
        return self._from_f32(arr)


CODECS = {c.name: c for c in (RawCodec, Int8Codec, Int4Codec, Fp8Codec)}
_BY_ID = {c.codec_id: c for c in CODECS.values()}


def codec_names() -> List[str]:
    names = [n for n in CODECS if n != "fp8" or _FP8_DTYPE is not None]
    return sorted(names)


def make_codec(name: str, *, np_dtype, head_dim: int) -> Codec:
    if name not in CODECS:
        raise ValueError(f"unknown KV codec {name!r} "
                         f"(have: {sorted(CODECS)})")
    return CODECS[name](np_dtype, head_dim)


def encode_payload(codec: Codec, body: bytes) -> bytes:
    """Self-describing encoded payload: header + codec body +
    blake2b-8 over everything before the digest."""
    payload = HEADER.pack(MAGIC, codec.codec_id, VERSION) \
        + codec.encode(body)
    return payload + _digest(payload)


def decode_payload(codec: Codec, data: bytes,
                   body_len: int) -> Optional[bytes]:
    """Verify + decode one encoded payload. Returns the reconstructed
    body (exactly ``body_len`` bytes) or None for anything torn,
    truncated, or foreign — the caller treats None as a miss."""
    if len(data) < HEADER.size + DIGEST_BYTES:
        return None
    if _digest(data[:-DIGEST_BYTES]) != data[-DIGEST_BYTES:]:
        return None
    magic, codec_id, version = HEADER.unpack_from(data)
    if magic != MAGIC or version != VERSION:
        return None
    if codec_id != codec.codec_id:
        # a tier whose configured codec changed across restarts reads
        # its old entries as misses; a later publish heals them
        return None
    try:
        body = codec.decode(data[HEADER.size:-DIGEST_BYTES], body_len)
    except (ValueError, TypeError):
        return None
    return body if len(body) == body_len else None


class CodecStore:
    """One tier wrapped with a codec.

    Values crossing this boundary are the connector's serialized
    chunks (``body + blake2b-8(body)``). ``put`` strips the connector
    digest, encodes the body, and stores the checksummed encoded
    payload; ``get`` verifies the post-encode checksum, decodes, and
    re-appends a fresh connector digest over the decoded body — so the
    connector's own ``_deserialize`` integrity check is preserved
    end to end, and ``TieredStore`` hit-promotion between tiers with
    different codecs re-encodes naturally (each tier's ``put`` sees
    plain serialized chunks).

    Counters (scraped into ``tpu:kvplane_codec_*``):

    - ``bytes_in`` / ``bytes_out`` — logical body bytes entering the
      encoder vs encoded bytes written (the compression accounting).
    - ``decoded_chunks`` — successful decodes on the read path.
    - ``rejects`` — torn/corrupt encoded payloads read as misses
      (the key is deleted so a later publish heals it).
    """

    def __init__(self, inner, codec: Codec, chunk_body_bytes: int):
        self.inner = inner
        self.codec = codec
        self.chunk_body_bytes = int(chunk_body_bytes)
        self.bytes_in = 0
        self.bytes_out = 0
        self.decoded_chunks = 0
        self.rejects = 0

    @property
    def tier_name(self) -> str:
        return self.inner.tier_name

    def _strip(self, value: bytes) -> Optional[bytes]:
        body = value[:-DIGEST_BYTES]
        if len(value) < DIGEST_BYTES or _digest(body) \
                != value[-DIGEST_BYTES:]:
            return None
        return body

    def put(self, key: bytes, value: bytes) -> bool:
        body = self._strip(value)
        if body is None:
            # never encode a value that is already torn — dropping it
            # here is what keeps a mid-migration kill a miss, not a
            # quantized copy of garbage
            return False
        payload = encode_payload(self.codec, body)
        self.bytes_in += len(body)
        self.bytes_out += len(payload)
        return self.inner.put(key, payload)

    def get(self, key: bytes) -> Optional[bytes]:
        data = self.inner.get(key)
        if data is None:
            return None
        body = decode_payload(self.codec, data, self.chunk_body_bytes)
        if body is None:
            self.rejects += 1
            try:
                self.inner.delete(key)
            except Exception:  # noqa: BLE001 - best-effort eviction
                pass
            return None
        self.decoded_chunks += 1
        return body + _digest(body)

    def get_with_tier(self, key: bytes):
        val = self.get(key)
        return (val, self.tier_name) if val is not None \
            else (None, None)

    def exists(self, key: bytes) -> bool:
        return self.inner.exists(key)

    def delete(self, key: bytes) -> bool:
        return self.inner.delete(key)

    def stats(self) -> Dict:
        return self.inner.stats()

    def tier_stats(self) -> List[Dict]:
        return self.inner.tier_stats()

    def codec_stats(self) -> Dict:
        return {"tier": self.tier_name, "codec": self.codec.name,
                "bytes_in": self.bytes_in, "bytes_out": self.bytes_out,
                "decoded_chunks": self.decoded_chunks,
                "rejects": self.rejects}

    def close(self) -> None:
        self.inner.close()


def apply_tier_codecs(store, tier_codecs: Dict[str, str], *,
                      np_dtype, head_dim: int,
                      chunk_body_bytes: int):
    """Wrap a store (or each tier of a TieredStore) per the
    ``{tier_name: codec_name}`` map. Unmapped tiers stay unwrapped
    (identical to ``raw`` minus the header/checksum overhead), so the
    default host tier keeps byte-exact r11 behavior."""
    from production_stack_tpu.kvcache.store import TieredStore

    def wrap(tier):
        name = tier_codecs.get(tier.tier_name)
        if not name:
            return tier
        codec = make_codec(name, np_dtype=np_dtype, head_dim=head_dim)
        return CodecStore(tier, codec, chunk_body_bytes)

    for tier_name in tier_codecs:
        if tier_name not in ("cpu", "disk", "remote"):
            raise ValueError(f"tier_codecs names unknown tier "
                             f"{tier_name!r} (have: cpu, disk, remote)")
    if isinstance(store, TieredStore):
        return TieredStore([wrap(t) for t in store.tiers])
    return wrap(store)


def codec_stats_of(store) -> List[Dict]:
    """Flat list of codec_stats() dicts from every CodecStore layer."""
    from production_stack_tpu.kvcache.store import TieredStore
    out: List[Dict] = []
    if isinstance(store, CodecStore):
        out.append(store.codec_stats())
    elif isinstance(store, TieredStore):
        for t in store.tiers:
            if isinstance(t, CodecStore):
                out.append(t.codec_stats())
    return out
