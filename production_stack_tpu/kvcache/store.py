"""Tiered byte stores for KV chunks.

Tier layout mirrors the reference's LMCache wiring (reference:
deployment-vllm-multi.yaml:154-178): host DRAM (LMCACHE_LOCAL_CPU +
LMCACHE_MAX_LOCAL_CPU_SIZE), local disk (LMCACHE_LOCAL_DISK), and a remote
shared server (LMCACHE_REMOTE_URL). Values are opaque bytes — serialization
of KV chunks lives in connector.py; the stores compose:

    TieredStore([HostMemoryStore, DiskStore, RemoteStore])

get() probes tiers in order and promotes hits into faster tiers; put()
writes through to every tier. The host tier uses the native C++ LRU
(native/pskv.cpp) when available.
"""

import collections
import os
import socket
import threading
from abc import ABC, abstractmethod
from typing import Dict, List, Optional

from production_stack_tpu.kvcache import protocol
from production_stack_tpu.kvcache._native import NativeLruStore, load
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class KVStore(ABC):
    """get/put/exists/delete over opaque byte values."""

    #: short name used as the ``tier`` label on occupancy gauges
    tier_name = "unknown"

    @abstractmethod
    def get(self, key: bytes) -> Optional[bytes]: ...

    def get_with_tier(self, key: bytes):
        """``(value, tier_name_that_served_it)`` — single stores serve
        from themselves; TieredStore reports the tier that actually hit
        (the connector's per-tier hit attribution, tracing.py)."""
        return self.get(key), self.tier_name

    @abstractmethod
    def put(self, key: bytes, val: bytes) -> bool: ...

    @abstractmethod
    def exists(self, key: bytes) -> bool: ...

    @abstractmethod
    def delete(self, key: bytes) -> bool: ...

    def stats(self) -> Dict[str, int]:
        return {}

    def tier_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-tier occupancy: {tier_name: stats}. Single stores report
        themselves; TieredStore fans out."""
        return {self.tier_name: self.stats()}

    def close(self) -> None:
        pass


class _PyLruStore:
    """Byte-bounded LRU on OrderedDict — fallback when libpskv is absent."""

    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self._data: "collections.OrderedDict[bytes, bytes]" = \
            collections.OrderedDict()
        self._bytes = 0
        self._hits = self._misses = self._evictions = 0
        self._lock = threading.Lock()

    def put(self, key: bytes, val: bytes) -> bool:
        with self._lock:
            if len(val) > self.capacity:
                return False
            old = self._data.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._data[key] = val
            self._bytes += len(val)
            while self._bytes > self.capacity and self._data:
                _, evicted = self._data.popitem(last=False)
                self._bytes -= len(evicted)
                self._evictions += 1
            return True

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            val = self._data.get(key)
            if val is None:
                self._misses += 1
                return None
            self._data.move_to_end(key)
            self._hits += 1
            return val

    def exists(self, key: bytes) -> bool:
        with self._lock:
            return key in self._data

    def delete(self, key: bytes) -> bool:
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            return old is not None

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._bytes = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"bytes": self._bytes, "count": len(self._data),
                    "hits": self._hits, "misses": self._misses,
                    "evictions": self._evictions}


class HostMemoryStore(KVStore):
    """Host-DRAM tier (the LMCACHE_LOCAL_CPU equivalent), native-backed.

    The configured byte budget is a hard bound enforced by LRU eviction
    (both backends), so a long soak can grow the tier only up to
    ``capacity_bytes`` — never into the host OOM killer."""

    tier_name = "cpu"

    def __init__(self, capacity_bytes: int, force_python: bool = False):
        self.capacity = capacity_bytes
        if not force_python and load() is not None:
            self._impl = NativeLruStore(capacity_bytes)
            self.backend = "native"
        else:
            self._impl = _PyLruStore(capacity_bytes)
            self.backend = "python"

    def get(self, key: bytes) -> Optional[bytes]:
        return self._impl.get(key)

    def put(self, key: bytes, val: bytes) -> bool:
        return bool(self._impl.put(key, val))

    def exists(self, key: bytes) -> bool:
        return self._impl.exists(key)

    def delete(self, key: bytes) -> bool:
        return self._impl.delete(key)

    def clear(self) -> None:
        self._impl.clear()

    def stats(self) -> Dict[str, int]:
        out = dict(self._impl.stats())
        out.setdefault("capacity", self.capacity)
        return out


class DiskStore(KVStore):
    """Local-disk tier (the LMCACHE_LOCAL_DISK equivalent).

    One file per chunk under `root`, LRU by mtime, byte-bounded. Writes are
    tmp-file + rename so a crash never leaves a torn chunk visible.
    Occupancy is accounted incrementally (seeded by one startup scan) so
    ``stats()`` — polled by /load and /metrics — never walks the
    directory on the serving path.
    """

    tier_name = "disk"

    def __init__(self, root: str, capacity_bytes: int = 1 << 34):
        self.root = root
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)
        self._bytes, self._count = self._scan()

    def _scan(self):
        total = count = 0
        try:
            with os.scandir(self.root) as it:
                for e in it:
                    if e.name.endswith(".kv"):
                        count += 1
                        total += e.stat().st_size
        except OSError:
            pass
        return total, count

    def _path(self, key: bytes) -> str:
        return os.path.join(self.root, key.hex() + ".kv")

    def get(self, key: bytes) -> Optional[bytes]:
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                data = f.read()
            os.utime(path)  # LRU touch
            return data
        except OSError:
            return None

    def put(self, key: bytes, val: bytes) -> bool:
        if len(val) > self.capacity:
            return False
        path = self._path(key)
        # per-writer tmp name: concurrent same-key PUTs (the threaded
        # --disk-path cache server) each write their own file and race
        # only on the atomic rename — last writer wins with a FULL
        # value, never interleaved bytes
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(val)
        except OSError:
            return False
        # stat + replace + accounting are one atomic step: a racing
        # delete() (prefetch-side eviction of a poisoned chunk) between
        # them would otherwise leave _bytes under-counted and eviction
        # deferred past the configured budget
        with self._lock:
            try:
                old = os.stat(path).st_size
            except OSError:
                old = -1          # new key
            try:
                os.replace(tmp, path)
            except OSError:
                try:
                    os.remove(tmp)     # never leak a stray tmp
                except OSError:
                    pass
                return False
            self._bytes += len(val) - max(old, 0)
            if old < 0:
                self._count += 1
        self._evict()
        return True

    def exists(self, key: bytes) -> bool:
        return os.path.exists(self._path(key))

    def delete(self, key: bytes) -> bool:
        path = self._path(key)
        with self._lock:
            try:
                size = os.stat(path).st_size
                os.remove(path)
            except OSError:
                return False
            self._bytes -= size
            self._count -= 1
        return True

    def _evict(self) -> None:
        with self._lock:
            if self._bytes <= self.capacity:
                return
            try:
                entries = []
                total = 0
                with os.scandir(self.root) as it:
                    for e in it:
                        if not e.name.endswith(".kv"):
                            continue
                        st = e.stat()
                        entries.append((st.st_mtime, st.st_size, e.path))
                        total += st.st_size
                entries.sort()  # oldest first
                removed_b = removed_n = 0
                for _, size, path in entries:
                    if total - removed_b <= self.capacity:
                        break
                    try:
                        os.remove(path)
                        removed_b += size
                        removed_n += 1
                    except OSError:
                        pass
                # re-anchor on the scan (heals drift from external
                # deletions too)
                self._bytes = total - removed_b
                self._count = len(entries) - removed_n
            except OSError:
                pass

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"bytes": self._bytes, "count": self._count,
                    "capacity": self.capacity}


class RemoteStore(KVStore):
    """TPKV client tier (the LMCACHE_REMOTE_URL equivalent).

    Synchronous socket client with lazy (re)connect and one connection *per
    calling thread* (threading.local): the KV writer thread pushes
    multi-megabyte chunk batches, and serializing the admission-path
    prefetch reads behind those writes would add the write time straight to
    TTFT on cache hits.

    Failure behavior is *bounded and breaker-guarded*: every operation is
    soft (a dead or hung cache server degrades to a miss/no-op inside
    ``connect_timeout``/``io_timeout``), and after
    ``breaker_threshold`` consecutive failures the store short-circuits
    every call for ``breaker_cooldown_s`` — a sick cache server costs
    each request at most the breaker probe, never a per-chunk timeout
    walk on the TTFT path (ISSUE 6 chaos contract; docs/kv-tiering.md).
    """

    tier_name = "remote"

    def __init__(self, url: str, connect_timeout: float = 5.0,
                 io_timeout: float = 30.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 10.0):
        self.host, self.port = protocol.parse_url(url)
        self.url = url
        self.connect_timeout = connect_timeout
        self.io_timeout = io_timeout
        self.breaker_threshold = max(1, breaker_threshold)
        self.breaker_cooldown_s = breaker_cooldown_s
        self._fail_lock = threading.Lock()
        self._consecutive_failures = 0
        self._open_until = 0.0
        self._breaker_trips = 0
        self._local = threading.local()
        self._all_socks: List[socket.socket] = []
        self._all_lock = threading.Lock()

    # -- breaker --------------------------------------------------------

    def breaker_open(self) -> bool:
        """True while calls are being short-circuited. The first caller
        past the cooldown closes the window and probes for real."""
        import time
        with self._fail_lock:
            return time.monotonic() < self._open_until

    def _record_failure(self) -> None:
        import time
        with self._fail_lock:
            self._consecutive_failures += 1
            if self._consecutive_failures >= self.breaker_threshold:
                self._open_until = time.monotonic() + \
                    self.breaker_cooldown_s
                self._breaker_trips += 1
                self._consecutive_failures = 0
                logger.warning(
                    "remote KV %s breaker open for %.1fs (%d consecutive "
                    "failures)", self.url, self.breaker_cooldown_s,
                    self.breaker_threshold)

    def _record_success(self) -> None:
        with self._fail_lock:
            self._consecutive_failures = 0

    def _connect(self) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.connect_timeout)
            sock.settimeout(self.io_timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.sock = sock
            with self._all_lock:
                self._all_socks.append(sock)
        return sock

    def _drop(self) -> None:
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            with self._all_lock:
                if sock in self._all_socks:
                    self._all_socks.remove(sock)
            self._local.sock = None

    def _recv_all(self, sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            part = sock.recv(n - len(buf))
            if not part:
                raise ConnectionError("remote KV server closed connection")
            buf.extend(part)
        return bytes(buf)

    def _call(self, op: int, key: bytes = b"", val: bytes = b""):
        """-> (status, payload); one reconnect retry on a dead socket.
        Thread-safe: each thread drives its own connection. Raises
        ConnectionError immediately while the breaker is open."""
        if self.breaker_open():
            raise ConnectionError(f"remote KV {self.url} breaker open")
        for attempt in (0, 1):
            try:
                sock = self._connect()
                sock.sendall(protocol.encode_request(op, key, val))
                hdr = self._recv_all(sock, protocol.RESP_HDR_SIZE)
                status, vlen = protocol.decode_response_header(hdr)
                payload = self._recv_all(sock, vlen) if vlen else b""
                self._record_success()
                return status, payload
            except (OSError, ConnectionError) as e:
                self._drop()
                if attempt:
                    self._record_failure()
                    logger.warning("remote KV %s unreachable: %s",
                                   self.url, e)
                    raise
        raise ConnectionError("unreachable")  # not reached

    def get(self, key: bytes) -> Optional[bytes]:
        try:
            status, payload = self._call(protocol.OP_GET, key)
        except (OSError, ConnectionError):
            return None
        return payload if status == protocol.STATUS_OK else None

    def put(self, key: bytes, val: bytes) -> bool:
        try:
            status, _ = self._call(protocol.OP_PUT, key, val)
            return status == protocol.STATUS_OK
        except (OSError, ConnectionError):
            return False

    def exists(self, key: bytes) -> bool:
        try:
            status, _ = self._call(protocol.OP_EXISTS, key)
            return status == protocol.STATUS_OK
        except (OSError, ConnectionError):
            return False

    def delete(self, key: bytes) -> bool:
        try:
            status, _ = self._call(protocol.OP_DEL, key)
            return status == protocol.STATUS_OK
        except (OSError, ConnectionError):
            return False

    def ping(self) -> bool:
        try:
            status, payload = self._call(protocol.OP_PING)
            return status == protocol.STATUS_OK and payload == b"pong"
        except (OSError, ConnectionError):
            return False

    def stats(self) -> Dict[str, int]:
        import json
        out: Dict[str, int] = {}
        try:
            status, payload = self._call(protocol.OP_STATS)
            if status == protocol.STATUS_OK:
                out = json.loads(payload)
        except (OSError, ConnectionError, ValueError):
            pass
        import time
        with self._fail_lock:
            out["breaker_open"] = int(time.monotonic() < self._open_until)
            out["breaker_trips"] = self._breaker_trips
        return out

    def tier_stats(self) -> Dict[str, Dict[str, int]]:
        """Local-only view (NO network round trip — tier_stats feeds
        load_report, which runs per response): breaker state here, the
        server's own occupancy on the server's side."""
        import time
        with self._fail_lock:
            return {self.tier_name: {
                "breaker_open": int(time.monotonic() < self._open_until),
                "breaker_trips": self._breaker_trips}}

    def close(self) -> None:
        with self._all_lock:
            for sock in self._all_socks:
                try:
                    sock.close()
                except OSError:
                    pass
            self._all_socks.clear()


class TieredStore(KVStore):
    """Probe-in-order composition with hit promotion and write-through."""

    def __init__(self, tiers: List[KVStore]):
        if not tiers:
            raise ValueError("TieredStore needs at least one tier")
        self.tiers = tiers

    def get(self, key: bytes) -> Optional[bytes]:
        return self.get_with_tier(key)[0]

    def get_with_tier(self, key: bytes):
        for i, tier in enumerate(self.tiers):
            val = tier.get(key)
            if val is not None:
                for faster in self.tiers[:i]:  # promote
                    faster.put(key, val)
                return val, tier.tier_name
        return None, None

    def put(self, key: bytes, val: bytes) -> bool:
        ok = False
        for tier in self.tiers:
            ok = tier.put(key, val) or ok
        return ok

    def exists(self, key: bytes) -> bool:
        return any(tier.exists(key) for tier in self.tiers)

    def delete(self, key: bytes) -> bool:
        deleted = False
        for tier in self.tiers:
            deleted = tier.delete(key) or deleted
        return deleted

    def stats(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for i, tier in enumerate(self.tiers):
            for k, v in tier.stats().items():
                out[f"tier{i}_{type(tier).__name__}_{k}"] = v
        return out

    def tier_stats(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for tier in self.tiers:
            out.update(tier.tier_stats())
        return out

    def close(self) -> None:
        for tier in self.tiers:
            tier.close()


def make_store(local_cpu_bytes: int = 0, local_disk_path: Optional[str] = None,
               local_disk_bytes: int = 1 << 34,
               remote_url: Optional[str] = None,
               remote_connect_timeout_s: float = 2.0,
               remote_io_timeout_s: float = 5.0,
               remote_breaker_threshold: int = 3,
               remote_breaker_cooldown_s: float = 10.0
               ) -> Optional[KVStore]:
    """Build the tier stack from config; None when all tiers are off."""
    tiers: List[KVStore] = []
    if local_cpu_bytes > 0:
        tiers.append(HostMemoryStore(local_cpu_bytes))
    if local_disk_path:
        tiers.append(DiskStore(local_disk_path, local_disk_bytes))
    if remote_url:
        tiers.append(RemoteStore(
            remote_url,
            connect_timeout=remote_connect_timeout_s,
            io_timeout=remote_io_timeout_s,
            breaker_threshold=remote_breaker_threshold,
            breaker_cooldown_s=remote_breaker_cooldown_s))
    if not tiers:
        return None
    return tiers[0] if len(tiers) == 1 else TieredStore(tiers)
