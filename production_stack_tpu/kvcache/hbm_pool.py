"""In-HBM prefix cache: finished sequences' KV chunks stay on device.

Implements the engine's ``enable_prefix_caching`` knob (the reference
passes the same-named flag down to vLLM,
helm/templates/deployment-vllm-multi.yaml:73-75, whose engine keeps
shared prefixes in GPU memory). TPU-first shape: one statically-shaped
pool buffer ``[P, L, C, Hkv, D]`` lives in HBM next to the slot cache; a
host-side LRU maps chunk keys (the same prefix chain hashes the tiers
use, kvcache/chunks.py — salted per LoRA adapter) to pool rows. Store
and inject are tiny jitted device-to-device copies — a prefix hit never
crosses the host boundary, unlike the host/disk/remote tiers
(kvcache/connector.py) which remain the capacity layers behind it.

Interplay with KV tiering: at admission the engine injects from
whichever source covers the longer prefix (engine.py _on_admit); the
pool is the fast small tier, the connector the big slow one.
"""

import collections
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.kvcache.chunks import ChunkHasher, model_fingerprint
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class HBMPrefixPool:
    def __init__(self, runner, model_cfg, engine_cfg,
                 num_chunks: int = 64, chunk_size: int = 256):
        self.runner = runner
        self.num_chunks = num_chunks
        self.chunk_size = chunk_size
        self.hasher = ChunkHasher(
            chunk_size,
            namespace="hbm|" + model_fingerprint(model_cfg,
                                                 engine_cfg.kv_dtype))
        L = model_cfg.num_layers
        Hkv, D = model_cfg.num_kv_heads, model_cfg.head_dim_
        dtype = runner.cache.k.dtype
        shape = (num_chunks, L, chunk_size, Hkv, D)
        self.pool_k = jnp.zeros(shape, dtype)
        self.pool_v = jnp.zeros(shape, dtype)
        # key -> pool row; move_to_end on hit = LRU. match() runs on the
        # server thread while store()/eviction run on the engine loop,
        # so every index operation takes the lock
        self._index: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._free: List[int] = list(range(num_chunks - 1, -1, -1))
        self._store_fn = jax.jit(self._store_impl, donate_argnums=(0, 1))
        self._inject_fn = jax.jit(self._inject_impl, donate_argnums=(0,))
        self.hits = 0
        self.misses = 0
        self.stores = 0

    # -- jitted device-to-device copies ---------------------------------

    def _store_impl(self, pool_k, pool_v, cache, row, slot, start):
        L, C = pool_k.shape[1], pool_k.shape[2]
        Hkv, D = pool_k.shape[3], pool_k.shape[4]
        ck = jax.lax.dynamic_slice(cache.k, (0, slot, start, 0, 0),
                                   (L, 1, C, Hkv, D))
        cv = jax.lax.dynamic_slice(cache.v, (0, slot, start, 0, 0),
                                   (L, 1, C, Hkv, D))
        pool_k = jax.lax.dynamic_update_slice(
            pool_k, jnp.swapaxes(ck, 0, 1), (row, 0, 0, 0, 0))
        pool_v = jax.lax.dynamic_update_slice(
            pool_v, jnp.swapaxes(cv, 0, 1), (row, 0, 0, 0, 0))
        return pool_k, pool_v

    def _inject_impl(self, cache, pool_k, pool_v, row, slot, start):
        L, C = pool_k.shape[1], pool_k.shape[2]
        Hkv, D = pool_k.shape[3], pool_k.shape[4]
        ck = jax.lax.dynamic_slice(pool_k, (row, 0, 0, 0, 0),
                                   (1, L, C, Hkv, D))
        cv = jax.lax.dynamic_slice(pool_v, (row, 0, 0, 0, 0),
                                   (1, L, C, Hkv, D))
        from production_stack_tpu.models.kv import KVCache
        new_k = jax.lax.dynamic_update_slice(
            cache.k, jnp.swapaxes(ck, 0, 1), (0, slot, start, 0, 0))
        new_v = jax.lax.dynamic_update_slice(
            cache.v, jnp.swapaxes(cv, 0, 1), (0, slot, start, 0, 0))
        return KVCache(new_k, new_v)

    # -- host API --------------------------------------------------------

    def match(self, prompt_tokens: Sequence[int],
              salt: str = "") -> Tuple[List[bytes], int]:
        """Longest cached chunk-prefix: ([chunk KEYS], covered_tokens).

        Returns keys, not rows: admission can lag arbitrarily behind
        add-time (queueing), during which eviction may reassign rows —
        inject() re-resolves keys under the index lock at injection time
        and uses only the still-valid prefix. Coverage here is the
        add-time estimate, capped at len(prompt)-1 so prefill always
        computes at least one position (same convention as
        connector.prefetch).
        """
        keys = self.hasher.chunk_keys(prompt_tokens, salt=salt)
        matched: List[bytes] = []
        with self._lock:
            for key in keys:
                if key not in self._index:
                    break
                self._index.move_to_end(key)
                matched.append(key)
        covered = min(len(matched) * self.chunk_size,
                      max(len(prompt_tokens) - 1, 0))
        if matched:
            self.hits += 1
        else:
            self.misses += 1
        return matched, covered

    def inject(self, keys: Sequence[bytes], slot: int,
               max_tokens: int) -> int:
        """Copy the still-cached key-prefix into a slot (device-to-
        device). Re-resolves each key at injection time; stops at the
        first evicted key (later chunks depend on earlier positions).
        Returns tokens actually injected, capped at max_tokens.
        """
        injected = 0
        for i, key in enumerate(keys):
            if injected >= max_tokens:
                break
            with self._lock:
                row = self._index.get(key)
                if row is None:
                    break           # evicted since match(); stop here
                self._index.move_to_end(key)
            self.runner.cache = self._inject_fn(
                self.runner.cache, self.pool_k, self.pool_v,
                jnp.int32(row), jnp.int32(slot),
                jnp.int32(i * self.chunk_size))
            injected = min(injected + self.chunk_size, max_tokens)
        return injected

    def store(self, seq, salt: str = "") -> None:
        """Capture a finished sequence's full prompt+output chunks into
        the pool (LRU eviction). Must run while the slot still holds the
        sequence's KV — same constraint as connector.on_finish."""
        slot = getattr(seq, "slot", -1)
        if slot < 0:
            return
        tokens = (seq.prompt_tokens + seq.output_tokens)[:-1]
        keys = self.hasher.chunk_keys(tokens, salt=salt)
        for i, key in enumerate(keys):
            with self._lock:
                if key in self._index:
                    self._index.move_to_end(key)
                    continue
                row = self._alloc_locked()
            self.pool_k, self.pool_v = self._store_fn(
                self.pool_k, self.pool_v, self.runner.cache,
                jnp.int32(row), jnp.int32(slot),
                jnp.int32(i * self.chunk_size))
            with self._lock:
                self._index[key] = row
            self.stores += 1

    def _alloc_locked(self) -> int:
        if self._free:
            return self._free.pop()
        _, row = self._index.popitem(last=False)  # LRU eviction
        return row

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0
