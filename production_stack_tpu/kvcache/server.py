"""Standalone TPKV cache server (`python -m production_stack_tpu.kvcache.server`).

The deployable shared-KV pod — the reference runs
`lmcache_experimental_server <host> <port>` for the same role (reference:
helm/templates/deployment-cache-server.yaml:20-24). Two interchangeable
implementations serve the identical wire protocol:

  * native: the C++ `pskv-server` binary (default when built) — store and
    transport never touch Python.
  * asyncio: pure-Python front-end over HostMemoryStore, for environments
    without the toolchain (``--backend python``).
"""

import argparse
import asyncio
import os
import signal
from typing import Optional

from production_stack_tpu.kvcache import protocol
from production_stack_tpu.kvcache._native import server_binary
from production_stack_tpu.kvcache.store import HostMemoryStore
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class CacheServer:
    """Asyncio TPKV server over a HostMemoryStore (+ optional disk spill).

    Write atomicity: a PUT mutates the store only after the ENTIRE value
    frame has been received (``readexactly``) — a replica killed
    mid-publish tears the connection, not the shared tier (pinned by
    tests/test_kvcache.py). Concurrent same-key PUTs are last-writer-wins
    full-value swaps: memory-tier puts replace under the store lock, and
    the disk tier writes tmp-file + rename. Consumers additionally
    validate a full-chunk checksum (kvcache/connector.py), so even a
    corrupt value degrades to a miss, never to poisoned KV.
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 8100,
                 capacity_bytes: int = 4 << 30,
                 disk_path: Optional[str] = None,
                 disk_capacity_bytes: int = 1 << 34):
        self.host, self.port = host, port
        self.store = HostMemoryStore(capacity_bytes)
        # with a disk tier, store ops do real file I/O (plus eviction
        # scans) — run them on worker threads so one replica's publish
        # burst can never stall every other client's GET on the event
        # loop (the stores are lock-protected and thread-safe)
        self._offload_ops = bool(disk_path)
        if disk_path:
            from production_stack_tpu.kvcache.store import (DiskStore,
                                                            TieredStore)
            self.store = TieredStore([self.store,
                                      DiskStore(disk_path,
                                                disk_capacity_bytes)])
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.port)
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]
        logger.info("TPKV cache server on %s:%d (backend=%s, tiers=%s)",
                    self.host, self.port,
                    getattr(self.store, "backend", "tiered"),
                    list(self.store.tier_stats()))

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    hdr = await reader.readexactly(protocol.REQ_HDR_SIZE)
                except asyncio.IncompleteReadError:
                    break
                op, klen, vlen = protocol.decode_request_header(hdr)
                key = await reader.readexactly(klen) if klen else b""
                val = await reader.readexactly(vlen) if vlen else b""
                if self._offload_ops:
                    resp = await asyncio.to_thread(self._dispatch, op,
                                                   key, val)
                else:
                    resp = self._dispatch(op, key, val)
                writer.write(resp)
                await writer.drain()
        except (ValueError, ConnectionError, OSError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    def _dispatch(self, op: int, key: bytes, val: bytes) -> bytes:
        enc, P = protocol.encode_response, protocol
        if op == P.OP_PUT:
            return enc(P.STATUS_OK if self.store.put(key, val)
                       else P.STATUS_ERROR)
        if op == P.OP_GET:
            data = self.store.get(key)
            return enc(P.STATUS_MISSING) if data is None \
                else enc(P.STATUS_OK, data)
        if op == P.OP_EXISTS:
            return enc(P.STATUS_OK if self.store.exists(key)
                       else P.STATUS_MISSING)
        if op == P.OP_DEL:
            self.store.delete(key)
            return enc(P.STATUS_OK)
        if op == P.OP_STATS:
            import json
            return enc(P.STATUS_OK,
                       json.dumps(self.store.stats()).encode())
        if op == P.OP_PING:
            return enc(P.STATUS_OK, b"pong")
        return enc(P.STATUS_ERROR)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="TPKV shared cache server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8100)
    parser.add_argument("--capacity-gb", type=float, default=4.0)
    parser.add_argument("--disk-path", default=None,
                        help="spill tier: evicted/overflow chunks "
                             "persist here (tmp-file + rename writes; "
                             "python backend only)")
    parser.add_argument("--disk-gb", type=float, default=16.0)
    parser.add_argument("--backend", choices=["auto", "native", "python"],
                        default="auto",
                        help="native = exec the C++ pskv-server binary")
    args = parser.parse_args(argv)

    if args.backend == "native" and args.disk_path:
        logger.error("--disk-path requires --backend python (the native "
                     "pskv-server is memory-only)")
        return 1
    if args.backend in ("auto", "native") and not args.disk_path:
        binary = server_binary()
        if binary is not None:
            os.execv(binary, [binary, "--host", args.host,
                              "--port", str(args.port),
                              "--capacity-gb", str(args.capacity_gb)])
        if args.backend == "native":
            logger.error("native pskv-server binary unavailable")
            return 1

    server = CacheServer(args.host, args.port,
                         int(args.capacity_gb * (1 << 30)),
                         disk_path=args.disk_path,
                         disk_capacity_bytes=int(args.disk_gb * (1 << 30)))
    loop = asyncio.new_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, loop.stop)
    loop.run_until_complete(server.start())
    try:
        loop.run_forever()
    finally:
        loop.run_until_complete(server.stop())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
