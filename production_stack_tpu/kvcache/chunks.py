"""Token-chunk prefix hashing for KV reuse.

KV for a token depends on the whole prefix before it, so chunk keys are a
hash *chain*: chunk i's key digests chunk i's tokens together with chunk
i-1's key. Two prompts sharing a prefix produce identical keys exactly up to
their longest common chunk-aligned prefix — lookup walks the chain until the
first miss. Only full chunks are stored (a partial tail is recomputed),
mirroring chunk-granular KV stores like the reference's LMCache tier
(reference: deployment-vllm-multi.yaml:154-178 sets LMCACHE_CHUNK_SIZE).

Keys must be identical across processes/replicas (router affinity sends
same-session requests to the same replica, but the remote tier is shared by
all replicas) — so hashing is hashlib.blake2b over a canonical little-endian
int32 packing, never Python's salted hash().
"""

import hashlib
import struct
from typing import TYPE_CHECKING, List, Optional, Sequence, Tuple

if TYPE_CHECKING:   # annotation only — keep this module import-light:
    # the ROUTER hashes prompt chunks through chain_digest_bytes, and
    # models.config would drag jax into its process
    from production_stack_tpu.models.config import ModelConfig

DEFAULT_CHUNK_SIZE = 256


def chain_digest_bytes(data: bytes, chunk_bytes: int,
                       digest_size: int = 12) -> List[bytes]:
    """Chained digests of ``data``'s full ``chunk_bytes`` chunks.

    The byte-level analogue of ``ChunkHasher.chain_keys``: digest i
    folds digest i-1, so two byte strings produce identical digests
    exactly up to their longest common chunk-aligned prefix, and a
    match on digest i implies the whole leading prefix matches. Shared
    by the router's cache-aware prefix ring and the fake engine's KV
    simulation (tests/fake_engine.py) so the two sides of the kvshare
    rig can never drift apart."""
    out: List[bytes] = []
    prev = b""
    for i in range(0, len(data) - chunk_bytes + 1, chunk_bytes):
        h = hashlib.blake2b(digest_size=digest_size)
        h.update(prev)
        h.update(data[i:i + chunk_bytes])
        prev = h.digest()
        out.append(prev)
    return out


def model_fingerprint(cfg: "ModelConfig",
                      kv_dtype: str = "bfloat16") -> str:
    """Cache-key namespace: everything the KV layout/values depend on."""
    raw = (f"{cfg.name}|L{cfg.num_layers}|H{cfg.num_kv_heads}"
           f"|D{cfg.head_dim_}|rope{cfg.rope_theta}|{kv_dtype}")
    return hashlib.blake2b(raw.encode(), digest_size=8).hexdigest()


class ChunkHasher:
    def __init__(self, chunk_size: int = DEFAULT_CHUNK_SIZE,
                 namespace: str = ""):
        if chunk_size <= 0:
            raise ValueError("chunk_size must be positive")
        self.chunk_size = chunk_size
        self.namespace = namespace

    def num_full_chunks(self, num_tokens: int) -> int:
        return num_tokens // self.chunk_size

    def chunk_keys(self, tokens: Sequence[int],
                   salt: str = "") -> List[bytes]:
        """Keys for every *full* chunk of `tokens`, in order.

        ``salt`` extends the namespace for variants that produce
        different KV from the same tokens under the same model geometry
        — e.g. a LoRA adapter name (adapters with k/v targets color the
        cache, so adapter and base chunks must never collide)."""
        keys, _ = self.chain_keys(tokens, salt=salt)
        return keys

    def chain_keys(self, tokens: Sequence[int], salt: str = "",
                   state: Optional[Tuple[int, bytes]] = None,
                   ) -> Tuple[List[bytes], Tuple[int, bytes]]:
        """Incremental chunk_keys: returns (new_keys, state').

        ``state`` = (chunks_already_keyed, previous_digest) from an
        earlier call over a PREFIX of the same token stream — the chain
        extends in O(new chunks) instead of rehashing from the start
        (progressive publish calls this once per prefill chunk; without
        the state a long prompt's hashing would be quadratic)."""
        start = 0
        prev = (self.namespace + ("|" + salt if salt else "")).encode()
        if state is not None:
            start, prev = state
        keys: List[bytes] = []
        n = self.num_full_chunks(len(tokens))
        for i in range(start, n):
            chunk = tokens[i * self.chunk_size:(i + 1) * self.chunk_size]
            h = hashlib.blake2b(digest_size=16)
            h.update(prev)
            h.update(struct.pack(f"<{len(chunk)}i", *chunk))
            digest = h.digest()
            keys.append(self.namespace.encode() + b":" + digest.hex().encode())
            prev = digest
        return keys, (max(n, start), prev)
