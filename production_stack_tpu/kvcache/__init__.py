"""KV tiering: HBM -> host DRAM -> local disk -> remote shared store.

The reference stack gets this capability from LMCache, wired purely through
engine env vars (reference: helm/templates/deployment-vllm-multi.yaml:154-178
sets LMCACHE_LOCAL_CPU / LMCACHE_MAX_LOCAL_CPU_SIZE / LMCACHE_LOCAL_DISK /
LMCACHE_REMOTE_URL) plus a standalone `lmcache_experimental_server` pod
(deployment-cache-server.yaml:20-24). Here the whole subsystem is
first-class: token-chunk hashing (chunks.py), tiered byte stores backed by a
native C++ LRU (store.py, native/pskv.cpp), a TPKV TCP wire protocol +
standalone cache server (protocol.py, server.py), and the engine-side
connector that moves KV between TPU HBM and the tiers without entering the
jit path (connector.py).
"""

from production_stack_tpu.kvcache.chunks import (ChunkHasher,
                                                 model_fingerprint)
from production_stack_tpu.kvcache.connector import (KVConnector,
                                                    KVTransferConfig)
from production_stack_tpu.kvcache.store import (DiskStore, HostMemoryStore,
                                                KVStore, RemoteStore,
                                                TieredStore, make_store)

__all__ = [
    "ChunkHasher", "model_fingerprint", "KVConnector", "KVTransferConfig",
    "KVStore", "HostMemoryStore", "DiskStore", "RemoteStore", "TieredStore",
    "make_store",
]
