"""Migration decision logic for the kvplane planner (pure, no I/O).

A replica is a migration SOURCE when its fragmented allocation-failure
counter rose since the previous poll — the BlockManager's signal that
free capacity exists fleet-wide but this pool cannot seat a request —
and a DESTINATION when it can absorb the source's shed blocks and
still keep ``dst_min_free`` of its own headroom. The planner never
migrates on occupancy alone: a full pool serving every admission is
healthy; a half-empty pool refusing admissions is the pathology.

Decisions are rate-limited per source (``cooldown_s``) so one poll
glitch cannot thrash a replica with back-to-back preemptions, and each
pass emits at most one migration per source. All clock reads are
injected (``now``) so tests drive time explicitly.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


@dataclass
class ReplicaState:
    """One replica's kv_pool census as polled from ``GET /load``."""

    url: str
    num_blocks: int = 0
    free: int = 0
    active: int = 0
    cached: int = 0
    alloc_failures_fragmented: int = 0
    alloc_failures_exhausted: int = 0
    free_contiguity: float = 1.0

    @classmethod
    def from_load(cls, url: str, report: dict) -> Optional["ReplicaState"]:
        pool = report.get("kv_pool")
        if not isinstance(pool, dict):
            return None
        return cls(
            url=url,
            num_blocks=int(pool.get("num_blocks", 0)),
            free=int(pool.get("free", 0)),
            active=int(pool.get("active", 0)),
            cached=int(pool.get("cached", 0)),
            alloc_failures_fragmented=int(
                pool.get("alloc_failures_fragmented", 0)),
            alloc_failures_exhausted=int(
                pool.get("alloc_failures_exhausted", 0)),
            free_contiguity=float(pool.get("free_contiguity", 1.0)))

    @property
    def allocatable(self) -> int:
        return self.free + self.cached


@dataclass
class Decision:
    """One planned migration: shed ``target_blocks`` from ``src`` and
    warm the published chunks on ``dst``."""

    src: str
    dst: str
    target_blocks: int
    reason: str = "fragmented"


@dataclass
class _SourceTrack:
    last_failures: int = -1
    last_move_at: float = field(default=float("-inf"))


class MigrationPlanner:
    """Stateful fragmented-delta watcher -> migration decisions.

    ``migrate_fraction`` sizes each move relative to the source pool
    (the census does not expose per-request block demand, so the
    planner sheds a pool fraction large enough to seat any admissible
    request rather than chasing an unknown exact need).
    """

    def __init__(self, migrate_fraction: float = 0.25,
                 dst_min_free: int = 8,
                 cooldown_s: float = 5.0,
                 max_seqs: int = 4):
        self.migrate_fraction = min(1.0, max(0.01, migrate_fraction))
        self.dst_min_free = max(0, dst_min_free)
        self.cooldown_s = cooldown_s
        self.max_seqs = max(1, max_seqs)
        self._tracks: Dict[str, _SourceTrack] = {}
        # decision tally by action, served on /status and /metrics
        self.decisions: Dict[str, int] = {
            "migrate": 0, "hold_cooldown": 0, "skip_no_dst": 0}

    def _track(self, url: str) -> _SourceTrack:
        t = self._tracks.get(url)
        if t is None:
            t = self._tracks[url] = _SourceTrack()
        return t

    def observe(self, states: List[ReplicaState],
                now: float) -> List[Decision]:
        """One poll pass -> migration decisions (possibly empty).

        The first observation of a replica only baselines its failure
        counter (a planner restart must not re-migrate for failures
        that predate it)."""
        out: List[Decision] = []
        by_url = {s.url: s for s in states}
        # drop tracks for replicas that left the fleet
        for url in list(self._tracks):
            if url not in by_url:
                del self._tracks[url]
        for state in states:
            track = self._track(state.url)
            prev = track.last_failures
            track.last_failures = state.alloc_failures_fragmented
            if prev < 0 or state.alloc_failures_fragmented <= prev:
                continue                     # baseline or no new pain
            if now - track.last_move_at < self.cooldown_s:
                self.decisions["hold_cooldown"] += 1
                continue
            target = max(1, int(state.num_blocks *
                                self.migrate_fraction))
            target = min(target, state.active)
            dst = self._pick_destination(state, states, target)
            if dst is None or target <= 0:
                self.decisions["skip_no_dst"] += 1
                logger.warning(
                    "kvplane: %s fragmented (+%d failures) but no "
                    "destination can absorb %d blocks",
                    state.url,
                    state.alloc_failures_fragmented - prev, target)
                continue
            track.last_move_at = now
            self.decisions["migrate"] += 1
            out.append(Decision(src=state.url, dst=dst.url,
                                target_blocks=target))
        return out

    def _pick_destination(self, src: ReplicaState,
                          states: List[ReplicaState],
                          target: int) -> Optional[ReplicaState]:
        """Most-free replica that can hold the shed blocks and keep
        its own admission headroom (a destination squeezed to zero
        free would become the next migration source)."""
        best = None
        for s in states:
            if s.url == src.url:
                continue
            if s.free < target + self.dst_min_free:
                continue
            if best is None or s.free > best.free:
                best = s
        return best
