"""kvplane: fleet-wide KV memory management (ISSUE 16 / ROADMAP 3).

The control-plane half of the KV memory plane: a planner process that
watches every replica's ``/load`` kv_pool census, detects the
fragmentation admission-failure regime (``tpu:kvpool_alloc_failures_
total{reason="fragmented"}`` rising on one replica while the fleet
still holds free HBM), and erases it by live-migrating victim
sequences' KV replica-to-replica over the existing tier-transfer path:

    source  POST /admin/kvplane/migrate_out   (publish + preempt)
    dest    POST /admin/kvplane/warm          (tier promotion)
    router  POST /admin/kvplane/rehome        (locality follows bytes)

The data-plane halves live elsewhere: per-tier codecs in
``kvcache/codec.py``, the pipelined prefetch walk in
``kvcache/pipeline.py``, intra-replica free-list defrag in
``engine/block_manager.py``. Run the planner with
``python -m production_stack_tpu.kvplane`` (docs/kv-tiering.md).
"""

from production_stack_tpu.kvplane.planner import (Decision,  # noqa: F401
                                                  MigrationPlanner,
                                                  ReplicaState)
