from production_stack_tpu.kvplane.app import main

if __name__ == "__main__":
    main()
