"""kvplane planner application: poll loop + HTTP surface + CLI.

One aiohttp Application hosting the migration poll loop; endpoint
surface (docs/kv-tiering.md "Migration, defrag, and codecs"):

- ``GET /health``   — planner liveness + per-replica reachability
- ``GET /status``   — last polled census per replica, recent moves,
                      decision tallies
- ``GET /metrics``  — the ``tpu:kvplane_planner_*`` families

Each poll pass reads every replica's ``GET /load`` kv_pool census,
feeds it to ``planner.MigrationPlanner``, and executes any decisions:

1. ``POST {src}/admin/kvplane/migrate_out`` — the source publishes the
   victims' computed KV to the shared tier and frees their blocks.
2. ``POST {dst}/admin/kvplane/warm`` — the destination pulls the
   returned chunk keys through its tier stack (fastest tier warm).
3. ``POST {router}/admin/kvplane/rehome`` — the router's decode
   locality ring follows the bytes (whole-replica form: the engine's
   chunk keys and the router's prompt digests are different hash
   spaces, so the planner rehomes the source's evidence wholesale).

Every step is at-most-once and failure-isolated: a dead destination
leaves the chunks published (re-admission on the source re-prefetches
them — a miss costs recompute, never corruption), and a dead router
only costs locality-score freshness.

Closed loop: ``python -m production_stack_tpu.loadgen kvmigrate``.
"""

import argparse
import asyncio
import collections
import signal
import time
from typing import Dict, List, Optional

import aiohttp
from aiohttp import web
from prometheus_client import CollectorRegistry, Gauge, generate_latest

from production_stack_tpu.kvplane.planner import (Decision,
                                                  MigrationPlanner,
                                                  ReplicaState)
from production_stack_tpu.utils import (init_logger,
                                        parse_comma_separated,
                                        set_ulimit)
from production_stack_tpu.version import __version__

logger = init_logger(__name__)


class PlannerMetrics:
    """``tpu:kvplane_planner_*`` exposition, refreshed from the
    poller's plain-int counters at scrape time (the obsplane
    delta-free idiom — nothing prometheus-shaped near the poll
    loop)."""

    def __init__(self):
        self.registry = CollectorRegistry()
        self.polls = Gauge(
            "tpu:kvplane_planner_polls_total",
            "Cumulative census poll passes across the replica fleet",
            registry=self.registry)
        self.poll_errors = Gauge(
            "tpu:kvplane_planner_poll_errors_total",
            "Cumulative failed replica /load polls (timeout, refused, "
            "no kv_pool block)", registry=self.registry)
        self.decisions = Gauge(
            "tpu:kvplane_planner_decisions_total",
            "Cumulative planner decisions by action (migrate / "
            "hold_cooldown / skip_no_dst)",
            ["action"], registry=self.registry)
        self.moves = Gauge(
            "tpu:kvplane_planner_moves_total",
            "Cumulative executed migrations (migrate_out + warm "
            "hand-offs that freed at least one block)",
            registry=self.registry)
        self.moved_blocks = Gauge(
            "tpu:kvplane_planner_moved_blocks_total",
            "Cumulative KV blocks freed on migration sources",
            registry=self.registry)
        self.warmed = Gauge(
            "tpu:kvplane_planner_warmed_chunks_total",
            "Cumulative chunks warmed on migration destinations",
            registry=self.registry)
        self.move_errors = Gauge(
            "tpu:kvplane_planner_move_errors_total",
            "Cumulative migrations that failed mid-execution "
            "(source refused, destination warm failed)",
            registry=self.registry)
        self.replica_blocks = Gauge(
            "tpu:kvplane_replica_blocks",
            "Last-polled kv_pool census per replica, by state "
            "(free / active / cached)",
            ["replica", "state"], registry=self.registry)

    def refresh(self, poller: "KVPlanePoller") -> None:
        self.polls.set(poller.polls)
        self.poll_errors.set(poller.poll_errors)
        for action, n in poller.planner.decisions.items():
            self.decisions.labels(action=action).set(n)
        self.moves.set(poller.moves)
        self.moved_blocks.set(poller.moved_blocks)
        self.warmed.set(poller.warmed_chunks)
        self.move_errors.set(poller.move_errors)
        for url, state in poller.last_census.items():
            for field in ("free", "active", "cached"):
                self.replica_blocks.labels(
                    replica=url, state=field).set(
                    getattr(state, field))

    def render(self) -> bytes:
        return generate_latest(self.registry)


class KVPlanePoller:
    """The poll-decide-execute loop over the replica fleet."""

    def __init__(self, replicas: List[str],
                 router: Optional[str] = None,
                 poll_interval_s: float = 1.0,
                 timeout_s: float = 3.0,
                 planner: Optional[MigrationPlanner] = None,
                 dry_run: bool = False):
        self.replicas = [u.rstrip("/") for u in replicas]
        self.router = router.rstrip("/") if router else None
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s
        self.planner = planner or MigrationPlanner()
        self.dry_run = dry_run
        self.polls = 0
        self.poll_errors = 0
        self.moves = 0
        self.moved_blocks = 0
        self.warmed_chunks = 0
        self.move_errors = 0
        self.last_census: Dict[str, ReplicaState] = {}
        self.unreachable: Dict[str, str] = {}
        self.recent_moves: "collections.deque" = \
            collections.deque(maxlen=64)
        self._session: Optional[aiohttp.ClientSession] = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout_s))
        self._task = asyncio.create_task(self._loop())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._session is not None:
            await self._session.close()

    async def _loop(self) -> None:
        while True:
            try:
                await self.poll_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - loop must survive
                logger.exception("kvplane poll pass failed")
            await asyncio.sleep(self.poll_interval_s)

    async def poll_once(self) -> List[Decision]:
        """One pass: census every replica, plan, execute. Public so
        tests (and the kvmigrate rig's assertions) can step the loop
        deterministically."""
        self.polls += 1
        states: List[ReplicaState] = []
        for url in self.replicas:
            state = await self._poll_replica(url)
            if state is None:
                continue
            states.append(state)
            self.last_census[url] = state
            self.unreachable.pop(url, None)
        decisions = self.planner.observe(states, now=time.monotonic())
        for d in decisions:
            await self._execute(d)
        return decisions

    async def _poll_replica(self, url: str) -> Optional[ReplicaState]:
        try:
            async with self._session.get(url + "/load") as resp:
                if resp.status != 200:
                    raise RuntimeError(f"/load -> {resp.status}")
                report = await resp.json()
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - census best-effort
            self.poll_errors += 1
            self.unreachable[url] = str(exc)
            return None
        state = ReplicaState.from_load(url, report)
        if state is None:
            # reachable but no kv_pool census: count it so a fleet of
            # engines predating the census shows up on /metrics
            # instead of silently planning over nothing
            self.poll_errors += 1
            self.unreachable[url] = "no kv_pool census on /load"
        return state

    async def _execute(self, d: Decision) -> None:
        record = {"at_unix": round(time.time(), 3), "src": d.src,
                  "dst": d.dst, "target_blocks": d.target_blocks,
                  "freed_blocks": 0, "warmed": 0, "rehomed": None,
                  "dry_run": self.dry_run, "error": None}
        self.recent_moves.append(record)
        if self.dry_run:
            return
        try:
            async with self._session.post(
                    d.src + "/admin/kvplane/migrate_out",
                    json={"max_seqs": self.planner.max_seqs,
                          "target_blocks": d.target_blocks}) as resp:
                body = await resp.json()
                if resp.status != 200:
                    raise RuntimeError(
                        f"migrate_out -> {resp.status}: {body}")
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - move best-effort
            self.move_errors += 1
            record["error"] = f"migrate_out: {exc}"
            logger.warning("kvplane: migrate_out on %s failed: %s",
                           d.src, exc)
            return
        freed = int(body.get("freed_blocks", 0))
        keys = body.get("keys") or []
        record["freed_blocks"] = freed
        if not freed:
            return
        self.moves += 1
        self.moved_blocks += freed
        try:
            async with self._session.post(
                    d.dst + "/admin/kvplane/warm",
                    json={"keys": keys}) as resp:
                warm = await resp.json()
            record["warmed"] = int(warm.get("warmed", 0))
            self.warmed_chunks += record["warmed"]
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - warm best-effort
            # chunks stay published in the shared tier: the migrated
            # traffic pays a remote fetch instead of a local hit
            self.move_errors += 1
            record["error"] = f"warm: {exc}"
            logger.warning("kvplane: warm on %s failed: %s",
                           d.dst, exc)
        if self.router is not None:
            try:
                async with self._session.post(
                        self.router + "/admin/kvplane/rehome",
                        json={"from": d.src, "to": d.dst}) as resp:
                    rh = await resp.json()
                record["rehomed"] = rh.get("rehomed")
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - locality only
                record["error"] = (record["error"] or "") + \
                    f" rehome: {exc}"
                logger.warning("kvplane: rehome via %s failed: %s",
                               self.router, exc)
        logger.info("kvplane: migrated %d blocks %s -> %s "
                    "(warmed %d chunks, rehomed %s)",
                    freed, d.src, d.dst, record["warmed"],
                    record["rehomed"])

    def status(self) -> dict:
        return {
            "version": __version__,
            "replicas": {
                url: ({"num_blocks": s.num_blocks, "free": s.free,
                       "active": s.active, "cached": s.cached,
                       "alloc_failures_fragmented":
                           s.alloc_failures_fragmented,
                       "alloc_failures_exhausted":
                           s.alloc_failures_exhausted,
                       "free_contiguity": s.free_contiguity}
                      if (s := self.last_census.get(url)) else None)
                for url in self.replicas},
            "unreachable": dict(self.unreachable),
            "router": self.router,
            "dry_run": self.dry_run,
            "polls": self.polls,
            "poll_errors": self.poll_errors,
            "decisions": dict(self.planner.decisions),
            "moves": self.moves,
            "moved_blocks": self.moved_blocks,
            "warmed_chunks": self.warmed_chunks,
            "move_errors": self.move_errors,
            "recent_moves": list(self.recent_moves),
        }


async def health(request: web.Request) -> web.Response:
    poller = request.app["state"]["poller"]
    body = {"status": "ok", "polls": poller.polls,
            "replicas": len(poller.replicas),
            "unreachable": sorted(poller.unreachable)}
    return web.json_response(body)


async def status(request: web.Request) -> web.Response:
    return web.json_response(request.app["state"]["poller"].status())


async def metrics(request: web.Request) -> web.Response:
    state = request.app["state"]
    state["metrics"].refresh(state["poller"])
    return web.Response(body=state["metrics"].render(),
                        content_type="text/plain")


def build_app(args: argparse.Namespace) -> web.Application:
    planner = MigrationPlanner(
        migrate_fraction=args.migrate_fraction,
        dst_min_free=args.dst_min_free_blocks,
        cooldown_s=args.move_cooldown,
        max_seqs=args.max_migrate_seqs)
    poller = KVPlanePoller(
        replicas=parse_comma_separated(args.replicas),
        router=args.router or None,
        poll_interval_s=args.poll_interval,
        timeout_s=args.poll_timeout,
        planner=planner,
        dry_run=args.dry_run)
    app = web.Application()
    app["state"] = {"poller": poller, "metrics": PlannerMetrics()}
    app.router.add_get("/health", health)
    app.router.add_get("/status", status)
    app.router.add_get("/metrics", metrics)

    async def on_startup(app):
        await poller.start()

    async def on_cleanup(app):
        await poller.close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        "pstpu-kvplane",
        description="fleet KV memory planner: live migration/defrag "
                    "control plane over the replicas' kv_pool census")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8300)
    p.add_argument("--replicas", default="",
                   help="comma-separated engine base URLs to manage "
                        "(/load census + /admin/kvplane/* surface)")
    p.add_argument("--router", default="",
                   help="router base URL whose decode-locality ring is "
                        "rehomed after each migration (optional; the "
                        "hand-off is best-effort)")
    p.add_argument("--poll-interval", type=float, default=1.0,
                   help="seconds between census poll passes")
    p.add_argument("--poll-timeout", type=float, default=3.0,
                   help="per-request timeout for census polls and "
                        "migration/warm/rehome calls")
    p.add_argument("--migrate-fraction", type=float, default=0.25,
                   help="fraction of a fragmented source pool to shed "
                        "per migration (the census does not expose "
                        "per-request block demand)")
    p.add_argument("--dst-min-free-blocks", type=int, default=8,
                   help="free-block headroom a destination must keep "
                        "AFTER absorbing a migration (a squeezed "
                        "destination would become the next source)")
    p.add_argument("--move-cooldown", type=float, default=5.0,
                   help="seconds a source is immune after a migration "
                        "(one poll glitch must not thrash a replica "
                        "with back-to-back preemptions)")
    p.add_argument("--max-migrate-seqs", type=int, default=4,
                   help="victim-sequence cap per migrate_out call")
    p.add_argument("--dry-run", action="store_true",
                   help="plan and log decisions without executing "
                        "them (census polling still live)")
    args = p.parse_args(argv)
    if not args.replicas:
        p.error("need --replicas to manage")
    return args


def main(argv=None) -> None:
    args = parse_args(argv)
    set_ulimit()
    app = build_app(args)

    async def _serve():
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, args.host, args.port)
        await site.start()
        logger.info("kvplane planner listening on %s:%d (%d replicas, "
                    "poll every %.1fs%s)",
                    args.host, args.port,
                    len(app["state"]["poller"].replicas),
                    args.poll_interval,
                    ", DRY RUN" if args.dry_run else "")
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
        await runner.cleanup()

    asyncio.run(_serve())


if __name__ == "__main__":
    main()
