"""Colored structured logging for the stack.

Capability parity with the reference's router logger
(reference: src/vllm_router/log.py) — per-level ANSI colors, one shared
format, idempotent handler install — with env-var level control.
"""

import logging
import os
import sys

_COLORS = {
    logging.DEBUG: "\x1b[38;20m",
    logging.INFO: "\x1b[36;20m",
    logging.WARNING: "\x1b[33;20m",
    logging.ERROR: "\x1b[31;20m",
    logging.CRITICAL: "\x1b[31;1m",
}
_RESET = "\x1b[0m"
_FMT = "[%(asctime)s] %(levelname)s %(name)s: %(message)s"


class ColorFormatter(logging.Formatter):
    def __init__(self, use_color: bool = True):
        super().__init__(_FMT, datefmt="%H:%M:%S")
        self.use_color = use_color

    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if self.use_color:
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


def init_logger(name: str, level: str | int | None = None) -> logging.Logger:
    """Create/fetch a logger with the stack's formatter attached once."""
    logger = logging.getLogger(name)
    if level is None:
        level = os.environ.get("PSTPU_LOG_LEVEL", "INFO")
    if isinstance(level, str):
        level = getattr(logging, level.upper(), logging.INFO)
    logger.setLevel(level)
    if not any(isinstance(h.formatter, ColorFormatter) for h in logger.handlers):
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(ColorFormatter(use_color=sys.stderr.isatty()))
        logger.addHandler(handler)
        logger.propagate = False
    return logger
