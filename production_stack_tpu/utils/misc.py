"""Shared helpers: singleton metaclass, URL validation, ulimit, parsers.

Capability parity with reference src/vllm_router/utils.py (SingletonMeta
:10-38, validate_url :41-60, set_ulimit :63-79, static list parsers :82-95);
re-designed with explicit reset support for tests and hot reconfiguration.
"""

import re
import resource
from abc import ABCMeta
from typing import Any, Dict, List, Optional

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class SingletonMeta(type):
    """Metaclass giving each class a single process-wide instance.

    Unlike a naive implementation, instances can be explicitly dropped
    (``Cls.reset_instance()``) so dynamic reconfiguration and tests can
    rebuild singletons without process restarts.
    """

    _instances: Dict[type, Any] = {}

    def __call__(cls, *args, **kwargs):
        if cls not in cls._instances:
            cls._instances[cls] = super().__call__(*args, **kwargs)
        return cls._instances[cls]

    def instance_or_none(cls) -> Optional[Any]:
        return cls._instances.get(cls)

    def has_instance(cls) -> bool:
        return cls in cls._instances

    def reset_instance(cls) -> None:
        cls._instances.pop(cls, None)


class SingletonABCMeta(ABCMeta, SingletonMeta):
    """Singleton + ABC combined (for abstract service-discovery bases)."""


_URL_RE = re.compile(r"^(https?)://([\w.-]+)(:\d+)?(/.*)?$")


def validate_url(url: str) -> bool:
    return bool(_URL_RE.match(url))


def set_ulimit(target_soft: int = 65535) -> None:
    """Raise RLIMIT_NOFILE soft limit for high-concurrency streaming."""
    try:
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < target_soft:
            resource.setrlimit(
                resource.RLIMIT_NOFILE, (min(target_soft, hard), hard)
            )
    except (ValueError, OSError) as e:
        logger.warning("could not raise RLIMIT_NOFILE: %s", e)


def parse_comma_separated(value: Optional[str]) -> List[str]:
    if not value:
        return []
    return [v.strip() for v in value.split(",") if v.strip()]


def parse_static_urls(static_backends: str) -> List[str]:
    urls = parse_comma_separated(static_backends)
    bad = [u for u in urls if not validate_url(u)]
    if bad:
        raise ValueError(f"invalid backend URLs: {bad}")
    return urls


def parse_static_model_types(value: Optional[str]) -> List[str]:
    return parse_comma_separated(value)


def parse_static_aliases(value: Optional[str]) -> Dict[str, str]:
    """Parse "alias1:model1,alias2:model2" into a dict."""
    aliases: Dict[str, str] = {}
    for pair in parse_comma_separated(value):
        if ":" not in pair:
            raise ValueError(f"invalid alias spec {pair!r}, expected alias:model")
        alias, model = pair.split(":", 1)
        aliases[alias.strip()] = model.strip()
    return aliases


def honor_platform_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative before backend init.

    The environment may register extra PJRT plugins via sitecustomize
    (e.g. a TPU tunnel) that import jax early with their own platform
    baked in, so the env var alone loses platform selection. Entry
    points call this before any jax computation; no-op once backends
    are initialized or when the env var is unset.
    """
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
    except Exception as e:  # backends already initialized
        logger.warning("could not pin jax platform to %s: %s", want, e)
