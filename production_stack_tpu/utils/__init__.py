from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.misc import (
    SingletonMeta,
    honor_platform_env,
    parse_comma_separated,
    parse_static_aliases,
    parse_static_model_types,
    parse_static_urls,
    set_ulimit,
    validate_url,
)

__all__ = [
    "init_logger",
    "honor_platform_env",
    "SingletonMeta",
    "validate_url",
    "set_ulimit",
    "parse_comma_separated",
    "parse_static_aliases",
    "parse_static_model_types",
    "parse_static_urls",
]
