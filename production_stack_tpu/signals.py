"""Shared engine load-signal poller: one scrape per engine, any consumer.

Every engine replica answers ``GET /load`` with a cheap lock-free JSON
report (engine/engine.py ``load_report``): queue depth, running
sequences, advertised admission capacity, KV pressure, and the
service-EWMA queue-delay estimate. Two subsystems consume those
numbers:

- the **router**, which derives its per-endpoint concurrency cap from
  advertised capacity and feeds the stats log (router/stats.py
  ``EngineStatsScraper``), and
- the **autoscaler**, whose scaling policy reads queue delay and
  utilization (autoscaler/collector.py).

This module is the one poller both are built on, so a process hosting
several consumers still issues exactly one ``/load`` request per engine
per interval instead of one per consumer. ``LoadPoller`` subclasses
override ``_build`` to store their own per-engine record type without
re-implementing the polling loop, the concurrency fan-out, or the
stale-engine eviction.

Engines that do not serve ``/load`` (a stock vLLM pod behind the same
router) are handled by the subclass fallback hook ``_fetch_fallback``
— the router's scraper uses it to fall back to parsing ``/metrics``.
"""

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Optional, Tuple

import aiohttp

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


@dataclass
class EngineLoad:
    """Parsed ``/load`` report for one engine replica."""

    queue_depth: float = 0.0       # WAITING sequences
    running: float = 0.0           # RUNNING + prefilling sequences
    # total in-flight the engine accepts before shedding; None =
    # unbounded admission (no --max-waiting-seqs) — consumers must not
    # coerce this to 0, which /metrics uses as its own sentinel
    capacity: Optional[float] = None
    max_num_seqs: float = 0.0
    est_queue_delay_ms: float = 0.0
    kv_usage: float = 0.0
    free_kv_blocks: float = 0.0
    # KV-tier sharing signals (engines running --kv-transfer-config
    # publish a "kv_cache" block in /load; zeros otherwise): the
    # cache-aware router reads the hit rate, the kvshare rig reads the
    # token counters
    kv_hit_rate: float = 0.0
    kv_query_tokens: float = 0.0
    kv_hit_tokens: float = 0.0
    kv_foreign_hit_tokens: float = 0.0
    # disagg role advertised in the kv_cache block ("kv_producer",
    # "kv_consumer", "kv_both"; "" = no KV tiering / unknown)
    kv_role: str = ""
    # engine-efficiency signals (/load "perf" block, engine/
    # efficiency.py; zeros for engines without the accounting layer):
    # recent effective-bandwidth/MBU rates, the decode live fraction,
    # cumulative real/pad/dead token-step totals, and compile
    # counters — compile_in_flight > 0 means the engine loop is
    # blocked on an XLA build RIGHT NOW (the /load path answers
    # through it)
    mbu_perc: float = 0.0
    effective_bytes_per_s: float = 0.0
    live_fraction: float = 0.0
    decode_tokens_per_s: float = 0.0
    token_steps_real: float = 0.0
    token_steps_pad: float = 0.0
    token_steps_dead: float = 0.0
    compiles_total: float = 0.0
    compile_in_flight: float = 0.0
    # the engine's live model catalog (/load "models": base model
    # first, then every currently-loaded LoRA adapter; () for engines
    # predating the field): the router's /v1/models aggregation and
    # pool resolution read it, so a runtime adapter load propagates
    # fleet-wide one scrape later without a config push
    models: Tuple[str, ...] = ()
    scraped_at: float = field(default_factory=time.time)

    @property
    def in_flight(self) -> float:
        """Everything admitted and not yet finished: what counts
        against advertised capacity."""
        return self.queue_depth + self.running

    @property
    def utilization(self) -> Optional[float]:
        """in_flight / capacity, or None when admission is unbounded
        (nothing to normalise against)."""
        if self.capacity is None or self.capacity <= 0:
            return None
        return self.in_flight / self.capacity


def parse_load_report(data: dict) -> EngineLoad:
    def pnum(src: dict, key: str) -> float:
        v = src.get(key)
        return 0.0 if v is None else float(v)

    cap = data.get("capacity")
    kv = data.get("kv_cache") or {}
    perf = data.get("perf") or {}
    steps = perf.get("token_steps") or {}

    return EngineLoad(
        queue_depth=pnum(data, "queue_depth"),
        running=pnum(data, "running"),
        capacity=None if cap is None else float(cap),
        max_num_seqs=pnum(data, "max_num_seqs"),
        est_queue_delay_ms=pnum(data, "est_queue_delay_ms"),
        kv_usage=pnum(data, "kv_usage"),
        free_kv_blocks=pnum(data, "free_kv_blocks"),
        kv_hit_rate=pnum(kv, "hit_rate"),
        kv_query_tokens=pnum(kv, "query_tokens"),
        kv_hit_tokens=pnum(kv, "hit_tokens"),
        kv_foreign_hit_tokens=pnum(kv, "foreign_hit_tokens"),
        kv_role=str(kv.get("role") or ""),
        mbu_perc=pnum(perf, "mbu_perc"),
        effective_bytes_per_s=pnum(perf, "effective_bytes_per_s"),
        live_fraction=pnum(perf, "live_fraction"),
        decode_tokens_per_s=pnum(perf, "decode_tokens_per_s"),
        token_steps_real=pnum(steps, "real"),
        token_steps_pad=pnum(steps, "pad"),
        token_steps_dead=pnum(steps, "dead"),
        compiles_total=pnum(perf, "compiles_total"),
        compile_in_flight=pnum(perf, "compile_in_flight"),
        models=tuple(str(m) for m in data.get("models") or ()),
    )


def coerce_load(rec) -> EngineLoad:
    """Adapt any per-engine record to an ``EngineLoad``.

    Lets the autoscaler's collector read a poller that stores a
    different record type — specifically the router's
    ``EngineStatsScraper`` (``EngineStats``: num_running/num_waiting,
    capacity 0.0 as the unbounded sentinel) — so an autoscaler embedded
    next to a router reuses the router's scrape verbatim.
    """
    if isinstance(rec, EngineLoad):
        return rec
    cap = getattr(rec, "capacity", 0.0) or 0.0
    return EngineLoad(
        queue_depth=getattr(rec, "num_waiting", 0.0),
        running=getattr(rec, "num_running", 0.0),
        capacity=None if cap <= 0 else cap,
        est_queue_delay_ms=getattr(rec, "est_queue_delay_ms", 0.0),
        kv_usage=getattr(rec, "kv_usage", 0.0),
        scraped_at=getattr(rec, "scraped_at", time.time()),
    )


class LoadPoller:
    """Polls each engine's ``/load`` on an interval (asyncio task).

    ``get_urls`` is called per pass so discovery swaps are followed;
    engines that stop answering drop out of ``get()`` (consumers treat
    absence as "no fresh signal"). ``poll_now()`` runs one immediate
    concurrent pass — the autoscaler calls it at each control tick so
    decisions act on current load, not an interval-old snapshot.
    """

    def __init__(self, get_urls: Callable[[], Iterable[str]],
                 interval_s: float = 10.0,
                 timeout_s: float = 5.0):
        self._get_urls = get_urls
        self.interval = interval_s
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self._stats: Dict[str, object] = {}
        self._task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._owns_session = False

    # -- record-building hooks (subclass surface) -----------------------

    def _build(self, data: dict) -> object:
        return parse_load_report(data)

    async def _fetch_fallback(self, url: str) -> Optional[object]:
        """Called when ``GET {url}/load`` answers 404 (an engine that
        predates /load or a foreign backend). Default: no signal."""
        return None

    # -- lifecycle ------------------------------------------------------

    async def start(self,
                    session: Optional[aiohttp.ClientSession] = None
                    ) -> None:
        if session is None:
            session = aiohttp.ClientSession()
            self._owns_session = True
        self._session = session
        self._task = asyncio.create_task(self._loop(), name="load-poller")

    def attach(self, session: aiohttp.ClientSession) -> None:
        """On-demand mode: no background interval loop — the consumer
        drives every scrape through ``poll_now()`` (the autoscaler's
        collector does this so each engine is scraped exactly once per
        control tick, never once per tick PLUS once per interval)."""
        self._session = session

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._session and self._owns_session:
            await self._session.close()
        self._session = None

    def healthy(self) -> bool:
        return self._task is not None and not self._task.done()

    # -- reads ----------------------------------------------------------

    def get(self) -> Dict[str, object]:
        return dict(self._stats)

    # -- polling --------------------------------------------------------

    async def _loop(self) -> None:
        while True:
            await self.poll_now()
            await asyncio.sleep(self.interval)

    async def _scrape_one(self, url: str) -> None:
        try:
            async with self._session.get(f"{url}/load",
                                         timeout=self._timeout) as r:
                if r.status == 200:
                    self._stats[url] = self._build(await r.json())
                    return
                if r.status == 404:
                    rec = await self._fetch_fallback(url)
                    if rec is not None:
                        self._stats[url] = rec
                        return
            self._stats.pop(url, None)
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            self._stats.pop(url, None)   # stale engine drops out

    async def poll_now(self) -> Dict[str, object]:
        """One concurrent scrape pass over the current URL set."""
        urls = {u.rstrip("/") for u in self._get_urls()}
        # concurrent: one slow/unreachable engine must not stall the rest
        await asyncio.gather(*(self._scrape_one(u) for u in urls))
        for gone in set(self._stats) - urls:
            del self._stats[gone]
        return self.get()
