"""Flight recorder: alert-triggered incident bundles + attribution.

When a subscribed SLO alert transitions to firing (or an operator
POSTs ``/fleet/capture``), the aggregator hands this module the
correlated state of every fleet process and it writes ONE
self-contained incident bundle to disk: per-process snapshots (router
health/alerts/QoS/peers/breakers, engine load + perf rings + kvpool
census), the slowest stitched chains, the fleet percentiles — and a
machine-written **attribution** naming the process and phase the
evidence points at, so the bundle opens with a verdict instead of a
scavenger hunt.

Attribution ranks three evidence classes, strongest first:

1. **A process stopped answering** — a replica that was scraped
   successfully and then went dark is guilty of any availability-ish
   burn (phase ``down``). Nothing latency-shaped outranks a corpse.
2. **Shed-rate alerts** — intentional backpressure is a router-side
   decision: the router with the largest shed delta since the last
   clean poll is guilty, phase ``admission``.
3. **Latency/availability alerts with everyone alive** — per-process
   per-phase stats from recently-stitched chains: the (process, phase)
   whose recent p95 most exceeds the fleet median for that phase wins.
   Router-internal phases (``admission``/``routing``) indict the
   router; backend phases observed engine-side indict the engine.

Retention is bounded: the newest ``retention`` bundles are kept on
disk, older ones deleted oldest-first.
"""

import json
import os
import time
from typing import Dict, List, Optional

from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)

# router-side phases that measure the backend, not the router: a slow
# backend_ttfb/relay span says "the engine named in attrs.server is
# slow", so these never indict the router process itself
_ROUTER_BACKEND_PHASES = frozenset({"backend_ttfb", "relay"})
# phases too generic to name as a verdict when a more specific phase
# is in evidence (unattributed time loses ties to any named phase)
_WEAK_PHASES = frozenset({"unattributed", "total"})


def attribute_incident(*, alert: Optional[dict],
                       processes: Dict[str, dict],
                       process_phase_stats: Dict[str, Dict[str, dict]],
                       shed_deltas: Optional[Dict[str, float]] = None
                       ) -> dict:
    """The verdict: ``{process, role, phase, confidence, reason,
    evidence}``. ``processes`` is {url: ProcessState.to_json()};
    ``alert`` the triggering alert row (None for manual captures)."""
    # -- rule 1: a dead process outranks everything ---------------------
    dead = [(url, p) for url, p in processes.items()
            if p.get("unreachable_since") is not None
            and p.get("ever_seen")]
    if dead:
        # the longest-dead first: a cascade's root cause died first
        dead.sort(key=lambda kv: kv[1]["unreachable_since"])
        url, p = dead[0]
        return {
            "process": url,
            "role": p.get("role", "?"),
            "phase": "down",
            "confidence": "high",
            "reason": (f"{url} ({p.get('role')}) stopped answering "
                       f"scrapes at "
                       f"{_iso(p['unreachable_since'])} and has not "
                       f"come back"),
            "evidence": {"unreachable": [u for u, _ in dead]},
        }

    slo_kind = (alert or {}).get("slo_kind", "")
    slo_name = (alert or {}).get("slo", "")

    # -- rule 2: sheds are a router admission decision ------------------
    if slo_kind == "shed_rate" or "shed" in slo_name:
        sheds = {url: d for url, d in (shed_deltas or {}).items()
                 if d > 0}
        if sheds:
            url = max(sheds, key=sheds.get)
            return {
                "process": url,
                "role": processes.get(url, {}).get("role", "router"),
                "phase": "admission",
                "confidence": "high",
                "reason": (f"{url} shed {int(sheds[url])} requests "
                           f"since the last clean poll — the largest "
                           f"shed delta in the fleet"),
                "evidence": {"shed_deltas": {u: int(d) for u, d
                                             in sheds.items()}},
            }

    # -- rule 3: rank (process, phase) latency excess -------------------
    # fleet median per phase, then each process's excess over it — the
    # guilty pair is the one whose recent p95 most exceeds what the
    # same phase costs elsewhere in the fleet (absolute excess, ms:
    # ratios overweight microsecond phases)
    from production_stack_tpu.obsplane.stitch import percentile
    by_phase: Dict[str, List[float]] = {}
    for url, phases in process_phase_stats.items():
        for phase, row in phases.items():
            by_phase.setdefault(phase, []).append(row["p95_ms"])
    best = None
    board = []
    for url, phases in process_phase_stats.items():
        for phase, row in phases.items():
            if phase in _WEAK_PHASES:
                continue
            if processes.get(url, {}).get("role") == "router" \
                    and phase in _ROUTER_BACKEND_PHASES:
                continue    # measures the backend, not this router
            med = percentile(by_phase[phase], 50)
            excess = row["p95_ms"] - med
            board.append({"process": url, "phase": phase,
                          "p95_ms": row["p95_ms"],
                          "fleet_median_ms": round(med, 2),
                          "excess_ms": round(excess, 2),
                          "n": row["n"]})
            if best is None or excess > best["excess_ms"]:
                best = board[-1]
    board.sort(key=lambda r: r["excess_ms"], reverse=True)
    if best is not None and best["excess_ms"] > 0:
        url = best["process"]
        return {
            "process": url,
            "role": processes.get(url, {}).get("role", "?"),
            "phase": best["phase"],
            "confidence": "medium",
            "reason": (f"{url} {best['phase']} p95 "
                       f"{best['p95_ms']:.0f}ms exceeds the fleet "
                       f"median for that phase "
                       f"({best['fleet_median_ms']:.0f}ms) by "
                       f"{best['excess_ms']:.0f}ms — the largest "
                       f"excess on the scoreboard"),
            "evidence": {"scoreboard": board[:10]},
        }
    return {
        "process": None,
        "role": None,
        "phase": None,
        "confidence": "none",
        "reason": "no process stood out: nothing dead, no shed "
                  "deltas, no phase excess in the stitched chains",
        "evidence": {"scoreboard": board[:10]},
    }


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S",
                         time.gmtime(ts)) + f".{int(ts % 1 * 1e3):03d}Z"


class IncidentRecorder:
    """Writes bounded-retention incident bundles; keeps an in-memory
    index served on ``GET /fleet/incidents``."""

    def __init__(self, incident_dir: str, retention: int = 32,
                 cooldown_s: float = 30.0, now_fn=time.time):
        self.incident_dir = incident_dir
        self.retention = max(1, retention)
        self.cooldown_s = cooldown_s
        self._now = now_fn
        self.captured_total = 0
        self.suppressed_total = 0
        self.last_capture_at: Optional[float] = None
        self._index: List[dict] = []      # newest last
        os.makedirs(incident_dir, exist_ok=True)
        self._rebuild_index()

    def _rebuild_index(self) -> None:
        """Reload the index from bundles already on disk, so an
        obsplane restart does not orphan incidents a remediation
        consumer (autoscaler/remediator.py) has not acted on yet.
        Unreadable files are skipped, not fatal — a half-written
        bundle cannot exist (atomic replace), but a truncated disk
        can produce one."""
        rows = []
        try:
            names = sorted(os.listdir(self.incident_dir))
        except OSError:
            return
        for name in names:
            if not (name.startswith("incident-")
                    and name.endswith(".json")):
                continue
            path = os.path.join(self.incident_dir, name)
            try:
                with open(path) as f:
                    bundle = json.load(f)
            except (OSError, ValueError):
                continue
            attribution = bundle.get("attribution") or {}
            rows.append({
                "incident_id": bundle.get("incident_id"),
                "path": path,
                "captured_at": bundle.get("captured_at"),
                "trigger": bundle.get("trigger"),
                "alert": (bundle.get("alert") or {}).get("name"),
                "attribution": {k: attribution.get(k) for k in
                                ("process", "role", "phase",
                                 "confidence", "reason")},
            })
        rows.sort(key=lambda r: r.get("captured_at") or 0.0)
        self._index = rows[-self.retention:]
        # keep incident ids (timestamp + counter) collision-free
        # across the restart
        self.captured_total = len(self._index)
        if self._index:
            logger.info("incident index rebuilt from disk: %d "
                        "bundle(s), newest %s", len(self._index),
                        self._index[-1]["incident_id"])

    def in_cooldown(self) -> bool:
        return (self.last_capture_at is not None
                and self._now() - self.last_capture_at
                < self.cooldown_s)

    def capture(self, *, trigger: str, alert: Optional[dict],
                fleet: dict,
                attribution: dict,
                force: bool = False) -> Optional[dict]:
        """Write one bundle; returns its index row, or None when the
        capture was suppressed by the cooldown (an alert storm must
        yield ONE bundle, not one per alert transition). Manual
        captures pass ``force=True``."""
        now = self._now()
        if not force and self.in_cooldown():
            self.suppressed_total += 1
            logger.info("incident capture suppressed (cooldown %.0fs): "
                        "%s", self.cooldown_s, trigger)
            return None
        self.captured_total += 1
        self.last_capture_at = now
        incident_id = (time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
                       + f"-{self.captured_total:04d}")
        bundle = {
            "schema": "tpu-incident-bundle/v1",
            "incident_id": incident_id,
            "captured_at": now,
            "captured_at_iso": _iso(now),
            "trigger": trigger,
            "alert": alert,
            "attribution": attribution,
            "fleet": fleet,
        }
        path = os.path.join(self.incident_dir,
                            f"incident-{incident_id}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        os.replace(tmp, path)       # readers never see a half bundle
        row = {
            "incident_id": incident_id,
            "path": path,
            "captured_at": now,
            "trigger": trigger,
            "alert": (alert or {}).get("name"),
            "attribution": {k: attribution.get(k) for k in
                            ("process", "role", "phase", "confidence",
                             "reason")},
        }
        self._index.append(row)
        self._enforce_retention()
        logger.warning("incident bundle captured: %s (%s) -> %s | %s",
                       incident_id, trigger, path,
                       attribution.get("reason"))
        return row

    def _enforce_retention(self) -> None:
        while len(self._index) > self.retention:
            old = self._index.pop(0)
            try:
                os.remove(old["path"])
            except OSError:
                pass

    # -- reads ----------------------------------------------------------

    def index(self) -> List[dict]:
        return list(self._index)

    def load(self, incident_id: str) -> Optional[dict]:
        for row in self._index:
            if row["incident_id"] == incident_id:
                try:
                    with open(row["path"]) as f:
                        return json.load(f)
                except (OSError, ValueError):
                    return None
        return None
