"""The fleet poll loop: incremental scrapes, edge detection, capture.

One ``FleetAggregator`` owns the whole scrape plane for a fleet of
router and engine processes:

- engine ``/load`` rides the shared ``signals.LoadPoller`` (attach
  mode — the aggregator's own tick drives ``poll_now()``, so each
  engine is scraped exactly once per tick no matter how many obsplane
  consumers read the result);
- every process's ``/debug/traces`` is read INCREMENTALLY through the
  ``since_seq`` cursor (tracing.TraceRecorder): each trace row crosses
  the wire once, and a slow poll interval loses traces only when the
  ring itself rotates past them;
- engines additionally surrender ``/debug/perf`` (timestamped window +
  compile rings, kvpool census), routers ``/health`` (breakers, drain,
  peers, QoS) and ``/alerts`` (the SLO state machine).

The aggregator keeps the LAST-KNOWN payload of every process even
while the process is unreachable — a flight recorder whose bundle
drops the dead replica's final state would be recording everything
except the crash.

Alert-edge detection: a subscribed alert transitioning into ``firing``
(keyed by its ``firing_since`` stamp, so a flapping alert re-triggers
and a steadily-firing one does not) hands the correlated fleet state
to the ``IncidentRecorder``. Shed attribution baselines reset on every
quiet poll, so a capture's shed delta covers exactly the burn window.
"""

import asyncio
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

import aiohttp

from production_stack_tpu.obsplane.recorder import (IncidentRecorder,
                                                    attribute_incident)
from production_stack_tpu.obsplane.stitch import ChainStore
from production_stack_tpu.signals import LoadPoller
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class ProcessState:
    """Everything the obsplane knows about one fleet process."""

    __slots__ = ("url", "role", "ever_seen", "last_seen",
                 "unreachable_since", "consecutive_failures",
                 "trace_cursor", "load", "perf", "health", "alerts",
                 "scrape_errors", "traces_read")

    def __init__(self, url: str, role: str):
        self.url = url.rstrip("/")
        self.role = role
        self.ever_seen = False
        self.last_seen: Optional[float] = None
        self.unreachable_since: Optional[float] = None
        self.consecutive_failures = 0
        self.trace_cursor = 0
        self.load: Optional[dict] = None
        self.perf: Optional[dict] = None
        self.health: Optional[dict] = None
        self.alerts: Optional[dict] = None
        self.scrape_errors = 0
        self.traces_read = 0

    @property
    def state(self) -> str:
        if self.unreachable_since is not None:
            return "unreachable"
        return "live" if self.ever_seen else "pending"

    def mark_ok(self, now: float) -> None:
        self.ever_seen = True
        self.last_seen = now
        self.consecutive_failures = 0
        self.unreachable_since = None

    def mark_failed(self, now: float,
                    unreachable_after: int = 2) -> None:
        self.scrape_errors += 1
        self.consecutive_failures += 1
        if self.ever_seen and self.unreachable_since is None \
                and self.consecutive_failures >= unreachable_after:
            self.unreachable_since = now
            logger.warning("fleet process unreachable: %s (%s)",
                           self.url, self.role)

    def to_json(self, include_payloads: bool = True) -> dict:
        out = {
            "url": self.url,
            "role": self.role,
            "state": self.state,
            "ever_seen": self.ever_seen,
            "last_seen": self.last_seen,
            "unreachable_since": self.unreachable_since,
            "consecutive_failures": self.consecutive_failures,
            "scrape_errors": self.scrape_errors,
            "trace_cursor": self.trace_cursor,
            "traces_read": self.traces_read,
        }
        if include_payloads:
            out["load"] = self.load
            out["perf"] = self.perf
            out["health"] = self.health
            out["alerts"] = self.alerts
        return out


class _FleetLoadPoller(LoadPoller):
    """LoadPoller subclass keeping BOTH the parsed EngineLoad and the
    raw /load dict (bundles want the raw report; signal consumers the
    parsed one)."""

    def _build(self, data: dict) -> object:
        from production_stack_tpu.signals import parse_load_report
        return {"raw": data, "parsed": parse_load_report(data)}


class FleetAggregator:
    """See module docstring. ``capture_severities`` filters which
    alert transitions trigger the flight recorder (default: pages
    only — tickets describe the same burn more slowly and would
    double-capture every incident)."""

    def __init__(self, *, routers: Iterable[str],
                 engines: Iterable[str],
                 prefill: Iterable[str] = (),
                 poll_interval_s: float = 1.0,
                 timeout_s: float = 3.0,
                 trace_batch: int = 500,
                 perf_ring_limit: int = 50,
                 unreachable_after: int = 2,
                 attribution_lookback_s: float = 60.0,
                 capture_severities: Tuple[str, ...] = ("page",),
                 capture_on_alerts: bool = True,
                 chain_store: Optional[ChainStore] = None,
                 recorder: Optional[IncidentRecorder] = None,
                 scrape_headers: Optional[dict] = None,
                 engines_config: Optional[str] = None,
                 now_fn=time.time):
        self.processes: Dict[str, ProcessState] = {}
        for url in routers:
            self._add(url, "router")
        for url in engines:
            self._add(url, "engine")
        for url in prefill:
            self._add(url, "prefill")
        # an elastic fleet: re-read the autoscaler's dynamic-config
        # file each poll so scaled-up engines are scraped without an
        # obsplane restart (and retired ones stop counting as
        # unreachable forever)
        self.engines_config = engines_config
        self._engines_config_mtime: Optional[float] = None
        if not self.processes and not engines_config:
            raise ValueError("a fleet needs at least one process "
                             "(--routers / --engines)")
        self.poll_interval_s = poll_interval_s
        self.trace_batch = max(1, trace_batch)
        self.perf_ring_limit = max(1, perf_ring_limit)
        self.unreachable_after = max(1, unreachable_after)
        self.attribution_lookback_s = attribution_lookback_s
        self.capture_severities = tuple(capture_severities)
        self.capture_on_alerts = capture_on_alerts
        self.chains = chain_store or ChainStore()
        self.recorder = recorder
        self._now = now_fn
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        # /debug/* on secured engines requires the engine Bearer
        # (loadgen trace precedent); late import keeps signals the only
        # hard router dependency
        if scrape_headers is None:
            from production_stack_tpu.router.service_discovery import (
                engine_auth_headers)
            scrape_headers = engine_auth_headers()
        self._headers = scrape_headers
        self._load_poller = _FleetLoadPoller(
            lambda: [p.url for p in self.processes.values()
                     if p.role in ("engine", "prefill")],
            timeout_s=timeout_s)
        self._session: Optional[aiohttp.ClientSession] = None
        self._task: Optional[asyncio.Task] = None
        # was any subscribed alert firing at the previous poll? An
        # incident is the FLEET's quiet -> burning transition: the
        # first subscribed alert to fire captures the bundle, and
        # further alerts joining the same burn (the rag page catching
        # up with the chat page) do not re-capture until the fleet has
        # gone quiet again
        self._was_burning = False
        # per-router shed baseline, reset on every quiet poll
        self._shed_baseline: Dict[str, float] = {}
        self._shed_baseline_at: Dict[str, float] = {}
        self.polls_total = 0
        self.captures_triggered = 0
        self.scrape_errors_total: Dict[str, int] = {
            "router": 0, "engine": 0, "prefill": 0}
        self.started_at = now_fn()

    def _add(self, url: str, role: str) -> None:
        state = ProcessState(url, role)
        self.processes[state.url] = state

    def _sync_engines_config(self) -> None:
        """Mirror the autoscaler's dynamic-config ``static_backends``
        into the scraped engine set (mtime-gated, so an unchanged file
        costs one stat per poll). Routers and prefill processes are
        never touched; an unreadable/absent file keeps the last set."""
        if not self.engines_config:
            return
        try:
            mtime = os.stat(self.engines_config).st_mtime
        except OSError:
            return
        if mtime == self._engines_config_mtime:
            return
        try:
            with open(self.engines_config) as f:
                urls = json.load(f).get("static_backends") or []
        except (OSError, ValueError):
            return
        self._engines_config_mtime = mtime
        want = {u.rstrip("/") for u in urls if isinstance(u, str)}
        have = {u for u, p in self.processes.items()
                if p.role == "engine"}
        for url in want - have:
            self._add(url, "engine")
            logger.info("fleet engine joined (dynamic config): %s", url)
        for url in have - want:
            del self.processes[url]
            logger.info("fleet engine retired (dynamic config): %s",
                        url)

    # -- lifecycle -------------------------------------------------------

    async def start(self, poll: bool = True) -> None:
        """``poll=False`` opens the session without the interval task —
        deterministic tests drive every pass through ``poll_once()``."""
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0))
        self._load_poller.attach(self._session)
        if poll:
            self._task = asyncio.create_task(self._loop(),
                                             name="fleet-aggregator")

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._session:
            await self._session.close()
            self._session = None

    def healthy(self) -> bool:
        return self._task is not None and not self._task.done()

    async def _loop(self) -> None:
        while True:
            try:
                await self.poll_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("fleet poll pass failed")
            await asyncio.sleep(self.poll_interval_s)

    # -- scraping --------------------------------------------------------

    async def _get_json(self, url: str, path: str,
                        params: Optional[dict] = None,
                        accept=(200,)) -> Optional[dict]:
        try:
            async with self._session.get(
                    f"{url}{path}", params=params,
                    headers=self._headers,
                    timeout=self._timeout) as r:
                if r.status in accept:
                    return await r.json()
        except (aiohttp.ClientError, ConnectionError, OSError,
                asyncio.TimeoutError, ValueError):
            pass
        return None

    async def _scrape_traces(self, proc: ProcessState) -> bool:
        data = await self._get_json(
            proc.url, "/debug/traces",
            params={"since_seq": str(proc.trace_cursor),
                    "limit": str(self.trace_batch)})
        if data is None:
            return False
        last_seq = int(data.get("last_seq") or 0)
        if last_seq < proc.trace_cursor:
            # the process restarted (fresh recorder, seq counter back
            # near zero): rewind so the next pass re-reads the new
            # ring from its start instead of filtering everything out
            # against a cursor from the previous incarnation forever
            proc.trace_cursor = 0
            return True
        traces = data.get("traces", [])
        if traces:
            self.chains.ingest(proc.url, proc.role, traces)
            proc.traces_read += len(traces)
        # advance to the ring's cursor even past traces the limit
        # dropped: better to lose rows explicitly than re-read forever
        proc.trace_cursor = last_seq
        return True

    async def _scrape_process(self, proc: ProcessState,
                              now: float) -> None:
        ok = False
        if proc.role == "router":
            # routers answer /health with 503 + a body while unhealthy;
            # total silence is what "down" means
            health = await self._get_json(proc.url, "/health",
                                          accept=(200, 503))
            alerts = await self._get_json(proc.url, "/alerts")
            if health is not None:
                proc.health = health
                ok = True
            if alerts is not None:
                proc.alerts = alerts
                ok = True
        else:
            load = self._load_poller.get().get(proc.url)
            if load is not None:
                proc.load = load["raw"]
                ok = True
            perf = await self._get_json(
                proc.url, "/debug/perf",
                params={"limit": str(self.perf_ring_limit)})
            if perf is not None:
                proc.perf = perf
                ok = True
        if await self._scrape_traces(proc):
            ok = True
        if ok:
            proc.mark_ok(now)
        else:
            proc.mark_failed(now, self.unreachable_after)
            self.scrape_errors_total[proc.role] = \
                self.scrape_errors_total.get(proc.role, 0) + 1

    async def poll_once(self) -> None:
        """One full pass: /load fan-out, per-process scrapes, alert
        edge detection, shed baseline upkeep."""
        now = self._now()
        self.polls_total += 1
        self._sync_engines_config()
        await self._load_poller.poll_now()
        await asyncio.gather(*(self._scrape_process(p, now)
                               for p in self.processes.values()))
        self._detect_alert_edges(now)
        self._update_shed_baselines(now)

    # -- alert edges + capture -------------------------------------------

    def _iter_firing(self) -> List[Tuple[ProcessState, dict, str]]:
        """Every currently-firing alert across the routers, with its
        SLO kind resolved from the same payload."""
        out = []
        for proc in self.processes.values():
            if proc.role != "router" or proc.alerts is None:
                continue
            kinds = {s["name"]: s.get("kind", "")
                     for s in proc.alerts.get("slos", [])}
            for row in proc.alerts.get("alerts", []):
                if row.get("state") == "firing":
                    out.append((proc, row, kinds.get(row.get("slo"),
                                                     "")))
        return out

    def _detect_alert_edges(self, now: float) -> None:
        subscribed = [(proc, row, kind)
                      for proc, row, kind in self._iter_firing()
                      if row.get("severity") in self.capture_severities]
        burning = bool(subscribed)
        was_burning, self._was_burning = self._was_burning, burning
        if not self.capture_on_alerts or self.recorder is None:
            return
        if burning and not was_burning:
            # the fleet just went from quiet to burning: ONE bundle,
            # triggered by the first subscribed alert (the recorder
            # cooldown additionally absorbs a flapping edge)
            proc, row, kind = subscribed[0]
            alert = {**row, "router": proc.url, "slo_kind": kind}
            self.captures_triggered += 1
            self.capture(trigger=f"alert:{row.get('name')}",
                         alert=alert)

    def _shed_total(self, proc: ProcessState) -> float:
        total = 0.0
        health = proc.health or {}
        for v in (health.get("sheds") or {}).values():
            total += float(v or 0)
        for tier in ((health.get("qos") or {}).get("tiers") or ()):
            total += float(tier.get("shed_total") or 0)
        return total

    def _update_shed_baselines(self, now: float) -> None:
        """While no subscribed alert is firing, each router's shed
        counter is its own baseline — so a capture's delta is 'sheds
        since the burn began', not 'sheds since boot'."""
        firing = any(row.get("severity") in self.capture_severities
                     for _p, row, _k in self._iter_firing())
        if firing:
            return
        for proc in self.processes.values():
            if proc.role == "router" and proc.health is not None:
                self._shed_baseline[proc.url] = self._shed_total(proc)
                self._shed_baseline_at[proc.url] = now

    def shed_deltas(self) -> Dict[str, float]:
        out = {}
        for proc in self.processes.values():
            if proc.role != "router" or proc.health is None:
                continue
            base = self._shed_baseline.get(proc.url, 0.0)
            out[proc.url] = max(0.0, self._shed_total(proc) - base)
        return out

    def capture(self, *, trigger: str, alert: Optional[dict] = None,
                force: bool = False) -> Optional[dict]:
        """Snapshot the fleet into one incident bundle (None when the
        recorder is absent or the cooldown suppressed it)."""
        if self.recorder is None:
            return None
        proc_json = {url: p.to_json(include_payloads=False)
                     for url, p in self.processes.items()}
        attribution = attribute_incident(
            alert=alert,
            processes=proc_json,
            process_phase_stats=self.chains.process_phase_stats(
                self.attribution_lookback_s),
            shed_deltas=self.shed_deltas())
        return self.recorder.capture(
            trigger=trigger, alert=alert, force=force,
            fleet=self.fleet_snapshot(full=True),
            attribution=attribution)

    # -- reads -----------------------------------------------------------

    def autoscaler_signal(self) -> Dict[str, dict]:
        """Compact per-engine scale signal for the fleet pilot
        (autoscaler/collector.py FleetSignalCollector): the parsed
        /load numbers the raw-polling collector would have derived
        itself, plus reachability state and sample age so the pilot
        can judge freshness without the full payloads."""
        now = self._now()
        out: Dict[str, dict] = {}
        from production_stack_tpu.signals import parse_load_report
        for url, proc in self.processes.items():
            if proc.role not in ("engine", "prefill"):
                continue
            row = {"role": proc.role, "state": proc.state,
                   "age_s": (None if proc.last_seen is None
                             else round(now - proc.last_seen, 3))}
            if proc.load is not None:
                load = parse_load_report(proc.load)
                row.update({
                    "in_flight": load.in_flight,
                    "capacity": load.capacity,
                    "est_queue_delay_ms": load.est_queue_delay_ms,
                })
            out[url] = row
        return out

    def fleet_snapshot(self, full: bool = False,
                       slowest: int = 10) -> dict:
        """The GET /fleet payload (``full`` adds every process's raw
        payloads — the bundle body; the HTTP summary stays compact)."""
        firing = [{"router": p.url, "name": row.get("name"),
                   "slo": row.get("slo"),
                   "severity": row.get("severity")}
                  for p, row, _k in self._iter_firing()]
        return {
            "polls_total": self.polls_total,
            "poll_interval_s": self.poll_interval_s,
            "uptime_s": round(self._now() - self.started_at, 1),
            "processes": {
                url: p.to_json(include_payloads=full)
                for url, p in sorted(self.processes.items())},
            "firing_alerts": firing,
            "autoscaler_signal": self.autoscaler_signal(),
            "shed_deltas": {u: int(d) for u, d
                            in self.shed_deltas().items()},
            "chains": self.chains.stats(),
            "slowest_chains": self.chains.slowest(slowest),
            "fleet_percentiles": self.chains.fleet_percentiles(),
            "incidents": (self.recorder.index()
                          if self.recorder else []),
            "captures_triggered": self.captures_triggered,
            "captures_suppressed": (self.recorder.suppressed_total
                                    if self.recorder else 0),
            "scrape_errors_total": dict(self.scrape_errors_total),
        }
