"""Online cross-process trace stitching: join span rings on trace id.

``loadgen trace`` proved the spans join — offline, once, after the
storm. This module performs the same join *continuously*: every poll
pass hands each process's freshly-scraped ``/debug/traces`` rows (the
``since_seq`` cursor guarantees each row arrives exactly once per
process) to ``ChainStore.ingest``, which groups them by trace id into
fleet-wide chains. A chain is **complete** when both the router view
and at least one engine view are present; prefill-pool views attach as
a third side when the disagg topology runs.

Everything is bounded: chains live in an insertion-ordered dict capped
at ``max_chains`` (oldest evicted), per-(class, phase) fleet latency
series and per-(process, phase) attribution series are fixed-length
deques. All state is touched from the aggregator's event loop only —
no locks.

Two read products:

- ``fleet_percentiles()`` — per-class per-phase p50/p95/p99 across the
  whole fleet, phases qualified by side (``router.backend_ttfb``,
  ``engine.prefill``, ...): the ``GET /fleet/traces`` payload;
- ``process_phase_stats()`` — per-process recent phase latency, the
  evidence ``recorder.attribute_incident`` ranks to name a guilty
  process and phase.
"""

import collections
import time
from typing import Dict, List, Optional, Tuple

# sides a scraped process can contribute to a chain, in the order the
# request traverses them
ROLES = ("router", "prefill", "engine")


def percentile(values: List[float], p: float) -> float:
    """Nearest-rank-with-interpolation percentile (mirrors
    loadgen.report.percentile; duplicated so the obsplane stays
    importable without the loadgen package)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    if len(ordered) == 1:
        return ordered[0]
    rank = (p / 100.0) * (len(ordered) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(ordered) - 1)
    frac = rank - lo
    return ordered[lo] * (1.0 - frac) + ordered[hi] * frac


class ChainStore:
    """Bounded store of stitched cross-process trace chains."""

    def __init__(self, max_chains: int = 4096,
                 samples_per_series: int = 512,
                 now_fn=time.time):
        self.max_chains = max(16, max_chains)
        self.samples_per_series = max(16, samples_per_series)
        self._now = now_fn
        # trace_id -> chain dict (insertion-ordered for eviction)
        self._chains: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        # (class, qualified phase) -> deque[duration_ms] — fed once,
        # when a chain first becomes complete
        self._fleet_series: Dict[Tuple[str, str],
                                 "collections.deque"] = {}
        # (process url, phase) -> deque[(wall_ts, duration_ms)] — fed
        # per ingested trace (attribution evidence needs no router join)
        self._proc_series: Dict[Tuple[str, str],
                                "collections.deque"] = {}
        self.traces_ingested = 0
        self.chains_created = 0
        self.chains_complete = 0
        self.chains_evicted = 0

    # -- writes (aggregator poll loop) ----------------------------------

    def ingest(self, url: str, role: str, traces: List[dict]) -> int:
        """Fold one process's freshly-scraped trace rows in. ``role``
        is the scraper's knowledge of what the process is ("router" /
        "engine" / "prefill"); returns how many rows were new."""
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; options: {ROLES}")
        new = 0
        for trace in traces:
            tid = trace.get("trace_id")
            if not tid:
                continue
            chain = self._chains.get(tid)
            if chain is None:
                chain = self._chains[tid] = {
                    "trace_id": tid,
                    "class": "",
                    "started_at": None,
                    "duration_ms": None,
                    "status": None,
                    "router": None,
                    "router_url": None,
                    "engines": {},
                    "prefill": {},
                    "complete": False,
                }
                self.chains_created += 1
                self._evict()
            if role == "router":
                if chain["router"] is not None:
                    continue        # duplicate scrape row
                chain["router"] = trace
                chain["router_url"] = url
                chain["class"] = str(
                    (trace.get("attrs") or {}).get("class") or "other")
                chain["started_at"] = trace.get("started_at")
                chain["duration_ms"] = trace.get("duration_ms")
                chain["status"] = trace.get("status")
            else:
                side = chain[role + "s" if role == "engine"
                             else role]
                if url in side:
                    continue
                side[url] = trace
            new += 1
            self.traces_ingested += 1
            self._feed_process_series(url, trace)
            if not chain["complete"] and chain["router"] is not None \
                    and chain["engines"]:
                chain["complete"] = True
                self.chains_complete += 1
                self._feed_fleet_series(chain)
        return new

    def _evict(self) -> None:
        while len(self._chains) > self.max_chains:
            self._chains.popitem(last=False)
            self.chains_evicted += 1

    def _feed_process_series(self, url: str, trace: dict) -> None:
        at = trace.get("started_at") or self._now()
        for span in trace.get("spans", ()):
            if span.get("kind") != "phase":
                continue
            key = (url, span["name"])
            series = self._proc_series.get(key)
            if series is None:
                series = self._proc_series[key] = collections.deque(
                    maxlen=self.samples_per_series)
            series.append((at, span.get("duration_ms") or 0.0))
        # a trace's unattributed time is evidence too (a stall no
        # phase covers — e.g. a compile on an engine without the
        # xla_compile hook — must still be rankable)
        una = trace.get("unattributed_ms")
        if una is not None:
            key = (url, "unattributed")
            series = self._proc_series.get(key)
            if series is None:
                series = self._proc_series[key] = collections.deque(
                    maxlen=self.samples_per_series)
            series.append((at, una))

    def _feed_fleet_series(self, chain: dict) -> None:
        cls = chain["class"] or "other"

        def feed(qualified: str, dur_ms: float) -> None:
            key = (cls, qualified)
            series = self._fleet_series.get(key)
            if series is None:
                series = self._fleet_series[key] = collections.deque(
                    maxlen=self.samples_per_series)
            series.append(dur_ms)

        for side, traces in (("router", [chain["router"]]),
                             ("prefill", chain["prefill"].values()),
                             ("engine", chain["engines"].values())):
            for trace in traces:
                sums: Dict[str, float] = {}
                for span in trace.get("spans", ()):
                    if span.get("kind") == "phase":
                        sums[span["name"]] = sums.get(span["name"], 0.0) \
                            + (span.get("duration_ms") or 0.0)
                for name, ms in sums.items():
                    feed(f"{side}.{name}", ms)
        feed("total", chain["duration_ms"] or 0.0)

    # -- reads ----------------------------------------------------------

    def fleet_percentiles(self) -> Dict[str, Dict[str, dict]]:
        """{class: {qualified phase: {p50, p95, p99, n}}} in ms."""
        out: Dict[str, Dict[str, dict]] = {}
        for (cls, phase), series in sorted(self._fleet_series.items()):
            vals = list(series)
            out.setdefault(cls, {})[phase] = {
                "p50_ms": round(percentile(vals, 50), 2),
                "p95_ms": round(percentile(vals, 95), 2),
                "p99_ms": round(percentile(vals, 99), 2),
                "n": len(vals),
            }
        return out

    def process_phase_stats(self, lookback_s: Optional[float] = None
                            ) -> Dict[str, Dict[str, dict]]:
        """{process url: {phase: {p50_ms, p95_ms, n}}}, optionally
        restricted to samples stamped within the trailing
        ``lookback_s`` — the attribution evidence."""
        cutoff = None if lookback_s is None else self._now() - lookback_s
        out: Dict[str, Dict[str, dict]] = {}
        for (url, phase), series in self._proc_series.items():
            vals = [d for at, d in series
                    if cutoff is None or at >= cutoff]
            if not vals:
                continue
            out.setdefault(url, {})[phase] = {
                "p50_ms": round(percentile(vals, 50), 2),
                "p95_ms": round(percentile(vals, 95), 2),
                "n": len(vals),
            }
        return out

    def slowest(self, n: int = 10,
                cls: Optional[str] = None) -> List[dict]:
        """The current slowest COMPLETE chains, rendered compactly:
        per-side phase sums instead of raw span lists (an operator
        triaging an incident reads totals first, spans later)."""
        chains = [c for c in self._chains.values()
                  if c["complete"] and (cls is None
                                        or c["class"] == cls)]
        chains.sort(key=lambda c: c["duration_ms"] or 0.0, reverse=True)
        return [self.render_chain(c) for c in chains[:max(1, n)]]

    @staticmethod
    def render_chain(chain: dict) -> dict:
        def phase_sums(trace: dict) -> Dict[str, float]:
            sums: Dict[str, float] = {}
            for span in trace.get("spans", ()):
                if span.get("kind") == "phase":
                    sums[span["name"]] = round(
                        sums.get(span["name"], 0.0)
                        + (span.get("duration_ms") or 0.0), 3)
            return sums

        return {
            "trace_id": chain["trace_id"],
            "class": chain["class"],
            "status": chain["status"],
            "started_at": chain["started_at"],
            "duration_ms": chain["duration_ms"],
            "unattributed_ms": (chain["router"] or {}).get(
                "unattributed_ms"),
            "router": {"url": chain["router_url"],
                       "phases_ms": phase_sums(chain["router"] or {})},
            "prefill": {url: phase_sums(t)
                        for url, t in chain["prefill"].items()},
            "engines": {url: phase_sums(t)
                        for url, t in chain["engines"].items()},
        }

    def stats(self) -> dict:
        return {
            "traces_ingested": self.traces_ingested,
            "chains_created": self.chains_created,
            "chains_complete": self.chains_complete,
            "chains_evicted": self.chains_evicted,
            "chains_held": len(self._chains),
            "complete_fraction": round(
                self.chains_complete / self.chains_created, 4)
            if self.chains_created else 0.0,
        }
