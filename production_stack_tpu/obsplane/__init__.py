"""Fleet observability aggregator: online trace stitching + flight
recorder.

Every per-process signal this stack computes — r13 trace rings, r14
burn-rate alerts, r15/r17 efficiency rings, r16 peer/QoS state — lives
behind ONE process's ``/debug/*`` / ``/alerts`` / ``/load`` endpoint,
and the only cross-process join is done offline inside ``loadgen
trace`` after the fact. With an N-router/N-engine fleet, diagnosing an
incident means hand-scraping 2R+N+1 endpoints after the evidence has
rotated out of the bounded rings.

The obsplane is the standalone process that closes that gap:

- ``aggregator.FleetAggregator`` incrementally scrapes every router's
  and engine's ``/debug/traces`` (the ``since_seq`` cursor), ``/load``
  (via the shared ``signals.LoadPoller``), ``/debug/perf``,
  ``/alerts``, and ``/health`` on one poll loop;
- ``stitch.ChainStore`` joins router, prefill, and engine spans on
  trace id ONLINE into bounded fleet-wide chains, exposing per-class
  per-phase fleet percentiles and the current slowest chains at
  ``GET /fleet/traces``;
- ``recorder.IncidentRecorder`` is the flight recorder: when a
  subscribed SLO alert transitions to firing (or an operator POSTs
  ``/fleet/capture``), it snapshots the correlated state of every
  fleet process into a self-contained on-disk incident bundle
  (bounded retention) with a machine-written attribution summary
  naming the guilty process and phase.

CLI: ``python -m production_stack_tpu.obsplane --routers ...
--engines ...``. Operator surface: docs/observability.md "Fleet
observability"; closed loop: ``python -m production_stack_tpu.loadgen
incident`` (INCIDENT_r18.json).
"""

from production_stack_tpu.obsplane.aggregator import (FleetAggregator,
                                                      ProcessState)
from production_stack_tpu.obsplane.recorder import (IncidentRecorder,
                                                    attribute_incident)
from production_stack_tpu.obsplane.stitch import ChainStore

__all__ = ["FleetAggregator", "ProcessState", "IncidentRecorder",
           "attribute_incident", "ChainStore"]
