from production_stack_tpu.obsplane.app import main

if __name__ == "__main__":
    main()
